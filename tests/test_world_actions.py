"""World-facing actions: fetch_web, call_api, call_mcp, answer_engine,
generate_images — through live agents with fake transports, plus a REAL
stdio MCP server subprocess (the reference tests these with req_cassette
record/replay and Hammox transport mocks; our seam is the injectable
HttpFn / MCPManager)."""

import asyncio
import json
import os
import sys
import time

import pytest

from quoracle_tpu.agent import AgentConfig, AgentDeps, AgentSupervisor
from quoracle_tpu.context.history import RESULT
from quoracle_tpu.infra.http import FakeHttp, HttpResponse, check_ssrf, SSRFError
from quoracle_tpu.infra.mcp import MCPManager
from quoracle_tpu.models.images import ProceduralImageBackend
from quoracle_tpu.models.runtime import MockBackend
from quoracle_tpu.utils.html_md import html_to_markdown

POOL = MockBackend.DEFAULT_POOL


def j(action, params=None, wait=False):
    return json.dumps({"action": action, "params": params or {},
                       "reasoning": "t", "wait": wait})


def scripted(*entries):
    return MockBackend(scripts={m: list(entries) for m in POOL},
                       respond=lambda r: j("wait", {}))


async def until(cond, timeout=15.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        await asyncio.sleep(0.01)
    raise AssertionError("condition not met")


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def first_result(core):
    return next(e for e in core.ctx.history(POOL[0]) if e.kind == RESULT)


async def run_one_action(backend, **deps_over):
    deps_over.setdefault("ssrf_check", False)
    deps = AgentDeps.for_tests(backend, **deps_over)
    sup = AgentSupervisor(deps)
    core = await sup.start_agent(AgentConfig(
        agent_id="agent-w", task_id="t1", model_pool=list(POOL)))
    core.post({"type": "user_message", "content": "go", "from": "user"})
    await until(lambda: any(e.kind == RESULT
                            for e in core.ctx.history(POOL[0])))
    result = first_result(core)
    await sup.terminate_agent("agent-w")
    return core, result.as_text()


# ---------------------------------------------------------------------------
# html → markdown
# ---------------------------------------------------------------------------

def test_html_to_markdown():
    html = """<html><head><title>x</title><script>evil()</script></head>
    <body><h1>Title</h1><p>Hello <b>world</b>, see
    <a href="https://x.example/doc">the doc</a>.</p>
    <ul><li>alpha</li><li>beta</li></ul>
    <pre><code>x = 1</code></pre></body></html>"""
    md = html_to_markdown(html)
    assert "# Title" in md
    assert "**world**" in md
    assert "[the doc](https://x.example/doc)" in md
    assert "- alpha" in md and "- beta" in md
    assert "x = 1" in md
    assert "evil" not in md


def test_ssrf_check_blocks_private():
    with pytest.raises(SSRFError):
        check_ssrf("http://127.0.0.1/admin")
    with pytest.raises(SSRFError):
        check_ssrf("ftp://example.com/x")


# ---------------------------------------------------------------------------
# fetch_web / call_api through a live agent
# ---------------------------------------------------------------------------

def test_fetch_web_converts_html_and_fences_output():
    async def main():
        http = FakeHttp({"https://site.example": (
            200, "text/html",
            "<h1>Doc</h1><p>body text <script>ignore()</script></p>")})
        backend = scripted(
            j("fetch_web", {"url": "https://site.example/page"}),
            j("wait", {}))
        core, text = await run_one_action(backend, http=http)
        assert "# Doc" in text and "body text" in text
        assert "ignore()" not in text
        assert "NO_EXECUTE" in text            # untrusted output is fenced
        assert http.requests[0]["url"] == "https://site.example/page"
    run(main())


def test_fetch_web_image_returns_base64():
    async def main():
        http = FakeHttp({"https://img.example": (
            200, "image/png", b"\x89PNG fakebytes")})
        backend = scripted(
            j("fetch_web", {"url": "https://img.example/x.png"}),
            j("wait", {}))
        core, text = await run_one_action(backend, http=http)
        assert "image_base64" in text
        assert "image/png" in text
    run(main())


def test_call_api_jsonrpc_and_graphql():
    async def main():
        def rpc(url, method, headers, body):
            req = json.loads(body)
            assert req["jsonrpc"] == "2.0"
            return (200, "application/json",
                    json.dumps({"jsonrpc": "2.0", "id": req["id"],
                                "result": {"sum": 42}}))
        def gql(url, method, headers, body):
            req = json.loads(body)
            assert "query" in req
            return (200, "application/json",
                    json.dumps({"data": {"user": {"name": "ada"}}}))
        http = FakeHttp({"https://rpc.example": rpc,
                         "https://gql.example": gql})
        backend = scripted(
            j("call_api", {"url": "https://rpc.example", "method": "POST",
                           "protocol": "jsonrpc",
                           "body": {"method": "add", "params": [40, 2]}}),
            j("call_api", {"url": "https://gql.example", "method": "POST",
                           "protocol": "graphql",
                           "body": {"query": "{user{name}}"},
                           "auth": {"type": "bearer", "token": "tkn"}}),
            j("wait", {}))
        deps = AgentDeps.for_tests(backend, http=http, ssrf_check=False)
        sup = AgentSupervisor(deps)
        core = await sup.start_agent(AgentConfig(
            agent_id="agent-w", task_id="t1", model_pool=list(POOL)))
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: len([e for e in core.ctx.history(POOL[0])
                                 if e.kind == RESULT]) >= 2)
        texts = [e.as_text() for e in core.ctx.history(POOL[0])
                 if e.kind == RESULT]
        assert any('"sum": 42' in t for t in texts)
        assert any('"name": "ada"' in t for t in texts)
        # bearer auth header was built
        assert any(r["headers"].get("Authorization") == "Bearer tkn"
                   for r in http.requests)
        await sup.terminate_agent("agent-w")
    run(main())


def test_call_api_http_error_status():
    async def main():
        http = FakeHttp({"https://api.example": (500, "text/plain", "boom")})
        backend = scripted(
            j("call_api", {"url": "https://api.example", "method": "GET"}),
            j("wait", {}))
        core, text = await run_one_action(backend, http=http)
        assert '"status": "error"' in text and "HTTP 500" in text
    run(main())


# ---------------------------------------------------------------------------
# call_mcp against a REAL stdio MCP server subprocess
# ---------------------------------------------------------------------------

MCP_SERVER = r'''
import json, sys
tools = [{"name": "adder", "description": "adds a and b",
          "inputSchema": {"type": "object"}}]
for line in sys.stdin:
    msg = json.loads(line)
    mid = msg.get("id")
    method = msg.get("method")
    if mid is None:
        continue  # notification
    if method == "initialize":
        result = {"protocolVersion": msg["params"]["protocolVersion"],
                  "capabilities": {"tools": {}},
                  "serverInfo": {"name": "testsrv", "version": "0"}}
    elif method == "tools/list":
        result = {"tools": tools}
    elif method == "tools/call":
        args = msg["params"]["arguments"]
        result = {"content": [{"type": "text",
                               "text": str(args["a"] + args["b"])}]}
    else:
        result = {}
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "id": mid,
                                 "result": result}) + "\n")
    sys.stdout.flush()
'''


def test_call_mcp_stdio_end_to_end(tmp_path):
    async def main():
        server_py = tmp_path / "mcp_server.py"
        server_py.write_text(MCP_SERVER)
        mcp = MCPManager({"calc": {"transport": "stdio",
                                   "command": [sys.executable,
                                               str(server_py)]}})
        tools = await mcp.list_tools("calc")
        assert tools[0]["name"] == "adder"
        backend = scripted(
            j("call_mcp", {"server": "calc", "tool": "adder",
                           "arguments": {"a": 19, "b": 23}}),
            j("wait", {}))
        core, text = await run_one_action(backend, mcp=mcp)
        assert '"content": "42"' in text
        assert "NO_EXECUTE" in text
        # unknown server surfaces as an action error
        backend2 = scripted(
            j("call_mcp", {"server": "nope", "tool": "x"}), j("wait", {}))
        core2, text2 = await run_one_action(backend2, mcp=mcp)
        assert "unknown MCP server" in text2
        await mcp.close()
    run(main())


# ---------------------------------------------------------------------------
# answer_engine / generate_images
# ---------------------------------------------------------------------------

def test_answer_engine_uses_designated_model():
    async def main():
        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if "Answer the question" in joined:     # the grounding query
                return "The answer is 4."
            if '"answer"' in joined:                # result seen: idle
                return j("wait", {})
            return j("answer_engine", {"query": "what is 2+2?"})
        backend = MockBackend(respond=respond)
        core, text = await run_one_action(backend)
        assert "The answer is 4." in text
        assert "NO_EXECUTE" in text            # grounded answers are fenced
    run(main())


def test_answer_engine_multi_source_grounding():
    """Search template → top-k result links → per-source fetch + extract →
    numbered citations in the grounding prompt and per-source metadata in
    the result (reference answer_engine.ex:1-52 source extraction)."""
    async def main():
        search_html = (
            '<div class="r"><a href="https://a.example/page">Alpha '
            'doc</a></div>'
            '<a href="/internal">nav</a>'                 # same-host: drop
            '<a href="https://b.example/post">Beta <b>post</b></a>'
            '<a href="https://a.example/page">Alpha doc</a>'  # dupe: drop
            '<a href="https://c.example/x">Gamma</a>')
        http = FakeHttp({
            "https://search.example/?q=why%20is%20the%20sky%20blue":
                (200, "text/html", search_html),
            "https://a.example/page":
                (200, "text/html", "<p>Rayleigh scattering explains "
                                   "it.</p>"),
            "https://b.example/post":
                (200, "text/html", "<p>Blue light scatters more.</p>"),
            # c.example missing → that fetch fails, source marked
            # fetched=false, answer still assembles from the other two
        })
        seen_prompts = []

        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if "Answer the question" in joined:
                seen_prompts.append(joined)
                return "Rayleigh scattering [1][2]."
            if '"answer"' in joined:
                return j("wait", {})
            return j("answer_engine", {"query": "why is the sky blue"})

        from quoracle_tpu.persistence.db import Database
        from quoracle_tpu.persistence.store import Persistence
        store = Persistence(Database(":memory:"))
        store.set_setting("answer_engine_search_url",
                          "https://search.example/?q={query}")
        backend = MockBackend(respond=respond)
        core, text = await run_one_action(backend, http=http,
                                          persistence=store)
        assert "Rayleigh scattering [1][2]." in text
        # per-source citation metadata in the action result (the history
        # entry is NO_EXECUTE-fenced — parse the JSON inside the fence)
        fenced = first_result(core).content
        result = json.loads(
            fenced.split("\n", 2)[2].rsplit("</NO_EXECUTE>", 1)[0])["result"]
        srcs = {s["url"]: s for s in result["sources"]}
        assert srcs["https://a.example/page"]["fetched"] is True
        assert srcs["https://a.example/page"]["title"] == "Alpha doc"
        assert srcs["https://b.example/post"]["fetched"] is True
        assert srcs["https://b.example/post"]["title"] == "Beta post"
        assert srcs["https://c.example/x"]["fetched"] is False
        assert [s["index"] for s in result["sources"]] == [1, 2, 3]
        # the model saw numbered source sections with both extracts
        grounding = seen_prompts[0]
        assert "[1] Alpha doc (https://a.example/page)" in grounding
        assert "Rayleigh scattering explains" in grounding
        assert "[2] Beta post (https://b.example/post)" in grounding
        assert "cite" in grounding or "[n]" in grounding
    run(main())


def test_answer_engine_ssrf_guards_content_derived_links():
    """Result links come from page CONTENT (untrusted): with the SSRF
    guard on, a link-local metadata URL in the search results must not be
    fetched, while public sources still ground the answer."""
    async def main():
        search_html = (
            '<a href="http://169.254.169.254/latest/meta-data/">evil</a>'
            '<a href="http://8.8.8.8/page">Fine page</a>')
        http = FakeHttp({
            "https://search.example/?q=q": (200, "text/html", search_html),
            "http://169.254.169.254": (200, "text/plain", "SECRET-CREDS"),
            "http://8.8.8.8/page": (200, "text/html", "<p>useful</p>"),
        })

        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if "Answer the question" in joined:
                assert "SECRET-CREDS" not in joined
                return "grounded answer"
            if '"answer"' in joined:
                return j("wait", {})
            return j("answer_engine", {"query": "q"})

        from quoracle_tpu.persistence.db import Database
        from quoracle_tpu.persistence.store import Persistence
        store = Persistence(Database(":memory:"))
        store.set_setting("answer_engine_search_url",
                          "https://search.example/?q={query}")
        backend = MockBackend(respond=respond)
        core, text = await run_one_action(backend, http=http,
                                          persistence=store,
                                          ssrf_check=True)
        fenced = first_result(core).content
        result = json.loads(
            fenced.split("\n", 2)[2].rsplit("</NO_EXECUTE>", 1)[0])["result"]
        srcs = {s["url"]: s for s in result["sources"]}
        assert srcs["http://169.254.169.254/latest/meta-data/"][
            "fetched"] is False
        assert srcs["http://8.8.8.8/page"]["fetched"] is True
        # the blocked fetch never went out on the wire
        assert not any("169.254" in r["url"] for r in http.requests)
    run(main())


def test_generate_images_procedural(tmp_path):
    async def main():
        backend = scripted(
            j("generate_images", {"prompt": "a red square", "count": 2,
                                  "size": "32x32"}),
            j("wait", {}))
        deps = AgentDeps.for_tests(backend, images=ProceduralImageBackend())
        sup = AgentSupervisor(deps)
        core = await sup.start_agent(AgentConfig(
            agent_id="agent-w", task_id="t1", model_pool=list(POOL),
            working_dir=str(tmp_path)))
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: any(e.kind == RESULT
                                for e in core.ctx.history(POOL[0])))
        result = first_result(core).content["result"]
        assert result["status"] == "ok"
        assert len(result["images"]) == 2
        for img in result["images"]:
            assert os.path.isfile(img["path"])
            with open(img["path"], "rb") as f:
                assert f.read(8) == b"\x89PNG\r\n\x1a\n"
        await sup.terminate_agent("agent-w")
    run(main())


def test_zero_egress_mode_fails_cleanly():
    async def main():
        backend = scripted(
            j("fetch_web", {"url": "https://x.example"}), j("wait", {}))
        core, text = await run_one_action(backend, http=None)
        assert "zero-egress" in text
    run(main())


# ---------------------------------------------------------------------------
# MCP hardening (VERDICT r4 item 7): death mid-call w/ stderr context,
# reconnect after death, tool-list cache, agent-dismiss teardown
# ---------------------------------------------------------------------------

MCP_DYING_SERVER = r'''
import json, os, sys
marker = sys.argv[1]          # dies on the first-ever call, then recovers
for line in sys.stdin:
    msg = json.loads(line)
    mid = msg.get("id")
    method = msg.get("method")
    if mid is None:
        continue
    if method == "initialize":
        result = {"protocolVersion": msg["params"]["protocolVersion"],
                  "capabilities": {"tools": {}},
                  "serverInfo": {"name": "dying", "version": "0"}}
    elif method == "tools/list":
        result = {"tools": [{"name": "boom", "inputSchema": {}}]}
    elif method == "tools/call":
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.stderr.write("FATAL: tool exploded spectacularly\n")
            sys.stderr.flush()
            sys.exit(3)              # die MID-CALL, stderr explains why
        result = {"content": [{"type": "text", "text": "recovered"}]}
    else:
        result = {}
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "id": mid,
                                 "result": result}) + "\n")
    sys.stdout.flush()
'''


def test_mcp_server_death_mid_call_surfaces_stderr_and_reconnects(tmp_path):
    """A stdio server dying mid-call must (a) fail THAT call with the
    server's stderr tail in the error — not a bare 'closed the stream' —
    and (b) not poison the target: the next call reconnects fresh."""
    from quoracle_tpu.infra.mcp import MCPError

    async def main():
        server_py = tmp_path / "dying_server.py"
        server_py.write_text(MCP_DYING_SERVER)
        mcp = MCPManager({"dying": {"transport": "stdio",
                                    "command": [sys.executable,
                                                str(server_py),
                                                str(tmp_path / "died")]}})
        tools = await mcp.list_tools("dying", agent_id="agent-x")
        assert tools[0]["name"] == "boom"
        try:
            await mcp.call_tool("dying", "boom", {}, agent_id="agent-x")
            raise AssertionError("expected the call to fail")
        except MCPError as e:
            assert "exploded spectacularly" in str(e)   # stderr captured
            assert "exit code 3" in str(e)
        # error_context stays queryable for agent logs
        assert "exploded" in mcp.error_context("dying")
        # next call transparently reconnects (fresh process) and succeeds
        result = await mcp.call_tool("dying", "boom", {},
                                     agent_id="agent-x")
        assert result["content"][0]["text"] == "recovered"
        await mcp.close()
    run(main())


def test_mcp_tool_list_cached_per_connection(tmp_path):
    """tools/list hits the wire once per connection (reference
    mcp/client.ex:1-15 caching) — a counting server proves it."""
    server_py = tmp_path / "counting_server.py"
    server_py.write_text(MCP_SERVER.replace(
        '"tools": tools}',
        '"tools": tools, "_hits": globals().setdefault("h", 0)}')
        .replace('elif method == "tools/list":',
                 'elif method == "tools/list":\n'
                 '        globals()["h"] = globals().get("h", 0) + 1'))

    async def main():
        mcp = MCPManager({"calc": {"transport": "stdio",
                                   "command": [sys.executable,
                                               str(server_py)]}})
        t1 = await mcp.list_tools("calc")
        t2 = await mcp.list_tools("calc")
        assert t1 is t2                       # served from the cache
        await mcp.close()
    run(main())


def test_mcp_connections_close_on_agent_release(tmp_path):
    """Dismissing the only agent using a connection closes it (reference:
    per-agent clients die with their agent); a connection shared with a
    live agent survives."""
    async def main():
        server_py = tmp_path / "mcp_server.py"
        server_py.write_text(MCP_SERVER)
        mcp = MCPManager({"calc": {"transport": "stdio",
                                   "command": [sys.executable,
                                               str(server_py)]}})
        await mcp.list_tools("calc", agent_id="a1")
        await mcp.list_tools("calc", agent_id="a2")
        conn = mcp._connections[
            mcp.configs["calc"].dedup_key()]
        await mcp.release_agent("a1")
        assert conn.alive                      # a2 still uses it
        await mcp.release_agent("a2")
        for _ in range(100):
            if not conn.alive:
                break
            await asyncio.sleep(0.02)
        assert not conn.alive                  # last user gone → closed
        assert not mcp._connections
        await mcp.close()
    run(main())


# ---------------------------------------------------------------------------
# Credential store wiring (VERDICT r4 item 8): call_api + MCP auth through
# the encrypted, audited credential table
# ---------------------------------------------------------------------------

def _cred_store():
    from quoracle_tpu.persistence.db import Database
    from quoracle_tpu.persistence.store import CredentialStore
    db = Database(":memory:", encryption_key="unit-test-key")
    return CredentialStore(db), db


def test_credential_store_roundtrip_encrypted_and_audited():
    store, db = _cred_store()
    store.put("gh", {"type": "bearer", "token": "tok-123"},
              model_spec="api:github")
    # at rest: encrypted blob, plaintext token nowhere in the row
    row = db.query_one("SELECT * FROM credentials WHERE id='gh'")
    assert row["encrypted"] == 1
    assert b"tok-123" not in bytes(row["data"])
    # fetch decrypts + audits (same trail as secret access)
    data = store.get("gh", agent_id="agent-z", action="call_api")
    assert data["token"] == "tok-123"
    audit = db.query("SELECT * FROM secret_usage")
    assert audit and audit[-1]["secret_name"] == "credential:gh"
    assert audit[-1]["agent_id"] == "agent-z"
    # list() exposes metadata only
    meta = store.list()
    assert meta == [{"id": "gh", "model_spec": "api:github",
                     "encrypted": True}]
    assert store.for_model("api:github")["token"] == "tok-123"
    assert store.delete("gh") and store.get("gh") is None


def test_call_api_credential_auth_resolves_from_store():
    """auth {type: credential, id} pulls the encrypted record — the token
    never has to pass through the model's context."""
    from quoracle_tpu.infra.http import FakeHttp

    async def main():
        store, _db = _cred_store()
        store.put("svc", {"type": "header", "name": "X-Api-Key",
                          "value": "sk-55"})
        http = FakeHttp({"https://api.example": (
            200, "application/json", '{"ok": true}')})
        backend = scripted(
            j("call_api", {"url": "https://api.example/v1", "method": "GET",
                           "auth": {"type": "credential", "id": "svc"}}),
            j("wait", {}))
        core, text = await run_one_action(backend, http=http,
                                          credentials=store)
        assert '"ok": true' in text
        assert http.requests[0]["headers"]["X-Api-Key"] == "sk-55"
        # unknown credential id is a loud action error
        backend2 = scripted(
            j("call_api", {"url": "https://api.example/v1", "method": "GET",
                           "auth": {"type": "credential", "id": "nope"}}),
            j("wait", {}))
        _, text2 = await run_one_action(backend2, http=http,
                                        credentials=store)
        assert "unknown credential" in text2
    run(main())


def test_mcp_http_server_uses_stored_credential():
    """An MCP server config naming a credential connects with the resolved
    auth header (resolved at CONNECT, so rotation applies on reconnect)."""
    from quoracle_tpu.infra.http import FakeHttp, HttpResponse

    async def main():
        store, _db = _cred_store()
        store.put("mcp-auth", {"type": "bearer", "token": "mcp-tok"})

        def rpc(url, method, headers, body):
            msg = json.loads(body)
            result = ({"protocolVersion": "x", "capabilities": {}}
                      if msg["method"] == "initialize"
                      else {"tools": [{"name": "t"}]})
            return HttpResponse(200, {"content-type": "application/json"},
                                json.dumps({"jsonrpc": "2.0",
                                            "id": msg["id"],
                                            "result": result}).encode(),
                                url)
        http = FakeHttp({"https://mcp.example": rpc})
        mcp = MCPManager(
            {"svc": {"transport": "http", "url": "https://mcp.example",
                     "credential": "mcp-auth"}},
            http_fn=http,
            credential_resolver=lambda cid: store.get(cid, agent_id="mcp",
                                                      action="mcp_connect"))
        tools = await mcp.list_tools("svc")
        assert tools == [{"name": "t"}]
        assert all(r["headers"].get("Authorization") == "Bearer mcp-tok"
                   for r in http.requests)
        await mcp.close()
    run(main())
