"""On-device diffusion image generation (models/diffusion.py): the
TPU-native replacement for the reference's hosted image models behind the
generate_images action (reference models/image_query.ex:1-12)."""

import numpy as np
import pytest

import jax

from quoracle_tpu.models.diffusion import (
    DiffusionConfig, DiffusionImageBackend, ddim_sample,
    init_diffusion_params,
)

TINY = DiffusionConfig(image_size=16, base_ch=8, ch_mult=(1, 2),
                       emb_ch=16, groups=4, sample_steps=4)


@pytest.fixture(scope="module")
def backend(tmp_path_factory):
    return DiffusionImageBackend(cfg=TINY, seed=0)


def test_sampler_shapes_and_determinism(backend):
    ids = np.zeros((2, 8), np.int32)
    ids[0, :3] = [10, 20, 30]
    ids[1, :3] = [11, 21, 31]
    a = ddim_sample(backend.params, TINY, ids, jax.random.PRNGKey(1))
    b = ddim_sample(backend.params, TINY, ids, jax.random.PRNGKey(1))
    assert a.shape == (2, 16, 16, 3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(a.min()) >= 0.0 and float(a.max()) <= 1.0
    # rows see different noise and different prompts
    assert np.abs(np.asarray(a[0]) - np.asarray(a[1])).max() > 1e-4


def test_backend_writes_pngs_at_requested_size(backend, tmp_path):
    imgs = backend.generate("a red square", count=2, size="32x24",
                            out_dir=str(tmp_path))
    assert len(imgs) == 2
    for im in imgs:
        assert im.width == 32 and im.height == 24
        data = open(im.path, "rb").read()
        assert data[:8] == b"\x89PNG\r\n\x1a\n"
    # same prompt → same pixels (deterministic, like the procedural
    # backend); different prompt → different pixels
    again = backend.generate("a red square", count=1, size="32x24",
                             out_dir=str(tmp_path))
    other = backend.generate("a blue circle", count=1, size="32x24",
                             out_dir=str(tmp_path))
    assert open(again[0].path, "rb").read()[33:] == \
        open(imgs[0].path, "rb").read()[33:]
    assert open(other[0].path, "rb").read() != \
        open(again[0].path, "rb").read()


def test_runtime_composes_diffusion_backend():
    from quoracle_tpu.models.diffusion import DiffusionImageBackend as DIB
    from quoracle_tpu.runtime import Runtime, RuntimeConfig
    rt = Runtime(RuntimeConfig(image_backend="diffusion"))
    try:
        assert isinstance(rt.deps.images, DIB)
    finally:
        rt.close()


def test_generate_images_action_over_diffusion(tmp_path):
    """The generate_images action serves from the diffusion backend through
    the same seam the procedural backend uses (live agent, scripted
    consensus — mirrors test_world_actions.py's procedural drive)."""
    import os

    from tests.test_world_actions import (
        POOL, RESULT, first_result, j, run, scripted, until,
    )
    from quoracle_tpu.agent import AgentConfig, AgentDeps, AgentSupervisor

    async def main():
        backend = scripted(
            j("generate_images", {"prompt": "sunrise over water",
                                  "count": 1, "size": "16x16"}),
            j("wait", {}))
        deps = AgentDeps.for_tests(
            backend, images=DiffusionImageBackend(cfg=TINY))
        sup = AgentSupervisor(deps)
        core = await sup.start_agent(AgentConfig(
            agent_id="agent-dimg", task_id="t-dimg",
            model_pool=list(POOL), working_dir=str(tmp_path)))
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: any(e.kind == RESULT
                                for e in core.ctx.history(POOL[0])))
        result = first_result(core).content["result"]
        assert result["status"] == "ok"
        img = result["images"][0]
        assert img["width"] == 16 and img["model"] == "xla:diffusion-v0"
        assert os.path.isfile(img["path"])

    run(main())
