"""Checkpoint serving end-to-end: generated HF checkpoint → loader →
HFAutoTokenizer → TPUBackend → (sessions + constrained decoding) and the
Runtime composition root building that whole chain from RuntimeConfig.

This is the system the bench measures (VERDICT r2 item 2): no component is
stubbed — real safetensors weights, the checkpoint's own trained BPE
tokenizer + chat template, grammar-masked decode, KV session residency.
"""

import json

import pytest

from quoracle_tpu.models.config import get_model_config
from quoracle_tpu.models.loader import register_hf_checkpoint
from quoracle_tpu.models.make_checkpoint import make_checkpoint
from quoracle_tpu.models.runtime import QueryRequest, TPUBackend
from quoracle_tpu.models.tokenizer import HFAutoTokenizer, get_tokenizer
from quoracle_tpu.runtime import Runtime, RuntimeConfig


@pytest.fixture(scope="module")
def ckpt_dirs(tmp_path_factory):
    root = tmp_path_factory.mktemp("ckpts")
    return [
        make_checkpoint(str(root / "llama-t"), family="llama", scale="tiny",
                        seed=0),
        make_checkpoint(str(root / "gemma-t"), family="gemma", scale="tiny",
                        seed=1),
    ]


def test_checkpoint_registers_with_own_tokenizer(ckpt_dirs):
    cfg = register_hf_checkpoint(ckpt_dirs[0], name="e2e-llama")
    assert cfg.checkpoint_path == ckpt_dirs[0]
    tok = get_tokenizer("e2e-llama")
    assert isinstance(tok, HFAutoTokenizer)
    # specials round-trip and the chat template renders role markers
    ids = tok.encode_chat([{"role": "user", "content": "hello"}])
    assert ids[0] == cfg.bos_token_id
    assert tok.decode(tok.encode("hello world")) == "hello world"
    # exact counting: the serving tokenizer is the counting tokenizer
    assert tok.count("hello world") == len(tok.encode("hello world"))


def test_backend_serves_checkpoint_with_sessions_and_grammar(ckpt_dirs):
    register_hf_checkpoint(ckpt_dirs[0], name="e2e-llama")
    backend = TPUBackend(["xla:e2e-llama"])
    msgs = [{"role": "system", "content": "You decide actions."},
            {"role": "user", "content": "Report status, then continue."}]
    r1 = backend.query([QueryRequest(
        model_spec="xla:e2e-llama", messages=msgs, max_tokens=48,
        session_id="agent-e2e", constrain_json=True)])[0]
    assert r1.ok, r1.error
    assert r1.usage.prompt_tokens > 0 and r1.usage.completion_tokens > 0
    if r1.text.strip():
        # grammar-masked: whatever was emitted is a prefix of valid JSON
        # (full parse when the row closed before its budget)
        try:
            obj = json.loads(r1.text)
            assert isinstance(obj, (dict,))
        except json.JSONDecodeError:
            pass  # truncated at budget: prefix-valid by construction

    # refinement-style second round: same conversation + one more message
    engine = backend.engines["xla:e2e-llama"]
    msgs2 = msgs + [{"role": "assistant", "content": r1.text or "…"},
                    {"role": "user", "content": "Refine your proposal."}]
    r2 = backend.query([QueryRequest(
        model_spec="xla:e2e-llama", messages=msgs2, max_tokens=32,
        session_id="agent-e2e", constrain_json=True)])[0]
    assert r2.ok, r2.error
    full = len(engine.tokenizer.encode_chat(msgs2))
    # KV residency: only the suffix beyond round 1's resident prefix ran
    assert engine.last_prefill_tokens < full

    # dropping the session forgets the prefix
    backend.drop_session("agent-e2e")
    assert len(engine.sessions) == 0


def test_runtime_builds_tpu_backend_from_checkpoints(ckpt_dirs):
    rt = Runtime(RuntimeConfig(backend="tpu", checkpoints=list(ckpt_dirs),
                               tp=1))
    try:
        names = sorted(rt.backend.engines)
        assert names == ["xla:gemma-t", "xla:llama-t"]
        assert sorted(rt.default_pool()) == names
        # engines hold REAL loaded weights: embed rows match the checkpoint
        cfg = get_model_config("xla:llama-t")
        assert cfg.checkpoint_path == ckpt_dirs[0]
        # the runtime's token manager counts through the HF tokenizer
        n = rt.token_manager.count("xla:llama-t", "hello world")
        tok = get_tokenizer("xla:llama-t")
        assert n == tok.count("hello world")
        # one query through the runtime's backend (submeshes active: the
        # conftest forces 8 virtual devices, so this exercises the
        # sub-meshed composition root path too)
        r = rt.backend.query([QueryRequest(
            model_spec="xla:llama-t",
            messages=[{"role": "user", "content": "hi"}], max_tokens=8)])[0]
        assert r.ok, r.error
    finally:
        rt.close()


def test_runtime_checkpoint_pool_overridden_by_explicit_pool(ckpt_dirs):
    register_hf_checkpoint(ckpt_dirs[0], name="e2e-llama")
    rt = Runtime(RuntimeConfig(backend="tpu", checkpoints=[ckpt_dirs[1]],
                               model_pool=["xla:e2e-llama"], tp=1))
    try:
        assert list(rt.backend.engines) == ["xla:e2e-llama"]
    finally:
        rt.close()
