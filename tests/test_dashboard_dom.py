"""DOM-level dashboard tests (VERDICT r4 item 5).

No browser or JS engine exists in this image, so the DOM under test is the
server-rendered standalone views (/logs, /mailbox, /telemetry —
web/views.py): a grove task is started through the SAME API call the SPA's
new-task modal posts, and the resulting pages are parsed into an element
tree with html.parser — assertions run against real DOM structure (nodes,
classes, data attributes), not substring greps.

The SPA's client-side JS can't execute here; its regression net is the
contract test at the bottom: every element id the JS looks up must exist
in the page markup, and every API path it fetches must be a route the
server serves — the two ways the 441-line page actually breaks.
"""

import asyncio
import json
import re
import time
import urllib.request
from html.parser import HTMLParser

from quoracle_tpu.models.runtime import MockBackend
from quoracle_tpu.runtime import Runtime, RuntimeConfig
from quoracle_tpu.web import DashboardServer
from quoracle_tpu.web.page import DASHBOARD_HTML

POOL = MockBackend.DEFAULT_POOL


def j(action, params=None, wait=False):
    return json.dumps({"action": action, "params": params or {},
                       "reasoning": "t", "wait": wait})


# ---------------------------------------------------------------------------
# Minimal DOM: parse HTML into a navigable element tree (stdlib only)
# ---------------------------------------------------------------------------

class Node:
    def __init__(self, tag, attrs):
        self.tag = tag
        self.attrs = dict(attrs)
        self.children: list = []
        self.text = ""

    @property
    def classes(self):
        return (self.attrs.get("class") or "").split()

    def all_text(self) -> str:
        return self.text + "".join(c.all_text() for c in self.children)

    def find_all(self, tag=None, cls=None, **data):
        out = []
        stack = list(self.children)
        while stack:
            n = stack.pop(0)
            ok = ((tag is None or n.tag == tag)
                  and (cls is None or cls in n.classes)
                  and all(n.attrs.get(k.replace("_", "-")) == v
                          for k, v in data.items()))
            if ok:
                out.append(n)
            stack = n.children + stack
        return out

    def find(self, tag=None, cls=None, **data):
        found = self.find_all(tag, cls, **data)
        return found[0] if found else None


VOID = {"meta", "br", "hr", "img", "input", "link"}


class DomParser(HTMLParser):
    def __init__(self):
        super().__init__()
        self.root = Node("#root", [])
        self.stack = [self.root]

    def handle_starttag(self, tag, attrs):
        node = Node(tag, attrs)
        self.stack[-1].children.append(node)
        if tag not in VOID:
            self.stack.append(node)

    def handle_endtag(self, tag):
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag:
                del self.stack[i:]
                break

    def handle_data(self, data):
        self.stack[-1].text += data


def dom(html_text: str) -> Node:
    p = DomParser()
    p.feed(html_text)
    return p.root


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

async def fetch(url: str) -> str:
    def call():
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read().decode()
    return await asyncio.get_running_loop().run_in_executor(None, call)


async def post(url: str, body: dict):
    def call():
        req = urllib.request.Request(
            url, method="POST", data=json.dumps(body).encode(),
            headers={"content-type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    return await asyncio.get_running_loop().run_in_executor(None, call)


async def until(cond, timeout=15.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError("condition not met")


# ---------------------------------------------------------------------------
# the VERDICT criterion: grove task from the browser → live todos + cost
# roll-up, asserted on DOM
# ---------------------------------------------------------------------------

def test_grove_task_shows_todos_and_costs_in_dom(tmp_path):
    import sys
    sys.path.insert(0, "tests")
    from test_governance_grove import write_grove

    async def main():
        grove_dir, _ws = write_grove(tmp_path, confinement_mode="warn")

        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if "ui-grove-run" in joined and "dom-todo-alpha" not in joined:
                return j("todo", {"items": [
                    {"task": "dom-todo-alpha", "done": False},
                    {"task": "dom-todo-beta", "done": True}]})
            if "dom-todo-alpha" in joined and "ui-manual-cost" not in joined:
                # drive the cost pipeline the way an agent does (MockBackend
                # queries are free): record_cost → CostRecorder → roll-up
                return j("record_cost", {"amount": 0.25,
                                         "description": "ui-manual-cost"})
            return j("wait", {})

        rt = Runtime(RuntimeConfig(groves_dir=str(tmp_path)),
                     backend=MockBackend(respond=respond))
        server = await DashboardServer(rt, port=0).start()
        base = server.url
        try:
            status, made = await post(base + "/api/tasks", {
                "description": "ui-grove-run", "grove": str(grove_dir),
                "model_pool": list(POOL)})
            assert status == 201
            task_id = made["task_id"]
            await until(lambda: rt.registry.all() and any(
                a.core.ctx.todos for a in rt.registry.all()))
            # costs recorded for the consensus rounds
            await until(lambda: any(
                float(rt.costs.total_for(a.agent_id)) > 0
                for a in rt.registry.all()))

            # ---- /mailbox DOM: agent card with live todos + cost ----
            page = dom(await fetch(base + f"/mailbox?task_id={task_id}"))
            cards = page.find_all(cls="agent-card")
            assert cards, "no agent cards rendered"
            root_card = cards[0]
            todo_items = root_card.find_all("li", cls="todo")
            texts = [t.all_text().strip() for t in todo_items]
            assert "dom-todo-alpha" in texts
            assert "dom-todo-beta" in texts
            done = [t for t in todo_items if "todo-done" in t.classes]
            assert [t.all_text().strip() for t in done] == ["dom-todo-beta"]
            cost_span = root_card.find(cls="agent-cost")
            assert cost_span is not None
            cost_val = float(cost_span.all_text().split("=", 1)[1])
            assert cost_val > 0, "agent card cost roll-up not positive"

            # ---- task strip: cost roll-up + live agent count ----
            task_rows = page.find_all(cls="task-row")
            row = next(r for r in task_rows
                       if r.attrs.get("data-task") == task_id)
            cost_cell = row.find(cls="task-cost")
            assert float(cost_cell.all_text()) > 0

            # ---- /logs DOM: decision logs joined to the task ----
            logs = dom(await fetch(base + f"/logs?task_id={task_id}"))
            log_rows = logs.find_all(cls="log-row")
            assert log_rows, "no log rows rendered"
            assert any(task_id in r.all_text() for r in log_rows)
            # level filter narrows the DOM
            only_dec = dom(await fetch(
                base + f"/logs?task_id={task_id}&level=decision"))
            dec_rows = only_dec.find_all(cls="log-row")
            assert dec_rows and all("lvl-decision" in r.classes
                                    for r in dec_rows)

            # ---- /telemetry DOM: metric tables render ----
            tele = dom(await fetch(base + "/telemetry"))
            assert tele.find_all(cls="metrics"), "no metric tables"

            # ---- /settings DOM: read-only audit view ----
            rt.secrets.put("dom-secret", "never-shown-value")
            st = dom(await fetch(base + "/settings"))
            models_list = st.find("ul", **{"id": "models"})
            assert models_list is not None and models_list.find_all("li")
            secret_items = st.find_all(cls="secret")
            assert any("dom-secret" in s.all_text() for s in secret_items)
            assert "never-shown-value" not in st.all_text()
        finally:
            await server.stop()
            await rt.shutdown()
    asyncio.run(main())


def test_mailbox_dom_shows_task_messages(tmp_path):
    """A user message posted from the browser prompts the agent to reply
    with send_message (announcement) — the task_message event lands in the
    durable mailbox and the /mailbox DOM shows it with its sender."""
    async def main():
        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if ("hello-from-the-mailbox" in joined
                    and "mailbox-reply-mark" not in joined):
                return j("send_message", {"target": "announcement",
                                          "content": "mailbox-reply-mark"})
            return j("wait", {})
        rt = Runtime(RuntimeConfig(), backend=MockBackend(respond=respond))
        server = await DashboardServer(rt, port=0).start()
        base = server.url
        try:
            status, made = await post(base + "/api/tasks", {
                "description": "mailbox dom", "model_pool": list(POOL)})
            assert status == 201
            root = made["root_agent"]
            await until(lambda: rt.registry.all())
            status, _ = await post(base + "/api/messages", {
                "agent_id": root, "content": "hello-from-the-mailbox"})
            await until(lambda: rt.db.query(
                "SELECT 1 FROM messages WHERE content LIKE "
                "'%mailbox-reply-mark%'"))
            page = dom(await fetch(base + "/mailbox"))
            msgs = page.find_all(cls="msg")
            target = [m for m in msgs
                      if "mailbox-reply-mark" in m.all_text()]
            assert target, "agent reply not rendered in mailbox DOM"
            sender = target[0].find(cls="from")
            assert sender is not None and sender.all_text().strip() == root
        finally:
            await server.stop()
            await rt.shutdown()
    asyncio.run(main())


# ---------------------------------------------------------------------------
# SPA contract: JS element ids + API routes must exist
# ---------------------------------------------------------------------------

def test_spa_js_dom_and_api_contract():
    """The page's JS breaks in two ways this harness can catch without a
    JS engine: a getElementById for an id the markup no longer has, or a
    fetch of an API path the server no longer routes. Both are extracted
    from the real page source and checked against the real artifacts."""
    markup, _, script = DASHBOARD_HTML.partition("<script>")
    looked_up = set(re.findall(r'\$\("([a-zA-Z0-9_-]+)"\)', script))
    assert looked_up, "no $(id) lookups found — extraction broken?"
    declared = set(re.findall(r'id="([a-zA-Z0-9_-]+)"', markup))
    # ids the JS creates dynamically before looking them up
    dynamic = set(re.findall(r'id="([a-zA-Z0-9_-]+)"', script)) | \
        set(re.findall(r"\.id\s*=\s*\"([a-zA-Z0-9_-]+)\"", script))
    missing = looked_up - declared - dynamic
    assert not missing, f"JS looks up ids missing from markup: {missing}"

    import inspect

    from quoracle_tpu.web import server as server_mod
    handler_src = inspect.getsource(server_mod)
    for path in set(re.findall(r'api\("(/[a-z/]+)"', script)):
        assert f'"{path}"' in handler_src or \
            f'"{path}/' in handler_src or path in handler_src, \
            f"SPA fetches {path} but the server never routes it"
