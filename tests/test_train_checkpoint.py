"""Fine-tuning substrate weight checkpoints (models/train.py +
orbax): save a trained state, restore into a fresh template, and resume
training bit-identically. The reference has no training at all (hosted
models, SURVEY §2.3); this capability is new, so the round-trip test is
the contract."""

import jax
import jax.numpy as jnp
import numpy as np

from quoracle_tpu.models.config import get_model_config
from quoracle_tpu.models.train import (
    TrainState, load_train_state, make_optimizer, save_train_state,
    train_step,
)
from quoracle_tpu.models.transformer import init_params


def _batch(cfg, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(3, cfg.vocab_size, (2, 16)), jnp.int32)
    return tokens, jnp.ones((2, 16), jnp.float32)


def test_train_state_roundtrip_resumes_identically(tmp_path):
    cfg = get_model_config("xla:tiny")
    opt = make_optimizer(1e-3)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))
    tokens, mask = _batch(cfg)
    state, _ = train_step(state, cfg, opt, tokens, mask)
    save_train_state(str(tmp_path / "ckpt"), state)

    fresh = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.bfloat16)
    template = TrainState(fresh, opt.init(fresh), jnp.zeros((), jnp.int32))
    restored = load_train_state(str(tmp_path / "ckpt"), template)
    assert int(restored.step) == 1
    # exact round-trip
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # resuming from the restore matches continuing the original run
    t2, m2 = _batch(cfg, seed=1)
    s_cont, loss_cont = train_step(state, cfg, opt, t2, m2)
    s_rest, loss_rest = train_step(restored, cfg, opt, t2, m2)
    np.testing.assert_array_equal(np.asarray(loss_cont),
                                  np.asarray(loss_rest))
    assert int(s_cont.step) == int(s_rest.step) == 2


def test_save_overwrites_stable_path(tmp_path):
    """Periodic saves to one path (ckpt/latest every N steps) must
    overwrite, not crash (orbax defaults to force=False)."""
    cfg = get_model_config("xla:tiny")
    opt = make_optimizer(1e-3)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))
    path = str(tmp_path / "latest")
    save_train_state(path, state)
    tokens, mask = _batch(cfg)
    state, _ = train_step(state, cfg, opt, tokens, mask)
    save_train_state(path, state)            # second save: must overwrite
    restored = load_train_state(path, state)
    assert int(restored.step) == 1
