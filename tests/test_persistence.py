"""Persistence: durable state, task lifecycle, pause/restore, boot revival.

Mirrors the reference's checkpoint/resume coverage (SURVEY.md §3.4/§5):
continuous conversation persistence, pause → stopped rows → restore rebuilds
the live tree with histories, revival finalizes stale states. Every test
gets its own in-memory SQLite DB + registry + bus — full isolation.
"""

import asyncio
import json
import time

import pytest

from quoracle_tpu.agent import AgentConfig, AgentDeps, AgentSupervisor
from quoracle_tpu.context.history import DECISION
from quoracle_tpu.infra.costs import CostRecorder
from quoracle_tpu.models.runtime import MockBackend
from quoracle_tpu.persistence import Database, Persistence, TaskManager
from quoracle_tpu.persistence.db import Vault
from quoracle_tpu.persistence.store import (
    PersistentSecretStore, deserialize_config, deserialize_context,
    serialize_config, serialize_context,
)

POOL = MockBackend.DEFAULT_POOL


def j(action, params=None, wait=False):
    return json.dumps({"action": action, "params": params or {},
                       "reasoning": "test", "wait": wait})


def scripted(*entries):
    return MockBackend(
        scripts={m: list(entries) for m in POOL},
        respond=lambda r: j("wait", {}))


def make_stack(backend, db=None, key="test-key-123"):
    db = db or Database(":memory:", encryption_key=key)
    store = Persistence(db)
    deps = AgentDeps.for_tests(backend,
                               secrets=PersistentSecretStore(db))
    deps.costs = CostRecorder(escrow=deps.escrow, events=deps.events,
                              persist_fn=store.persist_cost)
    sup = AgentSupervisor(deps)
    tm = TaskManager(deps, store)
    store.attach_bus(deps.events.bus)
    return db, store, deps, sup, tm


async def until(cond, timeout=10.0, interval=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError("condition not met within timeout")


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# Vault / serialization
# ---------------------------------------------------------------------------

def test_vault_roundtrip_and_degraded_mode():
    v = Vault("some-key")
    blob, enc = v.encrypt("hunter2secret")
    assert enc and blob != b"hunter2secret"
    assert v.decrypt(blob, enc) == "hunter2secret"
    degraded = Vault("")
    blob2, enc2 = degraded.encrypt("plain")
    assert not enc2 and blob2 == b"plain"
    assert degraded.decrypt(blob2, enc2) == "plain"
    with pytest.raises(RuntimeError):
        degraded.decrypt(blob, True)


def test_config_and_context_serialization_roundtrip():
    from decimal import Decimal
    from quoracle_tpu.context.history import (
        AgentContext, HistoryEntry, Lesson, USER,
    )
    cfg = AgentConfig(agent_id="a1", task_id="t1", model_pool=list(POOL),
                      parent_id="a0", profile="default",
                      capability_groups=["communication"],
                      forbidden_actions=("execute_shell",),
                      budget_mode="allocated",
                      budget_limit=Decimal("3.50"))
    cfg2 = deserialize_config(serialize_config(cfg))
    assert cfg2 == cfg

    ctx = AgentContext()
    ctx.append_all(HistoryEntry(kind=USER, content="hello"), POOL)
    ctx.context_lessons[POOL[0]] = [Lesson("factual", "the sky is blue", 2)]
    ctx.model_states[POOL[0]] = ["mid-task"]
    ctx.todos = [{"task": "x"}]
    ctx2 = deserialize_context(serialize_context(ctx, [{"agent_id": "c1"}]))
    assert ctx2.model_histories[POOL[0]][0].content == "hello"
    assert ctx2.context_lessons[POOL[0]][0].content == "the sky is blue"
    assert ctx2.context_lessons[POOL[0]][0].confidence == 2
    assert ctx2.todos == [{"task": "x"}]
    assert ctx2.children == [{"agent_id": "c1"}]


def test_persistent_secret_store_encrypts_at_rest():
    db = Database(":memory:", encryption_key="k1")
    store = PersistentSecretStore(db)
    store.put("api_key", "super-secret-value", "test secret")
    row = db.query_one("SELECT * FROM secrets WHERE name='api_key'")
    assert row["encrypted"] == 1
    assert b"super-secret-value" not in bytes(row["value"])
    # lookup with agent audit writes secret_usage
    assert store.lookup("api_key", agent_id="a1", action="call_api") \
        == "super-secret-value"
    usage = db.query("SELECT * FROM secret_usage")
    assert usage and usage[0]["agent_id"] == "a1"
    # a fresh store over the same DB decrypts
    store2 = PersistentSecretStore(db)
    assert store2.lookup("api_key") == "super-secret-value"


# ---------------------------------------------------------------------------
# Task lifecycle end-to-end
# ---------------------------------------------------------------------------

def test_create_task_persists_agent_and_events():
    async def main():
        backend = scripted(
            j("todo", {"items": [{"task": "step-1"}]}), j("wait", {}))
        db, store, deps, sup, tm = make_stack(backend)
        task_id, root = await tm.create_task(
            "plan the work", model_pool=list(POOL))
        await until(lambda: root.ctx.todos)
        await until(lambda: len(
            [e for e in root.ctx.history(POOL[0]) if e.kind == DECISION]) >= 2)
        # agent row persisted with conversation
        rows = store.agents_for_task(task_id)
        assert len(rows) == 1
        assert rows[0]["context"].todos == [{"task": "step-1"}]
        # durable logs + actions rows from the bus tail
        assert db.query("SELECT * FROM logs WHERE agent_id=?",
                        (root.agent_id,))
        acts = db.query("SELECT * FROM actions WHERE agent_id=?",
                        (root.agent_id,))
        assert {a["action"] for a in acts} >= {"todo", "wait"}
        assert all(a["status"] == "ok" for a in acts
                   if a["completed_at"] is not None)
        # cost rows written through
        assert db.query("SELECT * FROM agent_costs")
        await tm.pause_task(task_id)
    run(main())


def test_pause_then_restore_rebuilds_tree_with_history():
    async def main():
        def respond(r):
            joined = "\n".join(str(m.get("content", "")) for m in r.messages)
            if "[TASK]" in joined:
                return j("wait", {})
            if "resume-ping" in joined:
                return j("todo", {"items": [{"task": "resumed"}]})
            if '"agent_id"' in joined:
                return j("wait", {})
            return j("spawn_child", {
                "task_description": "hold position",
                "success_criteria": "n/a", "immediate_context": "n/a",
                "approach_guidance": "wait", "profile": "default"})
        backend = MockBackend(respond=respond)
        db, store, deps, sup, tm = make_stack(backend)
        task_id, root = await tm.create_task("delegate then idle",
                                             model_pool=list(POOL))
        await until(lambda: root.children)
        child_id = root.children[0]["agent_id"]
        root_id = root.agent_id
        stopped = await tm.pause_task(task_id)
        assert stopped == 2
        assert len(deps.registry) == 0
        assert store.get_task(task_id)["status"] == "paused"
        rows = store.agents_for_task(task_id)
        assert {r["agent_id"] for r in rows} == {root_id, child_id}
        assert all(r["status"] == "stopped" for r in rows)

        # restore into a FRESH runtime stack (new registry/supervisor/bus)
        # over the same DB — the reboot scenario
        deps2 = AgentDeps.for_tests(backend,
                                    secrets=PersistentSecretStore(db))
        sup2 = AgentSupervisor(deps2)
        tm2 = TaskManager(deps2, store)
        n = await tm2.restore_task(task_id)
        assert n == 2
        reg_root = deps2.registry.lookup(root_id)
        reg_child = deps2.registry.lookup(child_id)
        assert reg_root is not None and reg_child is not None
        assert reg_child.parent_id == root_id
        core2 = reg_root.core
        # children tracker and history survived
        assert [c["agent_id"] for c in core2.children] == [child_id]
        texts = [e.as_text() for e in core2.ctx.history(POOL[0])]
        assert any("delegate then idle" in t for t in texts)
        # the restored agent is idle but wakes on a message
        core2.post({"type": "user_message", "content": "resume-ping",
                    "from": "user"})
        await until(lambda: core2.ctx.todos == [{"task": "resumed"}])
        await tm2.pause_task(task_id)
    run(main())


def test_boot_revival_restores_running_finalizes_pausing():
    async def main():
        backend = MockBackend(respond=lambda r: j("wait", {}))
        db, store, deps, sup, tm = make_stack(backend)
        # task 1: left 'running' in the DB (simulated crash: rows persist,
        # no live agents)
        t1, root1 = await tm.create_task("crash victim",
                                         model_pool=list(POOL))
        await until(lambda: not root1.consensus_scheduled
                    and not root1.pending_actions
                    and len(root1.ctx.history(POOL[0])) >= 3)
        await sup.stop_all(t1)            # simulate crash: no status change
        store.db.execute("UPDATE tasks SET status='running' WHERE id=?",
                         (t1,))
        # task 2: stuck mid-pause
        t2, root2 = await tm.create_task("stale pauser",
                                         model_pool=list(POOL))
        await sup.stop_all(t2)
        store.set_task_status(t2, "pausing")

        deps2 = AgentDeps.for_tests(backend,
                                    secrets=PersistentSecretStore(db))
        AgentSupervisor(deps2)
        tm2 = TaskManager(deps2, store)
        result = await tm2.boot_revival()
        assert result["revived"] == [t1]
        assert result["failed"] == []
        assert store.get_task(t2)["status"] == "paused"
        assert deps2.registry.agents_for_task(t1)
        assert not deps2.registry.agents_for_task(t2)
        await tm2.pause_task(t1)
    run(main())


def test_restore_rebuilds_escrow_with_historical_spend():
    async def main():
        from decimal import Decimal
        from quoracle_tpu.infra.costs import CostEntry
        backend = MockBackend(respond=lambda r: j("wait", {}))
        db, store, deps, sup, tm = make_stack(backend)
        task_id, root = await tm.create_task(
            "budgeted", model_pool=list(POOL), budget="10")
        deps.costs.record(CostEntry(
            agent_id=root.agent_id, task_id=task_id,
            amount=Decimal("1.25"), cost_type="manual", description="x"))
        await tm.pause_task(task_id)

        deps2 = AgentDeps.for_tests(backend,
                                    secrets=PersistentSecretStore(db))
        AgentSupervisor(deps2)
        tm2 = TaskManager(deps2, store)
        await tm2.restore_task(task_id)
        st = deps2.escrow.get(root.agent_id)
        assert st.mode == "root" and st.limit == Decimal("10")
        assert st.spent >= Decimal("1.25")
        await tm2.pause_task(task_id)
    run(main())


def test_dismissal_deletes_rows():
    async def main():
        import re
        seen = {}
        def respond(r):
            joined = "\n".join(str(m.get("content", "")) for m in r.messages)
            if "[TASK]" in joined:                   # the child
                if '"delivered_to"' in joined:
                    return j("wait", {})
                return j("send_message",
                         {"target": "parent", "content": "child ready"})
            if '"dismissed"' in joined:
                return j("wait", {})
            if "child ready" in joined:
                # child is live and messaging → both rows must be durable
                seen["rows_before_dismiss"] = \
                    len(db.query("SELECT * FROM agents"))
                m = re.search(r'from="(agent-[0-9a-f]+)"', joined)
                return j("dismiss_child", {"child_id": m.group(1)})
            if '"agent_id"' in joined:               # spawn acked: wait
                return j("wait", {})
            return j("spawn_child", {
                "task_description": "ephemeral", "success_criteria": "n/a",
                "immediate_context": "n/a", "approach_guidance": "wait",
                "profile": "default"})
        backend = MockBackend(respond=respond)
        db, store, deps, sup, tm = make_stack(backend)
        seen2 = {}
        def on_lifecycle(t, e):
            if e["event"] == "agent_dismissed":
                seen2["dismissed"] = e["agent_id"]
        from quoracle_tpu.infra.bus import TOPIC_LIFECYCLE
        deps.events.bus.subscribe(TOPIC_LIFECYCLE, on_lifecycle)
        task_id, root = await tm.create_task("spawn and dismiss",
                                             model_pool=list(POOL))
        await until(lambda: "dismissed" in seen2, timeout=15)
        assert seen["rows_before_dismiss"] == 2
        rows = store.agents_for_task(task_id)
        assert len(rows) == 1 and rows[0]["agent_id"] == root.agent_id
        await tm.pause_task(task_id)
    run(main())


def test_profiles_and_settings():
    db = Database(":memory:")
    store = Persistence(db)
    store.save_profile("researcher", {
        "model_pool": list(POOL), "capability_groups": ["communication"],
        "max_refinement_rounds": 2})
    assert store.get_profile("researcher")["max_refinement_rounds"] == 2
    assert store.list_profiles() == ["researcher"]
    store.set_setting("embedding_model", "xla:tiny")
    assert store.get_setting("embedding_model") == "xla:tiny"
    assert store.get_setting("missing", "dflt") == "dflt"


def test_create_task_with_profile_resolution():
    async def main():
        backend = MockBackend(respond=lambda r: j("wait", {}))
        db, store, deps, sup, tm = make_stack(backend)
        store.save_profile("minimal", {
            "model_pool": list(POOL),
            "capability_groups": [],
            "max_refinement_rounds": 1})
        task_id, root = await tm.create_task("profiled task",
                                             profile="minimal")
        assert root.config.capability_groups == []
        assert root.config.max_refinement_rounds == 1
        assert root.config.profile_names == ("minimal",)
        with pytest.raises(ValueError):
            await tm.create_task("bad", profile="nope")
        await tm.pause_task(task_id)
    run(main())
