"""Agent runtime: actor core loop, routers, spawn/dismiss trees, shell.

Mirrors the reference's multi-agent 'distribution' testing style
(reference SURVEY.md §4): real actor trees with per-test isolated
registry/bus/backend — no shared state between tests, every test could run
in parallel.
"""

import asyncio
import json
import re
import time

import pytest

from quoracle_tpu.agent import (
    AgentConfig, AgentDeps, AgentRegistry, AgentSupervisor,
)
from quoracle_tpu.context.history import DECISION, RESULT
from quoracle_tpu.infra.bus import EventBus, AgentEvents, TOPIC_LIFECYCLE
from quoracle_tpu.models.runtime import MockBackend

POOL = MockBackend.DEFAULT_POOL


def j(action, params=None, wait=False, reasoning="test"):
    return json.dumps({"action": action, "params": params or {},
                       "reasoning": reasoning, "wait": wait})


WAIT_FOREVER = j("wait", {}, wait=False)


def scripted(*entries):
    """Same script for every pool member → unanimous round-1 consensus."""
    return MockBackend(scripts={m: list(entries) for m in POOL},
                       respond=lambda r: WAIT_FOREVER)


def make_env(backend):
    deps = AgentDeps.for_tests(backend)
    sup = AgentSupervisor(deps)
    return deps, sup


def root_config(**over):
    defaults = dict(agent_id="agent-root", task_id="task-1",
                    model_pool=list(POOL))
    defaults.update(over)
    return AgentConfig(**defaults)


async def until(cond, timeout=10.0, interval=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError("condition not met within timeout")


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def decisions(core, model=POOL[0]):
    return [e.content for e in core.ctx.history(model) if e.kind == DECISION]


def results(core, model=POOL[0]):
    return [e for e in core.ctx.history(model) if e.kind == RESULT]


# ---------------------------------------------------------------------------

def test_todo_then_wait_cycle():
    async def main():
        backend = scripted(
            j("todo", {"items": [{"task": "greet", "done": False}]}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "make a todo list",
                   "from": "user"})
        await until(lambda: len(decisions(core)) >= 2)
        assert core.ctx.todos == [{"task": "greet", "done": False}]
        assert decisions(core)[0]["action"] == "todo"
        assert decisions(core)[1]["action"] == "wait"
        # wait with no duration → indefinite idle, no pending actions
        await until(lambda: not core.pending_actions)
        assert not core.consensus_scheduled
        # each model got its own history with the same decisions
        for m in POOL:
            assert len(decisions(core, m)) == 2
        await sup.terminate_agent("agent-root")
        assert deps.registry.lookup("agent-root") is None
    run(main())


def test_message_wakes_indefinitely_waiting_agent():
    async def main():
        backend = scripted(
            j("wait", {}),                      # cycle 1: go idle
            j("todo", {"items": [{"task": "respond"}]}),  # woken by message
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "hold on", "from": "user"})
        await until(lambda: len(decisions(core)) == 1)
        await asyncio.sleep(0.05)
        assert core.ctx.todos == []             # still idle
        core.post({"type": "user_message", "content": "now act", "from": "user"})
        await until(lambda: core.ctx.todos)
        # the wake-up message was flushed into history as a batch
        texts = [e.as_text() for e in core.ctx.history(POOL[0])]
        assert any("now act" in t for t in texts)
        await sup.terminate_agent("agent-root")
    run(main())


def test_timed_wait_fires_timeout():
    async def main():
        backend = scripted(
            j("wait", {"duration": 1}),
            j("todo", {"items": [{"task": "after-timeout"}]}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        deps.shell_sync_threshold_s = 0.05
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: core.ctx.todos, timeout=15)
        texts = [e.as_text() for e in core.ctx.history(POOL[0])]
        assert any("wait period elapsed" in t for t in texts)
        await sup.terminate_agent("agent-root")
    run(main())


# ---------------------------------------------------------------------------
# Shell smart mode (reference shell.ex:13 — 100ms sync/async cutoff)
# ---------------------------------------------------------------------------

def test_shell_sync_fast_command():
    async def main():
        backend = scripted(
            j("execute_shell", {"command": "echo fast-path"}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run it", "from": "user"})
        await until(lambda: len(decisions(core)) >= 2)
        first_result = results(core)[0].as_text()
        assert "fast-path" in first_result
        assert '"sync": true' in first_result.lower() or "sync" in first_result
        # untrusted output is NO_EXECUTE-fenced before entering history
        assert "NO_EXECUTE" in first_result
        await sup.terminate_agent("agent-root")
    run(main())


def test_shell_async_slow_command_completion_notification():
    async def main():
        backend = scripted(
            j("execute_shell", {"command": "sleep 0.4; echo slow-done"}),
            j("wait", {}),     # after async-started result
            j("wait", {}),     # after completion notification
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run it", "from": "user"})
        # async result registers a live command router
        await until(lambda: core.shell_routers)
        cmd_id = next(iter(core.shell_routers))
        assert re.match(r"cmd-[0-9a-f]+", cmd_id)
        # completion posts a system message and clears the router
        await until(lambda: not core.shell_routers, timeout=15)
        await until(lambda: len(decisions(core)) >= 3)
        texts = [e.as_text() for e in core.ctx.history(POOL[0])]
        assert any("slow-done" in t for t in texts)
        assert any(cmd_id in t for t in texts)
        await sup.terminate_agent("agent-root")
    run(main())


def test_shell_check_id_poll_and_terminate():
    async def main():
        backend = scripted(
            j("execute_shell", {"command": "sleep 30"}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run", "from": "user"})
        await until(lambda: core.shell_routers)
        cmd_id = next(iter(core.shell_routers))
        owner = core.shell_routers[cmd_id]
        poll = owner.poll_command()
        assert poll["command_status"] == "running"
        term = await owner.terminate_command()
        assert term["command_status"] == "terminated"
        assert cmd_id not in core.shell_routers
        await sup.terminate_agent("agent-root")
    run(main())


def test_shell_early_output_not_lost_on_async_handoff():
    async def main():
        # Output emitted BEFORE the sync threshold must survive into the
        # completion notification (pump starts at launch).
        backend = scripted(
            j("execute_shell",
              {"command": "echo early-marker; sleep 0.4; echo late-marker"}),
            j("wait", {}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run", "from": "user"})
        await until(lambda: core.shell_routers)      # async handoff happened
        # completion notification (NOT the decision echoing the command)
        await until(lambda: any(
            "finished with status" in e.as_text()
            for e in core.ctx.history(POOL[0])), timeout=15)
        completion = next(t for t in
                          (e.as_text() for e in core.ctx.history(POOL[0]))
                          if "finished with status" in t)
        assert "early-marker" in completion and "late-marker" in completion
        await sup.terminate_agent("agent-root")
    run(main())


def test_shell_daemonizing_command_still_completes():
    async def main():
        # The shell exits quickly but a backgrounded descendant inherits
        # stdout and holds the pipe open — completion must key off process
        # exit, not pipe EOF.
        backend = scripted(
            j("execute_shell",
              {"command": "sleep 5 >/dev/null & echo daemon-started; sleep 0.2"}),
            j("wait", {}), j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run", "from": "user"})
        await until(lambda: any(
            "finished with status completed" in e.as_text()
            for e in core.ctx.history(POOL[0])), timeout=15)
        completion = next(t for t in
                          (e.as_text() for e in core.ctx.history(POOL[0]))
                          if "finished with status" in t)
        assert "daemon-started" in completion
        assert not core.shell_routers
        await sup.terminate_agent("agent-root")
    run(main())


def test_batch_with_two_slow_shells_gets_independent_owners():
    async def main():
        backend = scripted(
            j("batch_async", {"actions": [
                {"action": "execute_shell",
                 "params": {"command": "sleep 0.35; echo done-one"}},
                {"action": "execute_shell",
                 "params": {"command": "sleep 0.45; echo done-two"}},
            ]}),
            j("wait", {}), j("wait", {}), j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "run", "from": "user"})
        # both commands cross the threshold → two distinct owners
        await until(lambda: len(core.shell_routers) == 2)
        ids = list(core.shell_routers)
        assert len(set(ids)) == 2
        polls = [core.shell_routers[i].poll_command() for i in ids]
        assert {p["command_id"] for p in polls} == set(ids)
        # both complete independently and deliver their own output
        await until(lambda: not core.shell_routers, timeout=15)
        texts = " ".join(e.as_text() for e in core.ctx.history(POOL[0]))
        assert "done-one" in texts and "done-two" in texts
        await sup.terminate_agent("agent-root")
    run(main())


# ---------------------------------------------------------------------------
# Spawn / message / dismiss across a real tree
# ---------------------------------------------------------------------------

def spawn_params(**over):
    p = dict(task_description="greet your parent",
             success_criteria="parent greeted",
             immediate_context="you were just created",
             approach_guidance="send one message then wait",
             profile="default")
    p.update(over)
    return p


def tree_respond(r):
    """Content-driven scripted behavior for a parent+child tree."""
    joined = "\n".join(str(m.get("content", "")) for m in r.messages)
    if "[TASK]" in joined:                       # this is the child
        if '"delivered_to"' in joined:
            return WAIT_FOREVER
        return j("send_message",
                 {"target": "parent", "content": "hello from child"})
    # this is the root
    if "hello from child" in joined:
        m = re.search(r'from="(agent-[0-9a-f]+)"', joined)
        return j("dismiss_child", {"child_id": m.group(1)})
    if '"agent_id"' in joined:                    # spawn result seen
        return WAIT_FOREVER
    if '"dismissed"' in joined:
        return WAIT_FOREVER
    return j("spawn_child", spawn_params())


def test_spawn_child_message_dismiss_flow():
    async def main():
        backend = MockBackend(respond=tree_respond)
        deps, sup = make_env(backend)
        seen = {"spawned": [], "dismissed": []}
        def on_lifecycle(t, e):
            # Handlers run synchronously inside the broadcast, so these
            # observations can't race the fast spawn→dismiss sequence.
            if e["event"] == "agent_spawned" and e.get("parent_id"):
                seen["spawned"].append(e["agent_id"])
                assert deps.registry.lookup(e["agent_id"]).parent_id == \
                    e["parent_id"]
            if e["event"] == "agent_dismissed":
                seen["dismissed"].append(e["agent_id"])
        deps.events.bus.subscribe(TOPIC_LIFECYCLE, on_lifecycle)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "delegate the greeting",
                   "from": "user"})
        # child spawns, greets, and gets dismissed by the root
        await until(lambda: seen["dismissed"], timeout=15)
        child_id = seen["spawned"][0]
        assert seen["dismissed"] == [child_id]
        await until(lambda: deps.registry.lookup(child_id) is None)
        await until(lambda: not core.children)
        # root still alive, child gone
        assert deps.registry.lookup("agent-root") is not None
        assert len(deps.registry) == 1
        texts = [e.as_text() for e in core.ctx.history(POOL[0])]
        assert any("hello from child" in t for t in texts)
        await sup.terminate_agent("agent-root")
    run(main())


def test_spawn_oversized_field_is_presummarized():
    """An immediate_context past the per-field token threshold is
    condensed through the summarization model BEFORE the child inherits
    it (reference spawn/config_builder.ex pre-summarization); failures
    would degrade to the original text, success replaces it."""
    async def main():
        blob = "conversation history line. " * 1600   # ≫ 2000 mock tokens
        child_msgs: list = []

        def respond(r):
            joined = "\n".join(str(m.get("content", ""))
                               for m in r.messages)
            if "Condense the following context" in joined:
                return "SUMMARY-MARK: child must fix the parser."
            if "spawn-the-child" in joined and "child_spawned" not in joined:
                return j("spawn_child", spawn_params(
                    task_description="fix it",
                    immediate_context=blob,
                    approach_guidance="carefully"))
            if "[IMMEDIATE CONTEXT]" in joined:       # the child's view
                child_msgs.append(joined)
            return j("wait", {})

        backend = MockBackend(respond=respond)
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "spawn-the-child",
                   "from": "user"})
        await until(lambda: child_msgs, timeout=15)
        assert "SUMMARY-MARK" in child_msgs[0]
        assert blob not in child_msgs[0]
        # the short fields were left verbatim
        assert "[APPROACH GUIDANCE]\ncarefully" in child_msgs[0]
        await sup.terminate_agent("agent-root")
    run(main())


def test_spawn_requires_budget_when_parent_budgeted():
    async def main():
        backend = scripted(
            j("spawn_child", spawn_params()),   # no budget param → error
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config(
            budget_mode="root", budget_limit="10.0"))
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: results(core))
        first = results(core)[0].as_text()
        assert "budget is required" in first
        assert not core.children
        await sup.terminate_agent("agent-root")
    run(main())


def test_spawn_with_budget_escrows_and_dismiss_releases():
    async def main():
        def respond(r):
            joined = "\n".join(str(m.get("content", "")) for m in r.messages)
            if "[TASK]" in joined:                      # child
                if '"delivered_to"' in joined:
                    return WAIT_FOREVER
                return j("send_message",
                         {"target": "parent", "content": "child done"})
            if '"dismissed"' in joined:
                return WAIT_FOREVER
            if "child done" in joined:                  # root: dismiss now
                m = re.search(r'from="(agent-[0-9a-f]+)"', joined)
                return j("dismiss_child", {"child_id": m.group(1)})
            if '"agent_id"' in joined:                  # spawn acked: wait
                return WAIT_FOREVER
            return j("spawn_child", spawn_params(budget=4))
        backend = MockBackend(respond=respond)
        deps, sup = make_env(backend)
        # Capture escrow state at the instant the child comes alive —
        # broadcast handlers run synchronously, so this observation can't
        # race with the later dismissal.
        snapshots = {}
        def on_lifecycle(topic, e):
            if e["event"] == "agent_spawned" and e.get("parent_id") == "agent-root":
                snapshots["committed"] = deps.escrow.get("agent-root").committed
                snapshots["child_limit"] = deps.escrow.get(e["agent_id"]).limit
            if e["event"] == "agent_dismissed":
                snapshots["dismissed"] = e["agent_id"]
        deps.events.bus.subscribe(TOPIC_LIFECYCLE, on_lifecycle)
        core = await sup.start_agent(root_config(
            budget_mode="root", budget_limit="10.0"))
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: "dismissed" in snapshots, timeout=20)
        assert snapshots["committed"] == 4
        assert snapshots["child_limit"] == 4
        # dismissal released the unspent escrow back
        assert deps.escrow.get("agent-root").committed == 0
        assert len(deps.registry) == 1
        await sup.terminate_agent("agent-root")
    run(main())


def test_terminate_tree_is_bottom_up_and_idempotent():
    async def main():
        backend = MockBackend(respond=lambda r: WAIT_FOREVER)
        deps, sup = make_env(backend)
        root = await sup.start_agent(root_config())
        mid = await sup.start_agent(root_config(
            agent_id="agent-mid", parent_id="agent-root"))
        leaf = await sup.start_agent(root_config(
            agent_id="agent-leaf", parent_id="agent-mid"))
        assert len(deps.registry) == 3
        n = await sup.terminate_tree("agent-mid", by="agent-root")
        assert n == 2
        assert deps.registry.lookup("agent-mid") is None
        assert deps.registry.lookup("agent-leaf") is None
        assert deps.registry.lookup("agent-root") is not None
        # second dismissal is a no-op (dismissing flag, core.ex:213-220)
        assert await sup.terminate_tree("agent-mid") == 0
        await sup.terminate_agent("agent-root")
    run(main())


# ---------------------------------------------------------------------------
# Consensus failure → correction feedback → retry (agent AGENTS.md:204-214)
# ---------------------------------------------------------------------------

def test_consensus_retry_with_correction_feedback():
    async def main():
        backend = MockBackend(scripts={
            m: ["this is not json at all",
                j("todo", {"items": [{"task": "fixed"}]}),
                WAIT_FOREVER]
            for m in POOL}, respond=lambda r: WAIT_FOREVER)
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: core.ctx.todos, timeout=15)
        assert core.ctx.todos == [{"task": "fixed"}]
        # correction feedback was injected into the retry round's messages
        retry_calls = [c for c in backend.calls
                       if any("previous response was invalid"
                              in str(m.get("content", ""))
                              for m in c.messages)]
        assert retry_calls
        # and cleared after the successful decision
        assert core.ctx.correction_feedback == {}
        await sup.terminate_agent("agent-root")
    run(main())


def test_consensus_stall_notifies_parent():
    async def main():
        bad = MockBackend(respond=lambda r: "never valid json")
        deps, sup = make_env(bad)
        parent_inbox = []
        root = await sup.start_agent(root_config())
        child = await sup.start_agent(root_config(
            agent_id="agent-child", parent_id="agent-root",
            max_consensus_retries=2))
        # intercept the parent mailbox by watching its queued messages
        child.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: any(
            "consensus stalled" in str(m.get("content", ""))
            for m in root.queued_messages) or any(
            "consensus stalled" in e.as_text()
            for e in root.ctx.history(POOL[0])), timeout=20)
        await sup.terminate_agent("agent-child")
        await sup.terminate_agent("agent-root")
    run(main())


# ---------------------------------------------------------------------------
# Batch actions
# ---------------------------------------------------------------------------

def test_batch_sync_executes_in_order(tmp_path):
    async def main():
        backend = scripted(
            j("batch_sync", {"actions": [
                {"action": "todo", "params": {"items": [{"task": "a"}]}},
                {"action": "file_write", "params": {
                    "path": str(tmp_path / "out.txt"), "content": "hello"}},
                {"action": "file_read", "params": {
                    "path": str(tmp_path / "out.txt")}},
            ]}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "batch", "from": "user"})
        await until(lambda: results(core))
        r = results(core)[0].content["result"]
        assert r["status"] == "ok"
        assert [x["action"] for x in r["results"]] == \
            ["todo", "file_write", "file_read"]
        assert "hello" in r["results"][2]["content"]
        await sup.terminate_agent("agent-root")
    run(main())


def test_batch_async_rejects_wait_at_validation():
    async def main():
        # wait is not batchable (reference action_list.ex:79) — the proposal
        # is filtered at consensus validation, never reaching execution, and
        # the models get correction feedback on the retry round.
        backend = scripted(
            j("batch_async", {"actions": [
                {"action": "wait", "params": {}},
            ]}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "batch", "from": "user"})
        await until(lambda: results(core))
        assert decisions(core)[0]["action"] == "wait"   # retry round's pick
        retry_calls = [c for c in backend.calls
                       if any("failed validation" in str(m.get("content", ""))
                              for m in c.messages)]
        assert retry_calls
        await sup.terminate_agent("agent-root")
    run(main())


# ---------------------------------------------------------------------------
# Secrets end-to-end: generate → reference in params → scrubbed output
# ---------------------------------------------------------------------------

def test_secret_resolution_and_scrubbing():
    async def main():
        backend = scripted(
            j("generate_secret", {"name": "api_key", "length": 24}),
            j("execute_shell", {"command": "echo token={{SECRET:api_key}}"}),
            j("wait", {}),
        )
        deps, sup = make_env(backend)
        core = await sup.start_agent(root_config())
        core.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: len(decisions(core)) >= 3, timeout=15)
        value = deps.secrets.lookup("api_key")
        assert value and len(value) == 24
        shell_result = results(core)[1].as_text()
        # the secret value was substituted for execution but scrubbed from
        # the result the models see
        assert value not in shell_result
        assert "[REDACTED:api_key]" in shell_result
        # audit trail recorded the access
        assert any(a.secret_name == "api_key"
                   for a in deps.secrets.audit_log())
        await sup.terminate_agent("agent-root")
    run(main())


def test_registry_queries():
    reg = AgentRegistry()
    reg.register("a", object(), None, "t1")
    reg.register("b", object(), "a", "t1")
    reg.register("c", object(), "a", "t1")
    reg.register("d", object(), None, "t2")
    assert {r.agent_id for r in reg.children_of("a")} == {"b", "c"}
    assert reg.parent_of("b").agent_id == "a"
    assert [r.agent_id for r in reg.siblings_of("b")] == ["c"]
    assert {r.agent_id for r in reg.agents_for_task("t1")} == {"a", "b", "c"}
    with pytest.raises(Exception):
        reg.register("a", object(), None, "t1")


def test_spawn_dismiss_race_leaves_no_orphan():
    """The spawn/dismiss race (reference core.ex:213-220, spawn.ex:76-106):
    a parent's async spawn is in flight when the parent's tree is torn
    down. Whichever side wins, the registry must end empty — a child that
    escaped the dismissal BFS gets reaped by the spawn task itself."""
    async def main():
        release = asyncio.Event()

        class SlowSupervisor(AgentSupervisor):
            async def start_agent(self, cfg, *a, **kw):
                if cfg.agent_id != "agent-root":
                    # hold the child's startup until dismissal is underway
                    await release.wait()
                return await super().start_agent(cfg, *a, **kw)

        backend = scripted(
            j("spawn_child", spawn_params()),
            j("wait", {}))
        deps = AgentDeps.for_tests(backend)
        sup = SlowSupervisor(deps)
        root = await sup.start_agent(root_config())
        root.post({"type": "user_message", "content": "go", "from": "user"})
        # wait for the spawn action to be dispatched (pending background task)
        await until(lambda: any(
            d.get("action") == "spawn_child" for d in decisions(root)))
        # dismissal starts while the child's startup is parked
        teardown = asyncio.create_task(
            sup.terminate_tree("agent-root", by="test", reason="race"))
        await asyncio.sleep(0.05)
        release.set()
        await teardown
        # give the spawn task time to observe the dismissal and reap
        await until(lambda: not deps.registry.all(), timeout=10)

    run(main())


def test_spawn_failure_retries_then_notifies_parent(monkeypatch):
    """Reference spawn.ex:412-433 + :319-331 parity: when the background
    spawn task keeps failing, it retries SPAWN_MAX_RETRIES times with
    backoff and then posts spawn_failed to the parent — whose next
    consensus cycle sees the failure (rendered as "Spawning child ...
    FAILED: <reason>. You may retry or re-plan.") — and the child never
    registers."""
    from quoracle_tpu.actions import executors as ex

    monkeypatch.setattr(ex, "SPAWN_RETRY_DELAY_S", 0.01)
    def respond(r):
        joined = "\n".join(str(m.get("content", "")) for m in r.messages)
        if "FAILED: RuntimeError: child boom" in joined:
            return j("todo", {"items": [{"task": "saw-spawn-failure"}]})
        if '"agent_id"' in joined:                # spawn result ack
            return WAIT_FOREVER
        return j("spawn_child", spawn_params())

    async def main():
        backend = MockBackend(respond=respond)
        deps, sup = make_env(backend)
        root = await sup.start_agent(root_config())
        calls = []
        orig = sup.start_agent

        async def failing(cfg, *a, **k):
            calls.append(cfg.agent_id)
            raise RuntimeError("child boom")

        sup.start_agent = failing
        root.post({"type": "user_message", "content": "please spawn",
                   "from": "user"})
        # the parent's reaction to spawn_failed is observable as a todo
        await until(lambda: any("saw-spawn-failure" in str(t)
                                for t in root.ctx.todos), timeout=20)
        assert len(calls) == ex.SPAWN_MAX_RETRIES
        # the failed child never registered anywhere
        assert all(deps.registry.lookup(cid) is None for cid in calls)
        sup.start_agent = orig
        await sup.terminate_tree(root.agent_id, by="test", reason="done")
    asyncio.run(asyncio.wait_for(main(), 60))
