"""Mesh/sharding: tp×dp specs produce identical results to single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from quoracle_tpu.models.config import get_model_config
from quoracle_tpu.models.generate import GenerateEngine
from quoracle_tpu.models.tokenizer import ByteTokenizer
from quoracle_tpu.models.transformer import forward, init_cache, init_params
from quoracle_tpu.parallel.mesh import (
    cache_spec, data_spec, make_mesh, param_specs, shard_params,
)


def test_make_mesh_shapes(eight_devices):
    mesh = make_mesh(n_devices=8, tp=4)
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    mesh = make_mesh(n_devices=8)
    assert dict(mesh.shape) == {"dp": 1, "tp": 8}


def test_param_specs_match_param_tree():
    cfg = get_model_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    specs = param_specs(cfg)
    # Same tree structure => tree.map succeeds.
    jax.tree.map(lambda p, s: None, params, specs,
                 is_leaf=lambda x: isinstance(x, P))


def test_sharded_forward_matches_single_device(eight_devices):
    """The tp-sharded forward must be numerically identical (fp32 CPU) to the
    unsharded one — GSPMD inserts collectives, math unchanged."""
    cfg = get_model_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(16)[None, :], (4, 16)).astype(jnp.int32)

    def run(params, cache):
        logits, _ = forward(params, cfg, toks, pos, cache,
                            jnp.zeros((4,), jnp.int32),
                            jnp.full((4,), 16, jnp.int32))
        return logits

    base = run(params, init_cache(cfg, 4, 16, dtype=jnp.float32))

    mesh = make_mesh(n_devices=8, tp=2)
    sharded_params = shard_params(params, mesh, cfg)
    cache = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, cache_spec(cfg, mesh)))
        if x.ndim == 5 else jax.device_put(x, NamedSharding(mesh, P("dp"))),
        init_cache(cfg, 4, 16, dtype=jnp.float32))
    with jax.sharding.set_mesh(mesh):
        sharded = jax.jit(run)(sharded_params, cache)
    np.testing.assert_allclose(np.asarray(base), np.asarray(sharded),
                               rtol=1e-5, atol=1e-5)


def test_dryrun_multichip_runs():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    # Compile-check only (lower+compile, no execute — llama-1b on CPU is slow).
    jax.jit(fn).lower(*args).compile()
