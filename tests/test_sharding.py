"""Mesh/sharding: tp×dp specs produce identical results to single-device."""

import time
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from quoracle_tpu.models.config import get_model_config
from quoracle_tpu.models.generate import GenerateEngine
from quoracle_tpu.models.tokenizer import ByteTokenizer
from quoracle_tpu.models.transformer import forward, init_cache, init_params
from quoracle_tpu.parallel.mesh import (
    cache_spec, data_spec, make_mesh, param_specs, shard_params,
)


def test_make_mesh_shapes(eight_devices):
    mesh = make_mesh(n_devices=8, tp=4)
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    mesh = make_mesh(n_devices=8)
    assert dict(mesh.shape) == {"dp": 1, "tp": 8}


def test_param_specs_match_param_tree():
    cfg = get_model_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    specs = param_specs(cfg)
    # Same tree structure => tree.map succeeds.
    jax.tree.map(lambda p, s: None, params, specs,
                 is_leaf=lambda x: isinstance(x, P))


def test_sharded_forward_matches_single_device(eight_devices):
    """The tp-sharded forward must be numerically identical (fp32 CPU) to the
    unsharded one — GSPMD inserts collectives, math unchanged."""
    cfg = get_model_config("tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(16)[None, :], (4, 16)).astype(jnp.int32)

    def run(params, cache):
        logits, _ = forward(params, cfg, toks, pos, cache,
                            jnp.zeros((4,), jnp.int32),
                            jnp.full((4,), 16, jnp.int32))
        return logits

    base = run(params, init_cache(cfg, 4, 16, dtype=jnp.float32))

    mesh = make_mesh(n_devices=8, tp=2)
    sharded_params = shard_params(params, mesh, cfg)
    cache = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, cache_spec(cfg, mesh)))
        if x.ndim == 5 else jax.device_put(x, NamedSharding(mesh, P("dp"))),
        init_cache(cfg, 4, 16, dtype=jnp.float32))
    with jax.sharding.set_mesh(mesh):
        sharded = jax.jit(run)(sharded_params, cache)
    np.testing.assert_allclose(np.asarray(base), np.asarray(sharded),
                               rtol=1e-5, atol=1e-5)


def test_dryrun_multichip_runs():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    # Compile-check only (lower+compile, no execute — llama-1b on CPU is slow).
    jax.jit(fn).lower(*args).compile()


# ---------------------------------------------------------------------------
# Sharded SERVING (round 2): tp-sharded engine generate == single-device,
# sub-mesh pool partition, overlapped members through TPUBackend.
# ---------------------------------------------------------------------------

def test_tp_sharded_generate_matches_single_device(eight_devices):
    from quoracle_tpu.parallel.mesh import make_mesh
    cfg = get_model_config("xla:tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tok = ByteTokenizer()
    prompts = [tok.encode("hello sharded world", add_bos=True),
               tok.encode("a", add_bos=True),
               tok.encode("the quick brown fox", add_bos=True)]

    plain = GenerateEngine(cfg, params, tok, max_seq=256,
                           prompt_buckets=(32, 64))
    mesh = make_mesh(2, tp=2, devices=eight_devices[:2])
    sharded = GenerateEngine(cfg, params, tok, max_seq=256,
                             prompt_buckets=(32, 64), mesh=mesh)
    # greedy → rng-independent; logits must agree across shardings
    a = plain.generate(prompts, temperature=0.0, max_new_tokens=16)
    b = sharded.generate(prompts, temperature=0.0, max_new_tokens=16)
    assert [r.token_ids for r in a] == [r.token_ids for r in b]


def test_tp_with_dp_sharded_generate(eight_devices):
    from quoracle_tpu.parallel.mesh import make_mesh
    cfg = get_model_config("xla:tiny")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    tok = ByteTokenizer()
    prompts = [tok.encode(f"row {i}", add_bos=True) for i in range(4)]
    plain = GenerateEngine(cfg, params, tok, max_seq=256, prompt_buckets=(32,))
    mesh = make_mesh(4, tp=2, devices=eight_devices[:4])  # dp=2 x tp=2
    sharded = GenerateEngine(cfg, params, tok, max_seq=256,
                             prompt_buckets=(32,), mesh=mesh)
    a = plain.generate(prompts, temperature=0.0, max_new_tokens=8)
    b = sharded.generate(prompts, temperature=0.0, max_new_tokens=8)
    assert [r.token_ids for r in a] == [r.token_ids for r in b]


def test_pool_submeshes_partition(eight_devices):
    from quoracle_tpu.parallel.mesh import pool_submeshes
    meshes = pool_submeshes(3, devices=eight_devices)
    assert len(meshes) == 3
    # 8 devices / 3 members -> 2 each, no overlap among the first three
    used = [d for m in meshes for d in m.devices.flat]
    assert len(set(used)) == 6
    for m in meshes:
        assert int(np.prod(list(m.shape.values()))) == 2


def test_backend_overlapped_members_on_submeshes(eight_devices):
    """Full pool query across tp-sharded members running concurrently —
    results must match the sequential single-device path."""
    from quoracle_tpu.models.runtime import QueryRequest, TPUBackend
    from quoracle_tpu.parallel.mesh import pool_submeshes
    pool = ["xla:tiny", "xla:tiny-gemma"]
    msgs = [{"role": "user", "content": "pick an action"}]
    reqs = [QueryRequest(s, msgs, temperature=0.0, max_tokens=8)
            for s in pool for _ in range(2)]

    seq_backend = TPUBackend(pool=pool, overlap=False)
    par_backend = TPUBackend(pool=pool,
                             submeshes=pool_submeshes(2, devices=eight_devices,
                                                      tp=2),
                             overlap=True)
    a = seq_backend.query(reqs)
    b = par_backend.query(reqs)
    assert [r.ok for r in a] == [r.ok for r in b] == [True] * 4
    assert [r.text for r in a] == [r.text for r in b]


def test_member_batcher_coalesces_concurrent_rounds():
    """Baton batching: concurrent query() calls for the same member merge
    into fewer generate() calls (bench config 3's 2.3x throughput win,
    made available to real agent trees)."""
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from quoracle_tpu.models.runtime import QueryRequest, TPUBackend

    backend = TPUBackend(["xla:tiny"])
    engine = backend.engines["xla:tiny"]
    batch_sizes = []
    orig = engine.generate
    gate = threading.Event()

    def slow_generate(prompts, **kw):
        batch_sizes.append(len(prompts))
        if len(batch_sizes) == 1:
            gate.set()          # signal: the baton holder is inside
            time.sleep(0.5)     # let the other callers enqueue
        return orig(prompts, **kw)

    engine.generate = slow_generate

    def one_round(agent):
        return backend.query([QueryRequest(
            "xla:tiny", [{"role": "user", "content": f"round {agent}"}],
            temperature=0.0, max_tokens=4, session_id=f"agent-{agent}")])

    with ThreadPoolExecutor(max_workers=3) as ex:
        f0 = ex.submit(one_round, 0)
        gate.wait(timeout=30)             # holder is mid-generate
        f1 = ex.submit(one_round, 1)
        f2 = ex.submit(one_round, 2)
        all_res = [f.result(timeout=120) for f in (f0, f1, f2)]

    for res in all_res:
        assert res[0].ok, res[0].error
    # rounds 1+2 queued while 0 served -> drained as ONE merged batch
    assert batch_sizes[0] == 1
    assert max(batch_sizes) >= 2
    assert sum(batch_sizes) == 3
    # sessions stored per agent despite the merge
    assert all(engine.sessions.get(f"agent-{a}") is not None
               for a in range(3))


def test_tp_sharded_direct_paged_paths_match_gather(eight_devices):
    """Mesh engines must run the ragged paged kernels per-tp-shard via
    shard_map instead of silently falling back to gather (VERDICT r4
    item 3): direct decode + direct prefill on a tp=2 mesh produce the
    same greedy tokens as the single-device gather path, across a
    session-resumed refinement round with a sessionless neighbor row."""
    from quoracle_tpu.parallel.mesh import make_mesh
    cfg = get_model_config("xla:tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tok = ByteTokenizer()

    def run(eng):
        pa = tok.encode("user: compare sharded paged paths", add_bos=True)
        pb = tok.encode("user: sessionless neighbor", add_bos=True)
        r = eng.generate([pa, pb], temperature=0.0, max_new_tokens=8,
                         session_ids=["s", None])
        pa2 = pa + r[0].token_ids + tok.encode(" refine")[0:]
        r2 = eng.generate([pa2, pb], temperature=0.0, max_new_tokens=8,
                          session_ids=["s", None])
        return [x.token_ids for x in r + r2]

    plain = GenerateEngine(cfg, params, tok, max_seq=256,
                           prompt_buckets=(32, 64))
    plain._force_gather_decode = True

    mesh = make_mesh(2, tp=2, devices=eight_devices[:2])
    direct = GenerateEngine(cfg, params, tok, max_seq=256,
                            prompt_buckets=(32, 64), mesh=mesh)
    assert direct._paged_shard is not None
    direct.direct_decode_min_tokens = 0
    direct.direct_prefill_min_tokens = 0
    want, got = run(plain), run(direct)
    assert got == want


def test_tp_dp_sharded_direct_decode_matches(eight_devices):
    """dp×tp mesh: batch rides dp, heads ride tp, kernels per-shard."""
    from quoracle_tpu.parallel.mesh import make_mesh
    cfg = get_model_config("xla:tiny")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    tok = ByteTokenizer()
    prompts = [tok.encode(f"row {i} with some content", add_bos=True)
               for i in range(4)]
    sids = [f"s{i}" for i in range(4)]

    plain = GenerateEngine(cfg, params, tok, max_seq=256,
                           prompt_buckets=(32, 64))
    plain._force_gather_decode = True
    mesh = make_mesh(4, tp=2, devices=eight_devices[:4])  # dp=2 x tp=2
    direct = GenerateEngine(cfg, params, tok, max_seq=256,
                            prompt_buckets=(32, 64), mesh=mesh)
    direct.direct_decode_min_tokens = 0
    direct.direct_prefill_min_tokens = 0
    a = plain.generate(prompts, temperature=0.0, max_new_tokens=8,
                       session_ids=sids)
    b = direct.generate(prompts, temperature=0.0, max_new_tokens=8,
                        session_ids=sids)
    assert [r.token_ids for r in a] == [r.token_ids for r in b]
