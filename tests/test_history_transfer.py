"""HistoryTransfer: runtime model-pool switching.

Parity with the reference's HistoryTransfer + Core.switch_model_pool
(reference lib/quoracle/agent/history_transfer.ex, core.ex:115-127,257-263):
new pool members inherit the largest fitting old history (condensed if
nothing fits), ACE is re-keyed, old sessions drop, and the switch survives a
persistence restore.
"""

import asyncio
import json
import time

from quoracle_tpu.agent import AgentConfig, AgentDeps, AgentSupervisor
from quoracle_tpu.context.history import (
    DECISION, USER, AgentContext, HistoryEntry, Lesson,
)
from quoracle_tpu.context.history_transfer import transfer_histories
from quoracle_tpu.context.reflector import Reflection
from quoracle_tpu.context.token_manager import TokenManager
from quoracle_tpu.models.runtime import MockBackend
from quoracle_tpu.persistence import Database, Persistence, TaskManager
from quoracle_tpu.persistence.store import PersistentSecretStore

POOL = MockBackend.DEFAULT_POOL
NEW_POOL = ["mock:new-model-a", "mock:new-model-b"]


def j(action, params=None, wait=False):
    return json.dumps({"action": action, "params": params or {},
                       "reasoning": "test", "wait": wait})


def reflect_stub(model_spec, entries):
    return Reflection(summary_text=f"[summary of {len(entries)} entries]",
                      lessons=[], state=[])


def char_tm(limits):
    """1 token per 4 chars; per-model windows from ``limits``."""
    return TokenManager(lambda spec, text: max(1, len(text) // 4),
                        context_limit_fn=lambda spec: limits[spec])


async def until(cond, timeout=10.0, interval=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        await asyncio.sleep(interval)
    raise AssertionError("condition not met within timeout")


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# Pure transfer semantics
# ---------------------------------------------------------------------------

def test_largest_fitting_history_is_chosen():
    ctx = AgentContext()
    # old-a: large history; old-b: small one
    ctx.model_histories["old-a"] = [
        HistoryEntry(kind=USER, content="x" * 400) for _ in range(5)]
    ctx.model_histories["old-b"] = [HistoryEntry(kind=USER, content="short")]
    ctx.context_lessons["old-a"] = [Lesson(type="factual", content="A fact")]
    ctx.model_states["old-a"] = ["state summary"]

    limits = {"old-a": 100_000, "old-b": 100_000, "new-1": 100_000}
    tm = char_tm(limits)
    report = transfer_histories(
        ctx, ["old-a", "old-b"], ["new-1"], tm, reflect_stub,
        output_limit_fn=lambda spec: 4096)

    assert report.source_for["new-1"] == "old-a"
    assert len(ctx.model_histories["new-1"]) == 5
    # ACE re-keyed from the same source
    assert ctx.context_lessons["new-1"][0].content == "A fact"
    assert ctx.model_states["new-1"] == ["state summary"]
    # old keys dropped
    assert set(ctx.model_histories) == {"new-1"}
    assert sorted(report.dropped_models) == ["old-a", "old-b"]


def test_nonfitting_history_condenses_until_fits():
    ctx = AgentContext()
    # 30 entries x 100 tokens = 3000 tokens; new model window 2000 with
    # output_limit 500 -> floor 500 -> fits only below ~1470 tokens.
    ctx.model_histories["old-a"] = [
        HistoryEntry(kind=USER, content="y" * 400) for _ in range(30)]
    limits = {"old-a": 100_000, "new-1": 2000}
    tm = char_tm(limits)
    report = transfer_histories(
        ctx, ["old-a"], ["new-1"], tm, reflect_stub,
        output_limit_fn=lambda spec: 500)

    assert report.condensed.get("new-1")
    tokens = tm.history_tokens("new-1", ctx.model_histories["new-1"])
    assert tm.dynamic_max_tokens("new-1", tokens, 500) is not None
    # condensation left a summary entry at the head
    assert ctx.model_histories["new-1"][0].kind == "summary"


def test_kept_model_retains_its_own_history():
    ctx = AgentContext()
    ctx.model_histories["shared"] = [HistoryEntry(kind=USER, content="mine")]
    ctx.model_histories["old-b"] = [
        HistoryEntry(kind=USER, content="w" * 4000)]
    limits = {"shared": 100_000, "old-b": 100_000, "new-1": 100_000}
    tm = char_tm(limits)
    transfer_histories(
        ctx, ["shared", "old-b"], ["shared", "new-1"], tm, reflect_stub,
        output_limit_fn=lambda spec: 4096)
    # the kept model keeps its own (small) history, not the largest
    assert ctx.model_histories["shared"][0].content == "mine"
    # the new model inherits the largest
    assert ctx.model_histories["new-1"][0].content == "w" * 4000
    assert "old-b" not in ctx.model_histories


# ---------------------------------------------------------------------------
# Agent-level switch
# ---------------------------------------------------------------------------

class DropRecordingBackend(MockBackend):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.dropped_sessions = []

    def drop_session(self, session_id, model_specs=None):
        self.dropped_sessions.append((session_id, model_specs))


def test_switch_model_pool_preserves_context_and_drops_sessions():
    async def main():
        backend = DropRecordingBackend(
            scripts={m: [j("todo", {"items": [{"task": "t", "done": False}]})]
                     for m in POOL + NEW_POOL},
            respond=lambda r: j("wait", {}))
        deps = AgentDeps.for_tests(backend)
        sup = AgentSupervisor(deps)
        core = await sup.start_agent(AgentConfig(
            agent_id="agent-switch", task_id="task-1",
            model_pool=list(POOL)))
        core.post({"type": "user_message", "content": "do something",
                   "from": "user"})
        await until(lambda: any(
            e.kind == DECISION for e in core.ctx.history(POOL[0])))

        core.post({"type": "switch_model_pool", "model_pool": list(NEW_POOL)})
        await until(lambda: core.config.model_pool == NEW_POOL)

        # context preserved: the new models carry the old conversation
        for m in NEW_POOL:
            kinds = [e.kind for e in core.ctx.history(m)]
            assert USER in kinds and DECISION in kinds
        assert set(core.ctx.model_histories) == set(NEW_POOL)
        # resident KV sessions dropped for exactly the changed members
        # (old pool removed + new members that inherited a history)
        assert len(backend.dropped_sessions) == 1
        sid, specs = backend.dropped_sessions[0]
        assert sid == "agent-switch"
        assert set(specs) == set(POOL) | set(NEW_POOL)
        # consensus engine now queries the new pool
        assert core.engine.config.model_pool == NEW_POOL
        n_before = len(backend.calls)
        core.post({"type": "user_message", "content": "again", "from": "u"})
        await until(lambda: len(backend.calls) > n_before)
        # every post-switch query targets the new pool only
        assert {c.model_spec for c in backend.calls[n_before:]} <= set(NEW_POOL)
        await sup.terminate_agent("agent-switch")
    run(main())


def test_switch_survives_pause_and_restore():
    async def main():
        db = Database(":memory:", encryption_key="k" * 16)
        store = Persistence(db)
        backend = MockBackend(
            scripts={m: [j("todo", {"items": [{"task": "x", "done": False}]})]
                     for m in POOL + NEW_POOL},
            respond=lambda r: j("wait", {}))
        deps = AgentDeps.for_tests(backend,
                                   secrets=PersistentSecretStore(db))
        deps.persistence = store
        sup = AgentSupervisor(deps)
        tm = TaskManager(deps, store)
        task_id, root = await tm.create_task(
            "switch test", model_pool=list(POOL))
        root.post({"type": "user_message", "content": "go", "from": "user"})
        await until(lambda: any(
            e.kind == DECISION for e in root.ctx.history(POOL[0])))

        root.post({"type": "switch_model_pool", "model_pool": list(NEW_POOL)})
        await until(lambda: root.config.model_pool == NEW_POOL)
        await tm.pause_task(task_id)

        # restore into a fresh stack sharing the same DB
        deps2 = AgentDeps.for_tests(backend,
                                    secrets=PersistentSecretStore(db))
        deps2.persistence = store
        sup2 = AgentSupervisor(deps2)
        tm2 = TaskManager(deps2, store)
        n = await tm2.restore_task(task_id)
        assert n >= 1
        restored = deps2.registry.agents_for_task(task_id)[0].core
        # the switch persisted: restored agent runs the NEW pool with the
        # transferred history
        assert restored.config.model_pool == NEW_POOL
        for m in NEW_POOL:
            kinds = [e.kind for e in restored.ctx.history(m)]
            assert DECISION in kinds
        await tm2.pause_task(task_id)
    run(main())
