"""Tiered KV (serving/kvtier.py, ISSUE 7): host offload, session
hibernation, and the restart-surviving disk prefix store.

Covers the subsystem's acceptance bar end to end:
  * temp-0 BIT-EQUALITY of a hibernate→restore session against one that
    never left HBM (greedy and grammar-constrained rows);
  * COW/shared-page refcount integrity across demote/restore — demoting
    a donor must not disturb adopters or the radix tree, and a restored
    session diverging must still COW-swap;
  * kill-and-restart: a NEW engine over the same disk dir serves prefix
    hits from its predecessor's persisted blocks, and checksum-rejected
    corrupt entries are skipped (and unlinked), never served;
  * host-budget LRU eviction with prefix blocks spilling to disk;
  * the prefetch hook (engine.prefetch_session + ContinuousBatcher
    submit + backend.prefetch_sessions);
  * the QoS headroom signal counting demotable pages as reclaimable;
  * the formerly silent SessionStore.alloc drift branch now counting
    and flight-recording (ISSUE 7 satellite);
  * pool_sizing's per-tier capacity rows (ISSUE 7 satellite);
  * /api/kv + telemetry exposition.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quoracle_tpu.models.config import get_model_config
from quoracle_tpu.models.generate import (
    GenerateEngine, SessionStore, _Session,
)
from quoracle_tpu.models.tokenizer import ByteTokenizer
from quoracle_tpu.models.transformer import init_params
from quoracle_tpu.serving.kvtier import DiskPrefixStore, TierManager

CFG = get_model_config("xla:tiny")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def make_engine(**kw):
    return GenerateEngine(CFG, PARAMS, ByteTokenizer(), max_seq=512,
                          prompt_buckets=(32, 64, 128, 256), **kw)


def enc(text):
    return ByteTokenizer().encode(text, add_bos=True)


def hibernate_all(engine):
    """Force the eviction ladder over every resident session: demand all
    usable pages (no protected keys), then release them."""
    st = engine.sessions
    with engine._paged_lock:
        with st.lock:
            got = st.alloc(st.n_pages - 1)
            assert got is not None
            st._release(got)


SYS = "system: " + "policy rules apply here. " * 8    # > 1 page of 128


# ---------------------------------------------------------------------------
# Hibernate → restore bit-equality
# ---------------------------------------------------------------------------

def test_hibernate_restore_greedy_bit_equal():
    tok = ByteTokenizer()
    p1 = enc(SYS + " task: count to five.")
    ctl = make_engine()
    a1 = ctl.generate([p1], temperature=0.0, max_new_tokens=24,
                      session_ids=["s"])
    p2 = p1 + a1[0].token_ids + tok.encode(" continue")
    a2 = ctl.generate([p2], temperature=0.0, max_new_tokens=24,
                      session_ids=["s"])

    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    b1 = eng.generate([p1], temperature=0.0, max_new_tokens=24,
                      session_ids=["s"])
    assert b1[0].token_ids == a1[0].token_ids
    hibernate_all(eng)
    assert eng.sessions.get("s") is None
    assert tier.has_session("s")
    assert tier.demoted_sessions == 1
    # the splice layer still sees the conversation ids while hibernated
    assert eng.session_tokens("s") is not None
    b2 = eng.generate([p2], temperature=0.0, max_new_tokens=24,
                      session_ids=["s"])
    assert b2[0].token_ids == a2[0].token_ids
    assert tier.restored_sessions == 1
    # restore means PAGE-IN, not re-prefill: the cached-token count of
    # the resumed round matches the never-hibernated control exactly
    assert b2[0].n_cached_tokens == a2[0].n_cached_tokens > 0


def test_hibernate_restore_constrained_bit_equal():
    enum = ("wait", "send_message", "todo")
    p1 = enc(SYS + ' respond with an action json.')
    ctl = make_engine()
    a1 = ctl.generate([p1], temperature=0.0, max_new_tokens=48,
                      session_ids=["s"], constrain_json=[True],
                      action_enums=[enum])
    p2 = p1 + a1[0].token_ids + enc("again")[1:]
    a2 = ctl.generate([p2], temperature=0.0, max_new_tokens=48,
                      session_ids=["s"], constrain_json=[True],
                      action_enums=[enum])

    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    b1 = eng.generate([p1], temperature=0.0, max_new_tokens=48,
                      session_ids=["s"], constrain_json=[True],
                      action_enums=[enum])
    assert b1[0].token_ids == a1[0].token_ids
    hibernate_all(eng)
    b2 = eng.generate([p2], temperature=0.0, max_new_tokens=48,
                      session_ids=["s"], constrain_json=[True],
                      action_enums=[enum])
    assert b2[0].token_ids == a2[0].token_ids
    assert tier.restored_sessions == 1


def test_restore_failure_falls_back_to_prefill():
    """A hibernated session whose restore cannot get pages re-prefills
    (correctness never depends on the tier) and the stale host copy is
    discarded at store-back."""
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " task A")
    ctl = make_engine()
    a1 = ctl.generate([p1], temperature=0.0, max_new_tokens=16,
                      session_ids=["s"])
    b1 = eng.generate([p1], temperature=0.0, max_new_tokens=16,
                      session_ids=["s"])
    hibernate_all(eng)
    # sabotage: empty the free list with a fake resident hog the ladder
    # cannot demote past (protect it at restore time via direct call)
    st = eng.sessions
    with st.lock:
        hog = st.alloc(len(st._free))
        assert hog
    with eng._paged_lock:
        assert tier.restore_session("s") is None   # unattainable
    assert tier.restore_failures == 1
    with st.lock:
        st._release(hog)
    # generate still answers correctly (restore now succeeds — pages are
    # back; equality with the control is the invariant either way)
    b2 = eng.generate([p1], temperature=0.0, max_new_tokens=16,
                      session_ids=["s"])
    assert b2[0].token_ids == a1[0].token_ids == b1[0].token_ids


# ---------------------------------------------------------------------------
# COW / shared-page refcount integrity across demote/restore
# ---------------------------------------------------------------------------

def test_shared_refcounts_survive_demote_restore():
    """Demoting a session whose prefix pages the radix tree (and an
    adopter) still reference must not free or corrupt those pages; the
    restored session gets FRESH pages and a later divergence COW-swaps
    exactly like an always-resident one."""
    tok = ByteTokenizer()
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    st = eng.sessions
    p_donor = enc(SYS + " donor task")
    d1 = eng.generate([p_donor], temperature=0.0, max_new_tokens=16,
                      session_ids=["donor"])
    donor_pages = list(st.get("donor").pages)
    # adopter shares the cached page-aligned SYS prefix
    p_adopt = enc(SYS + " adopter goes elsewhere")
    a1 = eng.generate([p_adopt], temperature=0.0, max_new_tokens=16,
                      session_ids=["adopter"])
    assert a1[0].n_cached_tokens >= st.page
    shared = [p for p in st.get("adopter").pages if p in donor_pages]
    assert shared, "adopter did not share the donor's prefix pages"
    with st.lock:
        refs_before = {p: st._refs.get(p, 1) for p in shared}

    # hibernate ONLY the donor (protect the adopter through the ladder)
    with eng._paged_lock:
        with st.lock:
            sess = st._sessions.pop("donor")
            assert tier.demote_session("donor", sess)
            st._release(sess.pages)
    # shared pages survive with exactly one reference fewer; the
    # adopter's session and the cache still read them
    with st.lock:
        for p in shared:
            assert st._refs.get(p, 1) == refs_before[p] - 1
            assert p not in st._free
    oracle = make_engine()
    o1 = oracle.generate([p_adopt], temperature=0.0, max_new_tokens=16,
                         session_ids=["x"])
    a2 = eng.generate([p_adopt], temperature=0.0, max_new_tokens=16,
                      session_ids=["adopter2"])
    assert a2[0].token_ids == o1[0].token_ids

    # restore the donor and DIVERGE it mid-shared-page: the adopter's
    # prefix must stay byte-intact (COW at the write site still fires)
    p_div = p_donor[:st.page // 2] + tok.encode("DIVERGENT " * 8)
    d2 = eng.generate([p_div], temperature=0.0, max_new_tokens=16,
                      session_ids=["donor"])
    assert tier.restored_sessions == 1
    o2 = oracle.generate([p_adopt], temperature=0.0, max_new_tokens=16,
                         session_ids=["y"])
    a3 = eng.generate([p_adopt], temperature=0.0, max_new_tokens=16,
                      session_ids=["adopter3"])
    assert a3[0].token_ids == o2[0].token_ids
    od = oracle.generate([p_div], temperature=0.0, max_new_tokens=16,
                         session_ids=["z"])
    assert d2[0].token_ids == od[0].token_ids


def test_dropped_session_does_not_resurrect_from_host_tier():
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " ephemeral")
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    hibernate_all(eng)
    assert tier.has_session("s")
    eng.drop_session("s")
    assert not tier.has_session("s")
    assert eng.session_tokens("s") is None


# ---------------------------------------------------------------------------
# Disk prefix store: kill-and-restart warm start, checksum rejection
# ---------------------------------------------------------------------------

def test_disk_store_warm_starts_restarted_process(tmp_path):
    d = str(tmp_path / "kv")
    p1 = enc(SYS + " task one")
    # "process 1": serve traffic; store-back persists prefix blocks
    e1 = make_engine()
    t1 = e1.attach_tier(host_mb=64, disk_dir=d)
    r1 = e1.generate([p1], temperature=0.0, max_new_tokens=16,
                     session_ids=["a"])
    t1.flush_spills()          # disk writes are async (spill queue)
    files = glob.glob(os.path.join(d, "*", "*.npz"))
    assert files, "store-back persisted no prefix blocks"
    # oracle: tierless fresh engine
    rc = make_engine().generate([p1], temperature=0.0, max_new_tokens=16,
                                session_ids=["x"])
    # "process 2" (restart): brand-new engine + store, same disk dir
    e2 = make_engine()
    t2 = e2.attach_tier(host_mb=64, disk_dir=d)
    r2 = e2.generate([p1], temperature=0.0, max_new_tokens=16,
                     session_ids=["b"])
    assert r2[0].token_ids == rc[0].token_ids == r1[0].token_ids
    assert t2.restored_prefix_pages > 0, "no disk warm-start happened"
    assert r2[0].n_cached_tokens >= e2.sessions.page, \
        "restart prompt was not served from the warmed prefix cache"


def test_disk_store_corruption_under_concurrent_readers(tmp_path):
    """ISSUE 11 satellite: an entry corrupted while readers are
    mid-load must skip-unlink-degrade on every path — concurrent
    loaders never crash, never return poisoned KV (crc32 boundary),
    the file unlinks, and a warm-starting engine over the damaged
    store still serves BIT-IDENTICAL outputs by re-prefilling."""
    import threading

    d = str(tmp_path / "kv")
    p1 = enc(SYS + " concurrency victim")
    e1 = make_engine()
    t1 = e1.attach_tier(host_mb=64, disk_dir=d)
    r1 = e1.generate([p1], temperature=0.0, max_new_tokens=16,
                     session_ids=["a"])
    t1.flush_spills()
    files = glob.glob(os.path.join(d, "*", "*.npz"))
    assert files
    victim = files[0]
    key = os.path.basename(victim)[:-len(".npz")]
    store = t1.disk
    blk_tokens = None
    # recover the prefix the victim block stores: page-aligned prefixes
    # of the prompt, matched by content key
    for end in range(e1.sessions.page, len(p1) + 1, e1.sessions.page):
        if DiskPrefixStore.block_key([int(t) for t in p1[:end]]) == key:
            blk_tokens = [int(t) for t in p1[:end]]
            break
    assert blk_tokens is not None

    good = store.load(key, blk_tokens)
    assert good is not None               # sane before corruption

    # corrupt the payload in place, then hammer it from N readers at
    # once: every loader must see either None (corrupt path) — never
    # an exception, never wrong bytes
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        f.write(b"\xde\xad\xbe\xef" * 8)
    barrier = threading.Barrier(4)
    outcomes: list = []
    errors: list = []

    def reader():
        barrier.wait()
        try:
            outcomes.append(store.load(key, blk_tokens))
        except Exception as exc:          # noqa: BLE001 — the assertion
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    assert all(o is None for o in outcomes), \
        "a reader returned KV from a corrupted entry"
    assert store.corrupt >= 1
    assert not os.path.exists(victim), "corrupt entry was not unlinked"

    # degrade end-to-end: a fresh engine warm-starting over the
    # damaged store re-prefills and serves identical bits
    oracle = make_engine().generate([p1], temperature=0.0,
                                    max_new_tokens=16, session_ids=["x"])
    e2 = make_engine()
    e2.attach_tier(host_mb=64, disk_dir=d)
    r2 = e2.generate([p1], temperature=0.0, max_new_tokens=16,
                     session_ids=["b"])
    assert r2[0].token_ids == oracle[0].token_ids == r1[0].token_ids


def test_disk_store_skips_and_unlinks_corrupt_entries(tmp_path):
    d = str(tmp_path / "kv")
    p1 = enc(SYS + " task one")
    e1 = make_engine()
    t1 = e1.attach_tier(host_mb=64, disk_dir=d)
    e1.generate([p1], temperature=0.0, max_new_tokens=16,
                session_ids=["a"])
    t1.flush_spills()
    files = glob.glob(os.path.join(d, "*", "*.npz"))
    assert files
    victim = files[0]
    with open(victim, "r+b") as f:
        f.seek(100)
        f.write(b"\x00" * 64)
    rc = make_engine().generate([p1], temperature=0.0, max_new_tokens=16,
                                session_ids=["x"])
    e3 = make_engine()
    t3 = e3.attach_tier(host_mb=64, disk_dir=d)
    r3 = e3.generate([p1], temperature=0.0, max_new_tokens=16,
                     session_ids=["c"])
    # corrupt entry rejected, never served — output matches the oracle
    # via plain prefill, and the bad file was unlinked (the store-back
    # then re-persists a CLEAN block under the same content key)
    assert r3[0].token_ids == rc[0].token_ids
    assert t3.disk.corrupt >= 1
    assert t3.restored_prefix_pages == 0
    t3.flush_spills()          # the clean re-persist is async too
    fresh = DiskPrefixStore(d, os.path.basename(os.path.dirname(victim)))
    key = os.path.splitext(os.path.basename(victim))[0]
    if fresh.has(key):
        # the rewrite is clean: it loads (or it was unlinked entirely)
        toks = None
        with np.load(victim) as z:
            toks = z["tokens"].tolist()
        assert fresh.load(key, toks) is not None


def test_disk_store_round_trips_bfloat16(tmp_path):
    """Serving caches are bfloat16; npz round-trips extension dtypes as
    an opaque void dtype unless the store ships raw bytes + dtype name —
    regression for the silent-dtype-strip the CLI drive caught."""
    s = DiskPrefixStore(str(tmp_path), "sig", model="m")
    toks = list(range(128))
    k = (np.arange(2 * 128 * 2 * 16, dtype=np.float32)
         .reshape(2, 128, 2, 16).astype(jnp.bfloat16))
    v = (k * 2).astype(jnp.bfloat16)
    key = s.block_key(toks)
    assert s.save(key, toks, np.asarray(k), np.asarray(v))
    loaded = s.load(key, toks)
    assert loaded is not None
    lk, lv = loaded
    assert lk.dtype == jnp.bfloat16 and lv.dtype == jnp.bfloat16
    assert lk.tobytes() == np.asarray(k).tobytes()
    assert lv.tobytes() == np.asarray(v).tobytes()


def test_disk_store_rejects_token_mismatch(tmp_path):
    s = DiskPrefixStore(str(tmp_path), "sig", model="m")
    toks = list(range(128))
    k = np.ones((2, 128, 2, 16), np.float32)
    key = s.block_key(toks)
    assert s.save(key, toks, k, k * 2)
    assert s.load(key, toks) is not None
    # same key requested under different tokens (hash collision stand-in)
    # must be rejected, not served
    assert s.load(key, list(range(1, 129))) is None
    assert s.corrupt == 1


def test_disk_store_budget_prunes_oldest_and_touches_on_load(tmp_path):
    """REVIEW fix: the store is byte-bounded — a save that overflows the
    budget prunes oldest-mtime entries, and load() touches mtime so the
    order approximates LRU, not FIFO."""
    kk = np.ones((2, 16, 2, 8), np.float32)

    def toks(i):
        return [i * 1000 + j for j in range(16)]

    s = DiskPrefixStore(str(tmp_path), "sig", model="m")
    keys = []
    for i in range(6):
        key = s.block_key(toks(i))
        keys.append(key)
        assert s.save(key, toks(i), kk, kk)
        os.utime(s._path(key), (1_000_000 + i, 1_000_000 + i))
    per = os.path.getsize(s._path(keys[0]))
    s.budget_bytes = 3 * per + per // 2
    # loading key 0 touches it — despite the oldest write stamp it must
    # survive the prune below
    assert s.load(keys[0], toks(0)) is not None
    assert os.stat(s._path(keys[0])).st_mtime > 1_000_000 + 5
    key6 = s.block_key(toks(6))
    assert s.save(key6, toks(6), kk, kk)      # overflows -> prune
    assert s.pruned >= 1
    assert s.stats()["bytes"] <= s.budget_bytes
    assert s.has(keys[0]) and s.has(key6)     # touched + newest survive
    assert not s.has(keys[1])                 # coldest entry pruned
    # stats serves the incrementally-tracked size, not a fresh listdir
    st = s.stats()
    assert st["entries"] == sum(
        1 for f in os.listdir(s.dir) if f.endswith(".npz"))
    assert st["budget_bytes"] == s.budget_bytes


# ---------------------------------------------------------------------------
# extend_prefix refcount + poisoning regressions (REVIEW fixes)
# ---------------------------------------------------------------------------

def test_restored_prefix_pages_are_evictable(tmp_path):
    """REVIEW fix: a disk/host-restored prefix block must end up with
    the TREE as its only reference holder (like a store-back block after
    its session drops) — the old code kept alloc's base ref and pinned
    every restored page at refcount 2 forever."""
    d = str(tmp_path / "kv")
    p1 = enc(SYS + " task one")
    e1 = make_engine()
    t1 = e1.attach_tier(host_mb=64, disk_dir=d)
    e1.generate([p1], temperature=0.0, max_new_tokens=16,
                session_ids=["a"])
    t1.flush_spills()
    e2 = make_engine()
    t2 = e2.attach_tier(host_mb=64, disk_dir=d)
    e2.generate([p1], temperature=0.0, max_new_tokens=16,
                session_ids=["b"])
    assert t2.restored_prefix_pages > 0
    e2.drop_session("b")
    st = e2.sessions
    with st.lock:
        cached = list(st.prefix_cache._pages)
        assert cached
        for pg in cached:
            assert st._refs.get(pg, 1) == 1, \
                f"page {pg} pinned at refcount {st._refs.get(pg, 1)}"
        # and the tier ladder can actually reclaim them all
        freed = st.prefix_cache.evict(len(cached))
    assert freed == len(cached)


def test_extend_prefix_survives_alloc_evicting_matched_path():
    """REVIEW fix: st.alloc inside extend_prefix can strip the deepest
    node of the just-matched path (leaf-first eviction, and match_len
    bumps no LRU stamps). The restored block must never be inserted
    under the shorter re-walked path — that would label block j's KV
    with block j-1's tokens and serve wrong bytes at temp 0."""
    import jax.numpy as jnp

    from quoracle_tpu.serving.kvtier import _HostBlock
    page = 4
    store = SessionStore(max_tokens=4 * page, page=page)
    L, KV, HD = 2, 2, 4
    store.k = jnp.zeros((L, store.n_pages, page, KV, HD), jnp.float32)
    store.v = jnp.zeros_like(store.k)
    tier = TierManager(store, model="m", host_mb=1)
    store.tier = tier
    tokens = list(range(2 * page))

    def blk(depth):
        return np.full((L, page, KV, HD), float(depth), np.float32)

    # both blocks of the chain live in the host tier, content = depth
    tier.host.put_prefix(tier._block_key(tokens[:page]),
                         _HostBlock(tokens[:page], blk(1), blk(1)))
    tier.host.put_prefix(tier._block_key(tokens),
                         _HostBlock(tokens, blk(2), blk(2)))
    # seed the tree with block 0 as a refcount-1 leaf (tree-only ref)
    with store.lock:
        seed = store.alloc(1)
        store.k = store.k.at[:, seed[0]].set(1.0)
        assert store.prefix_cache.insert(tokens[:page], seed) == 1
        store._release(seed)            # tree keeps the only ref
        # hog the remaining free pages so the extend's alloc(1) must
        # evict — and the only evictable page is the matched leaf
        hog = store.alloc(len(store._free))
        assert hog
        tier.extend_prefix(tokens, len(tokens) + 1)
        # a pool this tight cannot hold the whole chain — that is fine;
        # what must NEVER happen is a node whose page holds another
        # depth's KV. The pre-fix code inserted the depth-2 block under
        # the depth-1 label after alloc stripped the matched leaf.
        depth_of = {}
        stack = [(store.prefix_cache._root, 0)]
        while stack:
            node, depth = stack.pop()
            for ch in node.children.values():
                depth_of[ch.page] = depth + 1
                stack.append((ch, depth + 1))
        for pg, depth in depth_of.items():
            got = np.asarray(jax.device_get(store.k[:, pg]))
            assert np.all(got == float(depth)), \
                f"page {pg} at depth {depth} holds wrong KV"
        # and page accounting stayed exact through the shrink/retry
        # dance: every usable page is free, cached, or hogged
        assert (len(store._free) + len(store.prefix_cache._pages)
                + len(hog)) == store.n_pages - 1
        store._release(hog)


# ---------------------------------------------------------------------------
# Host budget + disk spill
# ---------------------------------------------------------------------------

def test_host_budget_evicts_lru_and_spills_prefixes(tmp_path):
    store = SessionStore(max_tokens=8 * 4, page=4)
    tier = TierManager(store, model="m", host_mb=1,
                       disk_dir=str(tmp_path))
    store.tier = tier
    # budget of ~2 tiny blocks: force LRU churn
    blk = np.zeros((2, 4, 2, 4), np.float32)
    tier.host.budget_bytes = 3 * (2 * blk.nbytes)
    from quoracle_tpu.serving.kvtier import _HostBlock
    keys = []
    for i in range(5):
        toks = [100 * i + j for j in range(4)]
        key = tier._block_key(toks)
        keys.append(key)
        tier.host.put_prefix(key, _HostBlock(toks, blk + i, blk + i),
                             spill_fn=tier._spill_prefix_entry)
    assert tier.host.bytes <= tier.host.budget_bytes
    assert tier.host.evicted_prefixes == 2
    # evicted blocks landed on disk, checksummed (async writer)
    tier.flush_spills()
    for key in keys[:2]:
        assert tier.disk.has(key)
    for key in keys[2:]:
        assert key in tier.host.prefixes


def test_host_budget_drops_lru_sessions():
    store = SessionStore(max_tokens=8 * 4, page=4)
    tier = TierManager(store, model="m", host_mb=1)
    store.tier = tier
    from quoracle_tpu.serving.kvtier import _HostSession
    arr = np.zeros((2, 1, 4, 2, 4), np.float32)
    tier.host.budget_bytes = 2 * (2 * arr.nbytes)
    for i in range(4):
        tier.host.put_session(f"s{i}", _HostSession([i], 0, arr.copy(),
                                                    arr.copy()))
    assert tier.host.evicted_sessions == 2
    assert set(tier.host.sessions) == {"s2", "s3"}


# ---------------------------------------------------------------------------
# Prefetch hooks
# ---------------------------------------------------------------------------

def test_prefetch_restores_hibernated_session():
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " warm me")
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    hibernate_all(eng)
    assert eng.sessions.get("s") is None
    assert eng.prefetch_session("s") is True
    assert eng.sessions.get("s") is not None
    assert tier.restored_sessions == 1
    # idempotent: already-resident session is not restored twice
    assert eng.prefetch_session("s") is False


def test_prefetch_skips_busy_engine():
    eng = make_engine()
    eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " busy case")
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    hibernate_all(eng)
    with eng._paged_lock:          # simulate an in-flight paged call
        assert eng.prefetch_session("s") is False
    assert eng.prefetch_session("s") is True


def test_continuous_batcher_submit_prefetches():
    from quoracle_tpu.models.scheduler import ContinuousBatcher
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " via scheduler")
    ctl = make_engine()
    o1 = ctl.generate([p1], temperature=0.0, max_new_tokens=8,
                      session_ids=["s"])
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    hibernate_all(eng)
    cb = ContinuousBatcher(eng, chunk=8, max_slots=2)
    try:
        tok = ByteTokenizer()
        p2 = p1 + o1[0].token_ids + tok.encode(" go on")
        o2 = ctl.generate([p2], temperature=0.0, max_new_tokens=8,
                          session_ids=["s"])
        fut = cb.submit(p2, temperature=0.0, max_new_tokens=8,
                        session_id="s")
        got = fut.result(timeout=120)
        assert got.token_ids == o2[0].token_ids
        assert tier.restored_sessions == 1
    finally:
        cb.close()


def test_backend_prefetch_sessions():
    from quoracle_tpu.models.runtime import TPUBackend
    backend = TPUBackend(pool=["xla:tiny"], host_kv_mb=64)
    assert backend.kv_tiered
    eng = backend.engines["xla:tiny"]
    p1 = enc(SYS + " backend warm")
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["agent-1"])
    hibernate_all(eng)
    assert backend.prefetch_sessions("agent-1") == 1
    assert eng.sessions.get("agent-1") is not None
    assert backend.prefetch_sessions("agent-1") == 0


# ---------------------------------------------------------------------------
# QoS headroom: demotable pages count as reclaimable
# ---------------------------------------------------------------------------

def test_effective_headroom_counts_demotable_pages(monkeypatch):
    from quoracle_tpu.infra import resources
    from quoracle_tpu.models.runtime import TPUBackend
    backend = TPUBackend(pool=["xla:tiny"], host_kv_mb=64)
    eng = backend.engines["xla:tiny"]
    eng.generate([enc(SYS + " hold pages")], temperature=0.0,
                 max_new_tokens=8, session_ids=["s"])
    assert resources.reclaimable_kv_bytes(backend) > 0
    # fake a limit-reporting device so the fraction math is exercised
    monkeypatch.setattr(
        resources, "device_memory_stats",
        lambda: [{"device": 0, "bytes_in_use": 90, "bytes_limit": 100,
                  "peak_bytes_in_use": 0, "platform": "cpu",
                  "kind": "fake", "source": "test"}])
    frac = resources.effective_headroom_fraction(backend)
    assert frac is not None and frac > 0.1   # raw 0.1 + reclaimable
    # untiered backend: effective == raw
    untiered = TPUBackend(pool=["xla:tiny"], engines={"xla:tiny": eng})
    untiered_eng_tier, eng.sessions.tier = eng.sessions.tier, None
    try:
        assert resources.reclaimable_kv_bytes(untiered) == 0
        assert abs(resources.effective_headroom_fraction(untiered)
                   - 0.1) < 1e-9
    finally:
        eng.sessions.tier = untiered_eng_tier


def test_demotable_bytes_excludes_unreclaimable_pages():
    """REVIEW fix: the QoS headroom signal counts only pages the
    eviction ladder could actually free — victim-exclusive session
    pages plus strippable cache leaves. A page pinned by an in-flight
    adopter reference (acquire() without a registered session) is not
    reclaimable and must not be advertised as headroom."""
    eng = make_engine()
    tier = eng.attach_tier(host_mb=64)
    st = eng.sessions
    assert tier.demotable_bytes(1) == 0          # empty store
    eng.generate([enc(SYS + " hold pages")], temperature=0.0,
                 max_new_tokens=8, session_ids=["s"])
    with st.lock:
        base = st._attainable(list(st._sessions)) - len(st._free)
    assert 0 < base <= st.n_pages - 1 - st.free_pages()
    assert tier.demotable_bytes(1) == base
    pinned = [p for p in st.get("s").pages if p][0]
    st.acquire([pinned])                          # in-flight reader
    try:
        assert tier.demotable_bytes(1) == base - 1
    finally:
        st.release([pinned])
    assert tier.demotable_bytes(1) == base
    # still bounded by the remaining host budget
    tier.host.budget_bytes = tier.host.bytes      # zero headroom
    assert tier.demotable_bytes(1) == 0


# ---------------------------------------------------------------------------
# Satellite: the alloc drift branch is loud now
# ---------------------------------------------------------------------------

def test_alloc_drift_counts_and_flight_records(monkeypatch):
    from quoracle_tpu.infra.flightrec import FLIGHT
    from quoracle_tpu.infra.telemetry import KV_ALLOC_DRIFT_TOTAL
    store = SessionStore(max_tokens=4 * 4, page=4)
    store.model = "drifty"
    pages = store.alloc(2)
    store.put("a", _Session(tokens=list(range(8)), pages=pages))
    # force drift: attainability promises pages eviction can't deliver
    monkeypatch.setattr(store, "_attainable", lambda victims: 99)
    before = KV_ALLOC_DRIFT_TOTAL.value(model="drifty")
    assert store.alloc(10) is None
    assert KV_ALLOC_DRIFT_TOTAL.value(model="drifty") == before + 1
    events = [e for e in FLIGHT.snapshot()
              if e.get("kind") == "kv_alloc_drift"
              and e.get("model") == "drifty"]
    assert events and events[-1]["requested"] == 10


# ---------------------------------------------------------------------------
# Satellite: pool_sizing per-tier capacity
# ---------------------------------------------------------------------------

def test_pool_sizing_reports_tier_capacity():
    from quoracle_tpu.parallel.mesh import pool_sizing
    from quoracle_tpu.models.config import NORTH_STAR_POOL
    sizing = pool_sizing(NORTH_STAR_POOL, 8, host_kv_mb=4096,
                         disk_kv_gb=64.0)
    for m in sizing["members"]:
        tiers = m["tiers"]
        assert tiers["hbm_tokens"] == m["resident_kv_tokens"]
        assert tiers["hbm_pages"] == m["resident_kv_tokens"] // 128
        assert tiers["host_kv_mb"] == 4096
        assert tiers["host_kv_tokens"] > 0
        assert tiers["disk_kv_tokens"] > tiers["host_kv_tokens"]
    assert sizing["host_kv_mb_per_member"] == 4096
    # host tier capacity uses UNSHARDED bytes/token: it must not exceed
    # what the budget divided by the tp=1 rate allows
    from quoracle_tpu.models.config import get_model_config
    for m in sizing["members"]:
        cfg = get_model_config(f"xla:{m['model']}") \
            if not m["model"].startswith("xla:") else \
            get_model_config(m["model"])
        rate = cfg.kv_bytes_per_token(1, 2)
        assert m["tiers"]["host_kv_tokens"] == (4096 << 20) // rate
    # omitting the knobs keeps the tier block zeroed, not absent
    plain = pool_sizing(NORTH_STAR_POOL, 8)
    assert plain["members"][0]["tiers"]["host_kv_tokens"] == 0


# ---------------------------------------------------------------------------
# API + exposition
# ---------------------------------------------------------------------------

def test_kv_stats_and_prometheus_exposition():
    from quoracle_tpu.infra.telemetry import METRICS
    from quoracle_tpu.models.runtime import TPUBackend
    backend = TPUBackend(pool=["xla:tiny"], host_kv_mb=64)
    eng = backend.engines["xla:tiny"]
    eng.generate([enc(SYS + " stats")], temperature=0.0,
                 max_new_tokens=8, session_ids=["s"])
    hibernate_all(eng)
    eng.generate([enc(SYS + " stats")], temperature=0.0,
                 max_new_tokens=8, session_ids=["s"])
    stats = backend.kv_stats()
    assert stats["enabled"]
    m = stats["members"]["xla:tiny"]
    assert m["demoted_sessions"] >= 1
    assert m["restored_sessions"] >= 1
    assert m["hbm"]["pages"] == eng.sessions.n_pages
    text = METRICS.render_prometheus()
    assert "quoracle_kv_demotes_total" in text
    assert "quoracle_kv_restores_total" in text
    assert "quoracle_kv_restore_ms" in text
    assert 'kind="session"' in text


def test_api_kv_payload_shapes():
    """kv_payload over a MockBackend (no tiering) and the TPU backend —
    the endpoint must answer in both worlds."""
    from quoracle_tpu.models.runtime import MockBackend, TPUBackend

    class _FakeRuntime:
        def __init__(self, backend):
            self.backend = backend

    from quoracle_tpu.web.server import DashboardServer
    d = DashboardServer.__new__(DashboardServer)
    d.runtime = _FakeRuntime(MockBackend())
    payload = d.kv_payload()
    assert payload["enabled"] is False
    assert "counters" in payload

    backend = TPUBackend(pool=["xla:tiny"], host_kv_mb=64)
    d.runtime = _FakeRuntime(backend)
    payload = d.kv_payload()
    assert payload["enabled"] is True
    assert "xla:tiny" in payload["members"]


def test_kv_panel_renders():
    from quoracle_tpu.web.views import kv_panel
    assert kv_panel({"enabled": False}) == ""
    html = kv_panel({"enabled": True, "members": {"xla:tiny": {
        "hbm": {"pages": 10, "free_pages": 4, "used_pages": 5,
                "sessions": 2, "prefix_cache": {}},
        "host": {"bytes": 1 << 20, "budget_bytes": 64 << 20,
                 "sessions": 3, "prefix_blocks": 7},
        "disk": {"entries": 11, "corrupt_skipped": 0},
        "demoted_sessions": 5, "restored_sessions": 4,
    }}})
    assert "tiered KV" in html and "xla:tiny" in html and "11" in html


# ---------------------------------------------------------------------------
# Flight-recorder events
# ---------------------------------------------------------------------------

def test_demote_restore_flight_events():
    from quoracle_tpu.infra.flightrec import FLIGHT
    eng = make_engine()
    eng.attach_tier(host_mb=64)
    p1 = enc(SYS + " flight")
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    hibernate_all(eng)
    eng.generate([p1], temperature=0.0, max_new_tokens=8,
                 session_ids=["s"])
    kinds = [e["kind"] for e in FLIGHT.snapshot()]
    assert "kv_demote" in kinds
    assert "kv_restore" in kinds
