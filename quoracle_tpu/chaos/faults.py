"""Deterministic fault injection for the serving stack (ISSUE 11
tentpole, part a).

PRs 7–10 built the recovery paths — eviction-ladder demote/restore,
signature-checked KV handoff with replica-death re-placement, QoS shed
ladders, the lockdep sanitizer — but each was exercised only by
hand-built unit fixtures. This module makes hostile conditions a
first-class, SEEDED input: a :class:`FaultPlan` is armed on the
process-wide :data:`CHAOS` plane and the serving code's injection
points (threaded through existing seams as no-op-by-default hooks)
consult it on the hot path at the cost of one attribute read.

Determinism contract (the acceptance bar of the scenario harness):

* a plan carries an EXPLICIT seed and every fire decision is a pure
  function of ``(seed, point, key, n, rule)`` where ``n`` is the
  per-``(point, key)`` invocation counter — no wall-clock, no
  process-salted ``hash()``, no global RNG. Re-running the same traffic
  against the same seed fires the identical fault schedule, and the
  ``chaos_fault`` flight events prove it (chaos/invariants.py
  ``fault_schedule`` compares the ordered per-key tuples).
* ``key`` is the ctx field that names the independent stream (model for
  pool members, replica for cluster serves, "" otherwise), so threads
  serving DIFFERENT streams cannot perturb each other's schedules.

Injection points (the seams; all no-op while nothing is armed):

======================  =====================================  ==========
point                   seam                                   kinds
======================  =====================================  ==========
pool.member             TPUBackend._query_member_impl /        crash,
                        MockBackend.query                      slow,
                                                               garbage
sched.tick              ContinuousBatcher._loop (per tick)     demote,
                                                               delay
kvtier.restore          TierManager.restore_session            fail, delay
kvtier.disk_load        DiskPrefixStore.load (corrupts the     corrupt
                        FILE bytes so the crc32 boundary is
                        exercised end-to-end)
kvtier.scale_corrupt    DiskPrefixStore.load (flips a byte in  corrupt
                        an int8 entry's appended per-page
                        scale arrays — same crc boundary,
                        ISSUE 13)
compile.key             CompileRegistry.record (salts the      poison
                        shape key → ledger-level recompile
                        storm)
admission.signals       AdmissionController.refresh_signals    drop, delay
router.signals          ClusterRouter._load_score              drop
cluster.serve           ClusterPlane._delegate                 crash, slow
cluster.decode          ClusterPlane._decode_on (decode-       crash, slow
                        replica death mid-row → envelope
                        re-place)
handoff.export          KVHandoff.export                       fail
fabric.send             fabric/transport.Transport.request     drop, delay,
                        (per attempt — the bounded retry       corrupt
                        absorbs a flap; a corrupt frame is
                        rejected by the RECEIVER's crc
                        boundary end-to-end)
fabric.prefixd          fabric/prefixd.PrefixdClient           unavailable,
                        (fetch/publish degrade to local-       slow
                        only — warm-start becomes prefill,
                        never an error)
fleet.migrate           serving/fleet.FleetController._drain   crash, fail
                        (per session migration — a crash is
                        the draining replica dying with
                        sessions still aboard; a fail degrades
                        one session to re-prefill)
======================  =====================================  ==========

``crash`` kinds raise :class:`InjectedFault` out of ``fire()`` — a
STRUCTURED error naming point and key, so the recovery paths exercise
exactly the exception shape a real transport/device failure produces.
``slow``/``delay`` sleep (bounded by ``MAX_DELAY_S``) outside the plan
lock. Every other kind is returned as a :class:`Fault` directive for
the seam to interpret (corrupt the bytes, drop the signal, salt the
key), because only the seam owns the state being attacked.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import time
from typing import Any, Optional, Sequence

from quoracle_tpu.analysis.lockdep import named_lock

logger = logging.getLogger(__name__)

# hard ceiling on injected sleeps: chaos must bend latency, not wedge
# tier-1 or a canary
MAX_DELAY_S = 0.25

INJECTION_POINTS: dict = {
    "pool.member": "member crash / slow / garbage-output at the pool "
                   "runtime's per-member query entry",
    "sched.tick": "forced demote churn / tick delay in the continuous "
                  "batcher's decode loop",
    "kvtier.restore": "session restore failure / delay in the tier "
                      "ladder (degrades to re-prefill)",
    "kvtier.disk_load": "on-disk prefix entry corrupted before load — "
                        "the crc32 boundary must catch it",
    "kvtier.scale_corrupt": "int8 entry's per-page scale bytes flipped "
                            "on the restore path (ISSUE 13) — the same "
                            "crc boundary must reject it; a wrong "
                            "scale would silently rescale every token "
                            "of the page",
    "compile.key": "compile-cache key poisoning — every dispatch "
                   "ledgers as a fresh miss (recompile storm)",
    "admission.signals": "admission signal refresh dropped/delayed — "
                         "the shed ladder steers on stale data",
    "router.signals": "router-side replica signal snapshot dropped",
    "cluster.serve": "replica failure serving a delegated request",
    "cluster.decode": "decode-replica death mid-row, after the KV "
                      "handoff landed",
    "handoff.export": "prefill-side handoff export failure (cold "
                      "re-prefill degrade)",
    "fabric.send": "peer link fault per wire attempt — drop / delay / "
                   "corrupt-frame (the receiver's crc boundary rejects "
                   "it; bounded retry absorbs transient flaps)",
    "fabric.prefixd": "fleet prefix service unavailable / slow — the "
                      "read-through client degrades to local tiers "
                      "and cold prefill",
    "fleet.migrate": "replica death mid-drain (ISSUE 14) — fires per "
                     "session migration on the fleet controller's "
                     "drain path; a crash means the draining replica "
                     "died with sessions still aboard, which must "
                     "degrade to mark-failed + re-prefill, never "
                     "silent loss",
    "train.capture": "capture-plane record write dropped / corrupted / "
                     "crashed (ISSUE 19) — fires per capture batch on "
                     "the store's append path; serving must neither "
                     "block nor change a single output bit, and a "
                     "corrupt frame must be skipped-and-unlinked at "
                     "read like any disk rot",
    "train.promote": "draft hot-swap failure mid-promotion (ISSUE 19) "
                     "— fires per replica on the promotion rollout; a "
                     "crash means the fleet was left half-swapped, "
                     "which must roll back to the incumbent on every "
                     "replica with zero downtime (train_rollback "
                     "flight event present)",
}


class InjectedFault(RuntimeError):
    """A chaos-injected failure. Deliberately a plain RuntimeError
    subclass: the serving stack must recover through the SAME except
    paths a real failure takes — nothing is allowed to special-case
    chaos. Structured so invariant checks (and operators reading a
    flight dump) can attribute the failure to its injection."""

    def __init__(self, point: str, key: str = "", n: int = 0):
        super().__init__(
            f"chaos_injected: fault at {point!r}"
            + (f" (key={key!r}, n={n})" if key or n else f" (n={n})"))
        self.point = point
        self.key = key
        self.n = n


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One scheduled fault family. ``prob`` is evaluated by a seeded
    counter hash (see :meth:`FaultPlan._decide`); ``start``/``every``/
    ``max_fires`` window it; ``match`` filters on ctx fields (equality),
    so a rule can target one model or one replica."""

    point: str
    kind: str
    prob: float = 1.0
    start: int = 0
    every: int = 1
    max_fires: int = 1 << 30
    delay_ms: float = 50.0
    match: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Fault:
    """The directive ``fire()`` hands back to a seam (non-raising,
    non-sleeping kinds only)."""

    point: str
    kind: str
    key: str
    n: int
    delay_ms: float = 0.0


class FaultPlan:
    """A seeded set of :class:`FaultRule`\\ s plus the fired-fault
    ledger. The plan itself is immutable once armed (rules are frozen
    dataclasses); only the counters/ledger mutate, under the plane's
    lock."""

    # ctx fields that name a rule's independent stream, in priority
    # order — the per-(point, key) counter is what makes concurrent
    # streams independent and the schedule reproducible
    KEY_FIELDS = ("model", "replica", "tenant")

    def __init__(self, seed: int, rules: Sequence[FaultRule]):
        self.seed = int(seed)
        self.rules: tuple = tuple(rules)
        self.counts: dict = {}            # (point, key) -> invocations
        self.fired: list[dict] = []       # the ledger (bounded)
        self._fires_by_rule: dict = {}    # rule idx -> fires so far
        self.nonce = 0                    # set at arm (flight filtering)

    @classmethod
    def from_dict(cls, spec: dict) -> "FaultPlan":
        """Build from the JSON shape ``--chaos-plan`` loads:
        ``{"seed": 7, "faults": [{"point": ..., "kind": ...,
        "prob": 0.5, ...}, ...]}``. Unknown points are rejected loudly —
        a typo'd plan silently injecting nothing is worse than no
        plan."""
        rules = []
        for r in spec.get("faults") or spec.get("rules") or ():
            if r.get("point") not in INJECTION_POINTS:
                raise ValueError(
                    f"unknown injection point {r.get('point')!r} "
                    f"(known: {sorted(INJECTION_POINTS)})")
            rules.append(FaultRule(**r))
        return cls(seed=int(spec.get("seed", 0)), rules=rules)

    @classmethod
    def from_json(cls, path: str) -> "FaultPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    @staticmethod
    def _key(ctx: dict) -> str:
        for f in FaultPlan.KEY_FIELDS:
            v = ctx.get(f)
            if v:
                return str(v)
        return ""

    def _decide(self, rule_idx: int, rule: FaultRule, point: str,
                key: str, n: int) -> bool:
        """Pure schedule decision for invocation ``n`` of
        ``(point, key)``: window check, then a sha256-seeded Bernoulli —
        a real hash, not ``hash()`` (process-salted) and not crc32
        (linear over GF(2): adjacent seeds would draw near-identical
        schedules), because the schedule must reproduce across
        processes AND genuinely vary with the seed."""
        if n < rule.start or (n - rule.start) % rule.every != 0:
            return False
        if self._fires_by_rule.get(rule_idx, 0) >= rule.max_fires:
            return False
        if rule.prob >= 1.0:
            return True
        digest = hashlib.sha256(
            f"{self.seed}:{point}:{key}:{n}:{rule_idx}".encode()
        ).digest()
        draw = int.from_bytes(digest[:8], "big") / float(1 << 64)
        return draw < rule.prob

    def schedule(self) -> list[tuple]:
        """The fired-fault schedule as sorted ``(point, key, n, kind)``
        tuples — sorted because concurrent streams interleave
        arbitrarily in ledger order while each stream's own sequence is
        deterministic; the sorted view is the reproducible artifact."""
        return sorted((f["point"], f["key"], f["n"], f["kind"])
                      for f in self.fired)

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "rules": [r.as_dict() for r in self.rules],
            "fired": len(self.fired),
        }


class ChaosPlane:
    """The process-wide injection surface (module-level :data:`CHAOS`,
    deliberately global like FLIGHT/METRICS: the seams it serves span
    every subsystem and a fault plan is process-scoped by nature).
    Disarmed cost is one attribute read per seam hit."""

    def __init__(self):
        self._plan: Optional[FaultPlan] = None
        self._lock = named_lock("chaos.plan")
        self._last_report: Optional[dict] = None
        self._arm_seq = 0

    # -- arming ----------------------------------------------------------

    def arm(self, plan: FaultPlan) -> None:
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import CHAOS_ARMED
        with self._lock:
            self._arm_seq += 1
            # the nonce distinguishes THIS arming's flight events from a
            # previous plan's (the ring is process-wide); it is not part
            # of the deterministic schedule
            plan.nonce = self._arm_seq
            self._plan = plan
        CHAOS_ARMED.set(1.0)
        FLIGHT.record("chaos_armed", armed=True, seed=plan.seed,
                      rules=len(plan.rules))
        logger.warning("chaos plane ARMED: seed=%d, %d rule(s)",
                       plan.seed, len(plan.rules))

    def disarm(self) -> Optional[FaultPlan]:
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import CHAOS_ARMED
        with self._lock:
            plan, self._plan = self._plan, None
        CHAOS_ARMED.set(0.0)
        if plan is not None:
            FLIGHT.record("chaos_armed", armed=False, seed=plan.seed,
                          fired=len(plan.fired))
        return plan

    def armed(self) -> bool:
        return self._plan is not None

    class _Armed:
        def __init__(self, plane, plan):
            self.plane, self.plan = plane, plan

        def __enter__(self):
            self.plane.arm(self.plan)
            return self.plan

        def __exit__(self, *exc):
            self.plane.disarm()
            return False

    def arming(self, plan: FaultPlan) -> "ChaosPlane._Armed":
        """``with CHAOS.arming(plan): ...`` — scenario-scoped arming."""
        return ChaosPlane._Armed(self, plan)

    # -- the hot-path hook -----------------------------------------------

    def fire(self, point: str, **ctx: Any) -> Optional[Fault]:
        """The seam hook. Disarmed: one attribute read, returns None.
        Armed: bump the ``(point, key)`` counter, evaluate the rules,
        and on a hit record the fault (ledger + counter + flight event)
        and act — ``crash`` raises :class:`InjectedFault`, ``slow``/
        ``delay`` sleep (outside the lock, bounded), anything else
        returns the :class:`Fault` directive for the seam to apply."""
        plan = self._plan
        if plan is None:
            return None
        key = FaultPlan._key(ctx)
        hit: Optional[tuple] = None
        with self._lock:
            if self._plan is not plan:    # raced a disarm
                return None
            n = plan.counts.get((point, key), 0)
            plan.counts[(point, key)] = n + 1
            for idx, rule in enumerate(plan.rules):
                if rule.point != point:
                    continue
                if rule.match and any(ctx.get(k) != v
                                      for k, v in rule.match.items()):
                    continue
                if self._decide_locked(plan, idx, rule, point, key, n):
                    hit = (idx, rule, n)
                    break
        if hit is None:
            return None
        idx, rule, n = hit
        self._record(plan, point, rule.kind, key, n)
        if rule.kind == "crash":
            raise InjectedFault(point, key=key, n=n)
        if rule.kind in ("slow", "delay"):
            time.sleep(min(MAX_DELAY_S, max(0.0, rule.delay_ms) / 1000))
            return None
        return Fault(point=point, kind=rule.kind, key=key, n=n,
                     delay_ms=rule.delay_ms)

    @staticmethod
    def _decide_locked(plan: FaultPlan, idx: int, rule: FaultRule,
                       point: str, key: str, n: int) -> bool:
        if not plan._decide(idx, rule, point, key, n):
            return False
        plan._fires_by_rule[idx] = plan._fires_by_rule.get(idx, 0) + 1
        return True

    def _record(self, plan: FaultPlan, point: str, kind: str, key: str,
                n: int) -> None:
        from quoracle_tpu.infra.flightrec import FLIGHT
        from quoracle_tpu.infra.telemetry import CHAOS_FAULTS_TOTAL
        with self._lock:
            seq = len(plan.fired)
            if seq < 4096:                # ledger is bounded, counters not
                plan.fired.append({"seq": seq, "point": point,
                                   "kind": kind, "key": key, "n": n})
        CHAOS_FAULTS_TOTAL.inc(point=point, kind=kind)
        # the event's own kind is "chaos_fault"; the FAULT's kind rides
        # as fault_kind (chaos/invariants.chaos_events reads it back)
        FLIGHT.record("chaos_fault", point=point, fault_kind=kind,
                      key=key, n=n, seq=seq,
                      plan=getattr(plan, "nonce", 0))

    # -- reads (GET /api/chaos) ------------------------------------------

    def note_report(self, report: dict) -> None:
        with self._lock:
            self._last_report = report

    def status(self) -> dict:
        plan = self._plan
        with self._lock:
            last = self._last_report
        out: dict = {
            "armed": plan is not None,
            "points": dict(INJECTION_POINTS),
            "last_scenario": last,
        }
        if plan is not None:
            with self._lock:
                out["plan"] = plan.as_dict()
                out["fired"] = list(plan.fired[-64:])
        return out


CHAOS = ChaosPlane()


def chaos_demote_churn(engine) -> int:
    """Forced demote churn (the ``sched.tick`` seam's ``demote``
    directive): apply alloc pressure so the eviction ladder demotes
    every demotable victim to the host tier — sessions the still-live
    rows then restore by page-in, mid-traffic. Exactly the hostile
    interleaving PR 7's invariants promise to survive; temp-0 outputs
    must not move. Returns pages cycled (0 when no tier is attached —
    churn without a tier would DESTROY state, which is a pool-sizing
    incident, not chaos)."""
    st = getattr(engine, "sessions", None)
    if st is None or getattr(st, "tier", None) is None or st.k is None:
        return 0
    with engine._paged_lock:
        with st.lock:
            # demand (nearly) the WHOLE pool: the ladder must demote
            # every demotable victim to satisfy it. A refusal
            # (unattainable — pinned pages) still demoted everything it
            # could first, which is the churn this exists to inject.
            got = st.alloc(max(1, st.n_pages - 1))
            if got:
                st._release(got)
                return len(got)
    return 0
