"""Composable chaos scenarios (ISSUE 11 tentpole, part b).

Each scenario scripts one hostile condition over the REAL serving stack
(ClusterPlane + router + QoS + tiered KV + continuous batching — the
production objects, not stubs), declares the invariant set it must
satisfy (chaos/invariants.py), and runs in two phases:

  1. **clean** — the same traffic with nothing armed, establishing the
     fault-free baseline every survivor is compared against;
  2. **storm** — a seeded :class:`FaultPlan` armed on :data:`CHAOS`
     while the identical traffic replays.

``run_scenario(name, seed)`` returns a :class:`ScenarioReport` with
per-invariant verdicts, the fired fault schedule, and scenario-specific
evidence (handoff replacements, corrupt-entry counts, drift trips).
Scenarios marked ``deterministic_rerun`` run the storm twice and assert
the second plan (same seed, fresh counters) fires the IDENTICAL
schedule — the reproducibility contract that makes a chaos failure
debuggable instead of anecdotal.

Tier-1 runs every scenario on the mock-device (CPU tiny-engine)
cluster; bench.py config 17 drives the storm scenario against real
engines. The registry:

  traffic_storm       multi-tenant storm + admission/router signal loss
  kill_mid_handoff    decode-replica death mid-row + export failure
  restart_warm_start  process restart over a corrupted disk prefix store
  drift_storm         member garbage/crash feeding PR 5 drift detection
  hbm_pressure_churn  forced demote churn + restore failures + a
                      compile-key poisoning storm
  fabric_partition    peer links flap mid-handoff over the loopback
                      fabric (ISSUE 12) — drops and corrupt frames;
                      bounded retry absorbs the flap or the row
                      degrades/re-places structurally
  scale_storm         the elastic fleet (ISSUE 14) scales, re-tiers,
                      and drains mid-traffic while a replica is killed
                      during its own drain and a migration degrades —
                      survivors bit-equal, envelope ledger empty
"""

from __future__ import annotations

import dataclasses
import logging
import shutil
import tempfile
import time
from typing import Any, Callable, Optional

from quoracle_tpu.chaos import invariants as inv
from quoracle_tpu.chaos.faults import CHAOS, FaultPlan, FaultRule
from quoracle_tpu.infra.flightrec import FLIGHT

logger = logging.getLogger(__name__)

MEMBER = "xla:tiny"


@dataclasses.dataclass
class ScenarioReport:
    name: str
    seed: int
    passed: bool
    invariants: list
    schedule: list
    evidence: dict
    wall_s: float

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "passed": self.passed,
            "invariants": [r.as_dict() for r in self.invariants],
            "faults_fired": len(self.schedule),
            "schedule": [list(t) for t in self.schedule[:64]],
            "evidence": self.evidence,
            "wall_s": round(self.wall_s, 2),
        }


class Scenario:
    """Base: subclasses fill in build/rules/traffic/check."""

    name = "base"
    description = ""
    deterministic_rerun = False

    def build(self, ctx: dict) -> None:
        raise NotImplementedError

    def rules(self, ctx: dict) -> list:
        raise NotImplementedError

    def traffic(self, ctx: dict, phase: str) -> dict:
        """Drive one full pass; returns at least ``{"submitted": int,
        "results": [...]}`` plus scenario-specific keys. ``phase`` is
        "clean" / "storm" / "rerun" so session ids never collide across
        phases (a cross-phase splice would corrupt the baseline)."""
        raise NotImplementedError

    def check(self, ctx: dict, clean: dict, storm: dict,
              plan, flight_slice: list) -> list:
        raise NotImplementedError

    def close(self, ctx: dict) -> None:
        for b in ctx.get("backends", ()):
            try:
                b.close()
            except Exception:             # noqa: BLE001 — best-effort
                logger.exception("%s: backend close failed", self.name)


def _flight_for_plan(plan) -> list:
    """This plan's chaos_fault events out of the process-wide ring."""
    nonce = getattr(plan, "nonce", None)
    return [e for e in FLIGHT.snapshot()
            if e.get("kind") == "chaos_fault" and e.get("plan") == nonce]


def run_scenario(name: str, seed: int = 0,
                 context: Optional[dict] = None) -> ScenarioReport:
    """Build → clean pass → armed storm pass → invariants. With
    ``context`` the caller owns backend lifecycle (bench reuse); else
    the scenario builds and closes its own."""
    from quoracle_tpu.analysis import lockdep
    from quoracle_tpu.infra.telemetry import (
        CHAOS_INVARIANT_FAILURES, CHAOS_SCENARIOS_TOTAL,
    )

    sc = SCENARIOS[name]()
    ctx: dict = dict(context or {})
    owns = context is None
    ctx.setdefault("tmpdir", tempfile.mkdtemp(prefix=f"chaos-{name}-"))
    t0 = time.monotonic()
    try:
        if owns:
            sc.build(ctx)
        FLIGHT.record("chaos_scenario_start", scenario=name, seed=seed,
                      phase="clean")
        clean = sc.traffic(ctx, "clean")
        # the storm must not inherit blame for earlier inversions
        lockdep.LOCKDEP.drain()
        plan = FaultPlan(seed, sc.rules(ctx))
        FLIGHT.record("chaos_scenario_start", scenario=name, seed=seed,
                      phase="storm")
        with CHAOS.arming(plan):
            storm = sc.traffic(ctx, "storm")
        flight_slice = _flight_for_plan(plan)
        results = list(sc.check(ctx, clean, storm, plan, flight_slice))
        if sc.deterministic_rerun:
            plan2 = FaultPlan(seed, sc.rules(ctx))
            with CHAOS.arming(plan2):
                sc.traffic(ctx, "rerun")
            results.append(inv.fault_schedule(
                plan2, _flight_for_plan(plan2),
                expected=plan.schedule()))
        passed = all(r.ok for r in results)
        report = ScenarioReport(
            name=name, seed=seed, passed=passed, invariants=results,
            schedule=plan.schedule(), evidence=storm.get("evidence", {}),
            wall_s=time.monotonic() - t0)
        CHAOS_SCENARIOS_TOTAL.inc(scenario=name,
                                  result="pass" if passed else "fail")
        for r in results:
            if not r.ok:
                CHAOS_INVARIANT_FAILURES.inc(scenario=name,
                                             invariant=r.name)
                # correlated incident capture (ISSUE 15): a failed
                # recovery invariant is a bug report — bundle every
                # reachable flight ring under one deterministic id
                from quoracle_tpu.infra.fleetobs import INCIDENTS
                INCIDENTS.capture("chaos_invariant",
                                  f"{name}:{r.name}",
                                  reason=r.detail[:200])
        FLIGHT.record("chaos_scenario_end", scenario=name, seed=seed,
                      passed=passed,
                      failed=[r.name for r in results if not r.ok],
                      faults=len(plan.fired))
        CHAOS.note_report(report.as_dict())
        return report
    finally:
        if owns:
            sc.close(ctx)
        shutil.rmtree(ctx.get("tmpdir", ""), ignore_errors=True)


# ---------------------------------------------------------------------------
# Shared request plumbing
# ---------------------------------------------------------------------------


def _req(msgs, sid=None, cj=False, max_tokens=16, priority=None,
         tenant="default"):
    from quoracle_tpu.models.runtime import QueryRequest
    return QueryRequest(MEMBER, msgs, temperature=0.0,
                        max_tokens=max_tokens, session_id=sid,
                        constrain_json=cj, priority=priority,
                        tenant=tenant)


def _msgs(text: str) -> list:
    return [{"role": "user", "content": text}]


# ---------------------------------------------------------------------------
# 1. Multi-tenant traffic storm
# ---------------------------------------------------------------------------


class TrafficStorm(Scenario):
    """Mixed-class multi-tenant traffic through a 2-replica
    prefill/decode cluster with QoS on, while the admission controller's
    signal refresh drops/delays and the router loses replica snapshots.
    A rate-capped "burst" tenant floods bulk rows that must shed
    STRUCTURED (429-shaped), never silently; interactive rows must
    survive bit-equal to the fault-free run."""

    name = "traffic_storm"
    description = ("multi-tenant storm + admission/router signal "
                   "loss over the disaggregated cluster")
    deterministic_rerun = True

    N_EQ = 4
    N_BURST = 4

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.serving.cluster import ClusterPlane
        from quoracle_tpu.serving.qos import Priority, TenantPolicy
        # replicas=3 → 1 prefill + 2 decode: the router has a real
        # placement choice, so the router.signals drop path is live
        cl = ClusterPlane.build([MEMBER], replicas=3, disaggregate=True,
                                continuous=True, continuous_chunk=8,
                                qos=True)
        for rep in cl.replicas:
            ctrl = getattr(rep.backend, "qos_controller", None)
            if ctrl is not None:
                ctrl.set_tenant(TenantPolicy(
                    name="burst", rate_per_s=0.001, burst=1.0,
                    max_class=Priority.BACKGROUND))
        ctx["cluster"] = cl
        ctx["backends"] = [cl]

    def rules(self, ctx: dict) -> list:
        return [
            FaultRule("admission.signals", "drop", prob=0.5),
            FaultRule("admission.signals", "delay", prob=0.4,
                      delay_ms=15),
            FaultRule("router.signals", "drop", prob=0.5),
        ]

    def traffic(self, ctx: dict, phase: str) -> dict:
        from quoracle_tpu.serving.qos import Priority
        cl = ctx["cluster"]
        eq_reqs = []
        for i in range(self.N_EQ):
            eq_reqs.append(_req(
                _msgs(f"interactive row {i}: summarize the storm"),
                cj=(i % 2 == 1), priority=Priority.INTERACTIVE,
                tenant=f"tenant-{i % 2}"))
        burst_reqs = [
            _req(_msgs(f"burst row {j}: bulk backfill"),
                 priority=Priority.BACKGROUND, tenant="burst")
            for j in range(self.N_BURST)]
        eq = cl.query(eq_reqs)
        burst = cl.query(burst_reqs)
        return {
            "submitted": len(eq_reqs) + len(burst_reqs),
            "results": eq + burst,
            "eq": eq,
        }

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        cl = ctx["cluster"]
        return [
            inv.no_silent_loss(storm["submitted"], storm["results"],
                               backends=[cl]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.slo_burn_bounded(storm["results"], backends=[cl]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
        ]


# ---------------------------------------------------------------------------
# 2. Kill mid-handoff
# ---------------------------------------------------------------------------


class KillMidHandoff(Scenario):
    """A 3-replica cluster (1 prefill, 2 decode): the first row's
    decode replica dies AFTER its KV handoff landed — the retained
    envelope must re-place it onto the survivor bit-identically
    (kv_handoff_replace); a later export failure must degrade to a cold
    re-prefill. Every row survives; nothing is silently lost."""

    name = "kill_mid_handoff"
    description = ("decode-replica death mid-row (envelope re-place) "
                   "+ handoff export failure (cold degrade)")

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.serving.cluster import ClusterPlane
        cl = ClusterPlane.build([MEMBER], replicas=3, disaggregate=True,
                                continuous=True, continuous_chunk=8)
        ctx["cluster"] = cl
        ctx["backends"] = [cl]

    def rules(self, ctx: dict) -> list:
        return [
            FaultRule("cluster.decode", "crash", max_fires=1),
            FaultRule("handoff.export", "fail", start=2, max_fires=1),
        ]

    def traffic(self, ctx: dict, phase: str) -> dict:
        cl = ctx["cluster"]
        results = []
        for i in range(4):
            results += cl.query([_req(
                _msgs(f"handoff row {i}: explain replica failover"),
                cj=(i == 3), max_tokens=12)])
        return {"submitted": 4, "results": results, "eq": results}

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        cl = ctx["cluster"]
        ho = cl.handoff.stats()
        dead = [r.replica_id for r in cl.replicas if not r.alive]
        out = [
            inv.no_silent_loss(storm["submitted"], storm["results"],
                               backends=[cl]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "recovery_engaged",
                ho["replaced"] >= 1 and len(dead) == 1,
                f"replaced={ho['replaced']} dead={dead}"),
        ]
        storm["evidence"] = {"handoff": ho, "dead_replicas": dead}
        return out


# ---------------------------------------------------------------------------
# 3. Restart warm-start over a corrupted disk store
# ---------------------------------------------------------------------------


class RestartWarmStart(Scenario):
    """Process 1 serves traffic and persists prefix blocks; process 2
    (a fresh backend over the same --disk-kv-dir) warm-starts while
    chaos corrupts entries UNDER it mid-load. The crc32 boundary must
    skip-unlink-degrade: identical outputs, corrupt counter up, no
    poisoned prefix ever served."""

    name = "restart_warm_start"
    description = ("restart warm-start while disk prefix entries "
                   "corrupt under the reader")

    PROMPTS = [
        "system: shared policy preamble for every agent session. " * 4
        + f"task {i}: restate the rules briefly."
        for i in range(3)
    ]

    def _backend(self, ctx: dict):
        from quoracle_tpu.models.runtime import TPUBackend
        return TPUBackend([MEMBER], host_kv_mb=32,
                          disk_kv_dir=ctx["tmpdir"], disk_kv_gb=1.0)

    def build(self, ctx: dict) -> None:
        ctx["backends"] = []

    def rules(self, ctx: dict) -> list:
        return [FaultRule("kvtier.disk_load", "corrupt", every=2),
                FaultRule("kvtier.restore", "fail", prob=0.25)]

    def traffic(self, ctx: dict, phase: str) -> dict:
        b = self._backend(ctx)            # each phase IS a "process"
        try:
            results = []
            for i, p in enumerate(self.PROMPTS):
                results += b.query([_req(_msgs(p), max_tokens=12,
                                         sid=f"{phase}-s{i}")])
            for i in range(len(self.PROMPTS)):
                b.drop_session(f"{phase}-s{i}")
            for e in b.engines.values():
                tier = getattr(e.sessions, "tier", None)
                if tier is not None:
                    tier.flush_spills()
            stats = b.kv_stats()
            return {"submitted": len(self.PROMPTS), "results": results,
                    "eq": results, "kv": stats}
        finally:
            b.close()

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        disk = {}
        for m in (storm.get("kv") or {}).get("members", {}).values():
            disk = m.get("disk") or {}
        fired_corrupt = [t for t in plan.schedule()
                         if t[3] == "corrupt"]
        out = [
            inv.no_silent_loss(storm["submitted"], storm["results"]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "corruption_contained",
                (not fired_corrupt)
                or disk.get("corrupt_skipped", 0) >= len(fired_corrupt),
                f"corrupt_fired={len(fired_corrupt)} "
                f"corrupt_skipped={disk.get('corrupt_skipped')}"),
        ]
        storm["evidence"] = {"disk": disk,
                             "corrupt_fired": len(fired_corrupt)}
        return out


# ---------------------------------------------------------------------------
# 4. Drift storm
# ---------------------------------------------------------------------------


class DriftStorm(Scenario):
    """Member crash/garbage injection under real ConsensusEngine
    decides: a healthy baseline, then one member turns to garbage
    (valid-but-divergent proposals → dissent) and another starts
    crashing (structured transport failures). PR 5's detector must trip
    dissent drift on the garbage member, every audit record must stay
    coherent, and no decide may be lost. Resets the process-wide
    QUALITY rolling state — scenario baselines must not inherit another
    run's EWMA history."""

    name = "drift_storm"
    description = ("member garbage/crash under consensus decides — "
                   "drift detection + audit coherence")
    deterministic_rerun = True

    N_DECIDES = 26
    GARBAGE_AT = 20                       # past QUALITY.min_samples
    GARBAGE_MEMBER = "mock:consensus-model-3"
    CRASH_MEMBER = "mock:consensus-model-2"

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.models.runtime import MockBackend
        ctx["backend"] = MockBackend()
        ctx["backends"] = []              # MockBackend has no close()

    def rules(self, ctx: dict) -> list:
        return [
            FaultRule("pool.member", "garbage", start=self.GARBAGE_AT,
                      match={"model": self.GARBAGE_MEMBER}),
            FaultRule("pool.member", "crash", start=self.GARBAGE_AT + 2,
                      every=3, match={"model": self.CRASH_MEMBER}),
        ]

    def traffic(self, ctx: dict, phase: str) -> dict:
        from quoracle_tpu.consensus.engine import (
            ConsensusConfig, ConsensusEngine,
        )
        from quoracle_tpu.consensus.quality import QUALITY
        from quoracle_tpu.models.runtime import MockBackend
        QUALITY.reset()
        pool = list(MockBackend.DEFAULT_POOL)
        eng = ConsensusEngine(ctx["backend"], ConsensusConfig(
            model_pool=pool, session_key=f"chaos-{phase}",
            quality=True, task_id=f"chaos-drift-{phase}"))
        outcomes, records = [], []
        for i in range(self.N_DECIDES):
            msgs = {m: _msgs(f"decide {i}: pick the next action")
                    for m in pool}
            out = eng.decide(msgs)
            outcomes.append(out)
            if out.audit is not None:
                records.append(out.audit)
        return {"submitted": self.N_DECIDES, "outcomes": outcomes,
                "records": records,
                "scorecards": QUALITY.scorecards()}

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        cards = storm["scorecards"]
        garbage = cards["members"].get(self.GARBAGE_MEMBER, {})
        drift = (garbage.get("drift") or {}).get("dissent") or {}
        crash_card = cards["members"].get(self.CRASH_MEMBER, {})
        failures = crash_card.get("failures") or {}
        decided = sum(1 for o in storm["outcomes"]
                      if o.status is not None)
        out = [
            inv.InvariantResult(
                "no_silent_loss",
                decided == storm["submitted"]
                and len(storm["records"]) == storm["submitted"],
                f"decides={decided}/{storm['submitted']} "
                f"audit_records={len(storm['records'])}"),
            inv.audit_coherent(storm["records"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "drift_tripped", bool(drift.get("tripped")),
                f"garbage member dissent drift: {drift}"),
            inv.InvariantResult(
                "failures_attributed",
                sum(failures.values()) >= 1 if plan.schedule() else True,
                f"crash member failure kinds: {failures}"),
        ]
        storm["evidence"] = {"drifting": cards.get("drifting"),
                             "garbage_drift": drift,
                             "crash_failures": failures}
        return out


# ---------------------------------------------------------------------------
# 5. HBM-pressure churn
# ---------------------------------------------------------------------------


class HbmPressureChurn(Scenario):
    """Sessioned continuous-batching traffic on an INT8-quantized member
    (ISSUE 13) while chaos forces the eviction ladder to hibernate
    everything demotable every other tick, fails a quarter of the
    restores (degrade-to-re-prefill), poisons compile-cache keys into a
    ledger-level recompile storm, and flips per-page SCALE bytes in
    disk entries on the restore path. Outputs must not move a bit; the
    storm gauge must trip and recover; every scale corruption must be
    crc-rejected (skip, unlink, re-prefill) — silently-wrong KV is the
    one outcome this scenario exists to rule out."""

    name = "hbm_pressure_churn"
    description = ("forced demote churn + restore failures + compile-"
                   "key poisoning + per-page scale corruption under "
                   "sessioned continuous traffic on a quantized member")

    N_SESSIONS = 3

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.models.runtime import TPUBackend
        b = TPUBackend([MEMBER], continuous=True, continuous_chunk=8,
                       host_kv_mb=32, disk_kv_dir=ctx["tmpdir"],
                       disk_kv_gb=1.0, quantize_kv=True)
        ctx["backend"] = b
        ctx["backends"] = [b]

    def rules(self, ctx: dict) -> list:
        return [
            FaultRule("sched.tick", "demote", every=2),
            FaultRule("kvtier.restore", "fail", prob=0.25),
            FaultRule("compile.key", "poison", max_fires=8),
            FaultRule("kvtier.scale_corrupt", "corrupt", prob=0.5),
        ]

    def traffic(self, ctx: dict, phase: str) -> dict:
        b = ctx["backend"]
        results = []
        # > 1 page (128 tokens, byte tokenizer) so wave 1's store-backs
        # write-through full prefix blocks to the disk store
        prompts = [f"churn session {i}: keep a running tally. " * 4
                   for i in range(self.N_SESSIONS)]
        # wave 1 establishes sessions; churn demotes them between
        # ticks; wave 2 resumes them (restore or re-prefill, same bits)
        for wave in range(2):
            for i, p in enumerate(prompts):
                results += b.query([_req(
                    _msgs(p + f" wave {wave}."), max_tokens=10,
                    sid=f"{phase}-churn{i}")])
        # wave 3: FRESH sessions over the same shared prompts, with the
        # radix tree stripped and the host prefix copies evicted — the
        # prefix ladder's DISK rung must serve, i.e. every restore runs
        # through the crc boundary the scale_corrupt point flips
        # (reject → unlink → re-prefill, bits unchanged).
        eng = b.engines[MEMBER]
        tier = eng.sessions.tier
        tier.flush_spills()
        with eng._paged_lock:
            with eng.sessions.lock:
                got = eng.sessions.alloc(eng.sessions.n_pages - 1)
                if got is not None:
                    eng.sessions._release(got)
        with eng.sessions.lock:
            for key in list(tier.host.prefixes):
                e = tier.host.prefixes.pop(key)
                tier.host.bytes -= e.nbytes
            tier.host.sessions.clear()
            tier.host.bytes = 0
        for i, p in enumerate(prompts):
            results += b.query([_req(
                _msgs(p + " wave 0."), max_tokens=10,
                sid=f"{phase}-fresh{i}")])
        for i in range(self.N_SESSIONS):
            b.drop_session(f"{phase}-churn{i}")
            b.drop_session(f"{phase}-fresh{i}")
        eng = b.engines[MEMBER]
        tier = eng.sessions.tier
        return {
            "submitted": 3 * self.N_SESSIONS,
            "results": results, "eq": results,
            "tier": tier.stats() if tier is not None else {},
            "storms_total": eng.compiles.storms_total,
            # a storm already active at phase end never RE-trips inside
            # the 120 s window — the detection check must not demand a
            # second transition
            "storm_active": eng.compiles.storm,
        }

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        tier_clean = clean.get("tier") or {}
        tier_storm = storm.get("tier") or {}
        demoted = (tier_storm.get("demoted_sessions", 0)
                   - tier_clean.get("demoted_sessions", 0))
        storms = (storm.get("storms_total", 0)
                  - clean.get("storms_total", 0))
        poisoned = [t for t in plan.schedule() if t[3] == "poison"]
        churned = [t for t in plan.schedule() if t[3] == "demote"]
        scale_hits = [t for t in plan.schedule()
                      if t[0] == "kvtier.scale_corrupt"]
        disk = (tier_storm.get("disk") or {})
        corrupt_detected = (disk.get("corrupt_skipped", 0)
                            - ((tier_clean.get("disk") or {})
                               .get("corrupt_skipped", 0)))
        out = [
            inv.no_silent_loss(storm["submitted"], storm["results"],
                               backends=[ctx["backend"]]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "churn_engaged",
                demoted >= 1 if churned else True,
                f"demote_faults={len(churned)} sessions_demoted={demoted}"),
            inv.InvariantResult(
                "storm_detected",
                (storms >= 1 or bool(clean.get("storm_active"))
                 or bool(storm.get("storm_active")))
                if len(poisoned) >= 5 else True,
                f"poisoned_keys={len(poisoned)} storms_tripped={storms} "
                f"active={bool(storm.get('storm_active'))}"),
            # ISSUE 13 satellite: every flipped per-page scale byte must
            # be DETECTED — crc reject → skip + unlink + re-prefill. The
            # temp-0 equality check above is the "never silently wrong"
            # half; this is the "the boundary actually fired" half.
            inv.InvariantResult(
                "scale_corruption_detected",
                corrupt_detected >= 1 if scale_hits else True,
                f"scale_corrupt_faults={len(scale_hits)} "
                f"crc_rejects={corrupt_detected}"),
        ]
        storm["evidence"] = {"tier": tier_storm, "storms": storms,
                             "storm_active": bool(
                                 storm.get("storm_active")),
                             "poisoned": len(poisoned),
                             "scale_corrupt": len(scale_hits),
                             "crc_rejects": corrupt_detected}
        return out


# ---------------------------------------------------------------------------
# 6. Fabric partition (ISSUE 12)
# ---------------------------------------------------------------------------


class FabricPartition(Scenario):
    """Three replica "processes" (1 prefill + 2 decode FabricPeers)
    joined to a front door over loopback transports — every byte rides
    the real wire codec — while the peer links FLAP: frames drop and
    corrupt mid-handoff. The transport's bounded retry must absorb
    transient faults; persistent ones must degrade structurally (cold
    re-prefill, envelope re-place onto a survivor, or a structured
    failure naming peer + phase) — and every surviving row must be
    BIT-IDENTICAL to the fault-free run. No silent loss, ever."""

    name = "fabric_partition"
    description = ("peer link flap (drop + corrupt frames) over the "
                   "loopback fabric mid-handoff")

    N_ROWS = 4

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.serving.cluster import RemoteReplica
        from quoracle_tpu.serving.fabric.frontdoor import FabricPlane
        from quoracle_tpu.serving.fabric.peer import FabricPeer
        from quoracle_tpu.serving.fabric.transport import (
            LoopbackTransport,
        )
        peers = [
            FabricPeer.build([MEMBER], role="prefill",
                             replica_id="prefill-0", continuous_chunk=8),
            FabricPeer.build([MEMBER], role="decode",
                             replica_id="decode-1", continuous_chunk=8),
            FabricPeer.build([MEMBER], role="decode",
                             replica_id="decode-2", continuous_chunk=8),
        ]
        plane = FabricPlane([
            RemoteReplica(LoopbackTransport(p.handle, p.replica_id,
                                            backoff_ms=5.0))
            for p in peers])
        ctx["plane"] = plane
        ctx["peers"] = peers
        ctx["backends"] = [plane] + peers

    def rules(self, ctx: dict) -> list:
        # bounded fault families: the flap must be survivable by
        # design — a permanently partitioned fleet tests mark-failed,
        # not recovery. start=2 skips the build-time hellos so the
        # faults land on serving traffic (handoff legs included).
        return [
            FaultRule("fabric.send", "drop", prob=0.5, start=2,
                      max_fires=5),
            FaultRule("fabric.send", "corrupt", prob=0.6, start=3,
                      max_fires=5),
            FaultRule("fabric.send", "delay", prob=0.25, delay_ms=10,
                      start=2),
        ]

    def traffic(self, ctx: dict, phase: str) -> dict:
        plane = ctx["plane"]
        results = []
        for i in range(self.N_ROWS):
            results += plane.query([_req(
                _msgs(f"fabric row {i}: explain link-flap recovery"),
                cj=(i == 3), max_tokens=10)])
        return {"submitted": self.N_ROWS, "results": results,
                "eq": results}

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        plane = ctx["plane"]
        retried = sum(p.transport.stats()["retried"]
                      for p in plane.peers)
        survivors = sum(1 for r in storm["results"]
                        if getattr(r, "ok", False))
        recovered = (retried >= 1 or plane.replaced >= 1
                     or plane.cold_failovers >= 1)
        out = [
            inv.no_silent_loss(storm["submitted"], storm["results"],
                               backends=ctx["peers"]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "flap_absorbed_or_degraded",
                recovered if plan.schedule() else True,
                f"retried={retried} replaced={plane.replaced} "
                f"cold_failovers={plane.cold_failovers} "
                f"survivors={survivors}/{len(storm['results'])}"),
        ]
        storm["evidence"] = {
            "retried": retried,
            "replaced": plane.replaced,
            "cold_failovers": plane.cold_failovers,
            "dead_peers": [p.replica_id for p in plane.peers
                           if not p.alive],
            "survivors": survivors,
        }
        return out


# ---------------------------------------------------------------------------
# 7. Scale storm (ISSUE 14)
# ---------------------------------------------------------------------------


class ScaleStorm(Scenario):
    """The elastic fleet under fire: a 4-replica prefill/decode cluster
    runs sessioned traffic while the FleetController scales up (policy
    ticks over synthetic burn signals), retires a decode replica
    through a live drain, re-tiers a prefill replica and flips it back,
    and force-drains the replica holding a live session — with chaos
    KILLING the first draining replica mid-drain (sessions still
    aboard) and degrading one later migration. Every row must survive
    bit-equal to the fault-free pass (cold re-prefills allowed, wrong
    bits never), failures must be structured, and the handoff envelope
    ledger must end empty — a leaked envelope is a stranded failover
    source. Both phases run the SAME self-restoring script, so the
    shared cluster enters the storm with the clean phase's topology
    shape (2 prefill + 2 decode)."""

    name = "scale_storm"
    description = ("forced drain + re-tier + scale-down mid-traffic "
                   "with a replica killed during its own drain")

    N_SESSIONS = 3

    def build(self, ctx: dict) -> None:
        from quoracle_tpu.serving.cluster import ClusterPlane
        from quoracle_tpu.serving.fleet import FleetConfig, FleetController
        cl = ClusterPlane.build([MEMBER], replicas=4, disaggregate=True,
                                continuous=True, continuous_chunk=8)
        ctx["cluster"] = cl
        ctx["fleet"] = FleetController(cl, FleetConfig(
            min_replicas=1, max_replicas=4, hysteresis_ticks=2,
            cooldown_ticks=0, seed=5))
        ctx["backends"] = [cl]

    def rules(self, ctx: dict) -> list:
        return [
            # the first draining replica dies with sessions aboard —
            # mark-failed + re-prefill, never silent loss
            FaultRule("fleet.migrate", "crash", max_fires=1),
            # one later migration degrades a single session to
            # re-prefill (affinity dropped, bits unchanged)
            FaultRule("fleet.migrate", "fail", max_fires=1),
        ]

    @staticmethod
    def _burn_signals(cl):
        from quoracle_tpu.serving.fleet import FleetSignals, ReplicaSignal
        return FleetSignals(replicas=tuple(
            ReplicaSignal(r.replica_id, r.role,
                          12.0 if r.role == "decode" else 0.0)
            for r in cl.replicas), slo_burn=2.0)

    def traffic(self, ctx: dict, phase: str) -> dict:
        cl, fc = ctx["cluster"], ctx["fleet"]
        results, drains = [], []
        sids = [f"{phase}-elastic{i}" for i in range(self.N_SESSIONS)]
        # wave 1: establish sessions on the decode tier
        for i, sid in enumerate(sids):
            results += cl.query([_req(
                _msgs(f"elastic session {i}: plan the next scale "
                      f"event step by step"), sid=sid, max_tokens=10)])
        # policy scale-up: two burn ticks clear the hysteresis bound
        fc.tick(self._burn_signals(cl))
        up = fc.tick(self._burn_signals(cl))
        assert up is not None and up.action == "scale_up"
        results += cl.query([_req(_msgs("mid-traffic row A"),
                                  max_tokens=8)])
        # scale-down: retire the first decode replica through a live
        # drain — the storm kills it mid-drain (fleet.migrate crash)
        first_dec = sorted(r.replica_id for r in cl.replicas
                           if r.role == "decode")[0]
        drains.append(fc.drain(first_dec, retire=True,
                               reason=f"{phase}-scale-down"))
        # re-tier a prefill replica into the decode tier and back —
        # the drain-flip-drain-flip round trip must strand nothing
        pre = sorted(r.replica_id for r in cl.replicas
                     if r.role == "prefill")[-1]
        drains.append(fc.drain(pre, new_role="decode",
                               reason=f"{phase}-retier"))
        results += cl.query([_req(_msgs("mid-traffic row B"),
                                  max_tokens=8)])
        drains.append(fc.drain(pre, new_role="prefill",
                               reason=f"{phase}-retier-back"))
        # wave 2: resume every session (migrated, or re-prefilled where
        # the kill took its replica down)
        for i, sid in enumerate(sids):
            results += cl.query([_req(
                _msgs(f"elastic session {i}: continue the plan"),
                sid=sid, max_tokens=10)])
        # forced drain of the replica HOLDING session 0 (the hot-swap
        # primitive): its migration degrades in the storm (fail)
        holder = cl.router.affinity_of(sids[0])
        if holder is not None:
            drains.append(fc.drain(holder.replica_id, retire=False,
                                   reason=f"{phase}-hot-swap"))
        # wave 3: every session serves again, wherever it landed
        for i, sid in enumerate(sids):
            results += cl.query([_req(
                _msgs(f"elastic session {i}: summarize"),
                sid=sid, max_tokens=10)])
        for sid in sids:
            cl.drop_session(sid)
        return {
            "submitted": 3 * self.N_SESSIONS + 2,
            "results": results, "eq": results,
            "drains": drains,
            "handoff": cl.handoff.stats(),
        }

    def check(self, ctx, clean, storm, plan, flight_slice) -> list:
        cl = ctx["cluster"]
        crash_fired = [t for t in plan.schedule() if t[3] == "crash"]
        fail_fired = [t for t in plan.schedule() if t[3] == "fail"]
        clean_first, storm_first = clean["drains"][0], storm["drains"][0]
        out = [
            inv.no_silent_loss(storm["submitted"], storm["results"],
                               backends=[cl]),
            inv.structured_failures(storm["results"]),
            inv.temp0_equality(clean["eq"], storm["eq"]),
            inv.lockdep_clean(),
            inv.fault_schedule(plan, flight_slice),
            inv.InvariantResult(
                "clean_drain_migrated",
                clean_first["migrated"] >= 1
                and not clean_first["died"],
                f"clean scale-down drain: {clean_first}"),
            inv.InvariantResult(
                "kill_mid_drain_contained",
                (not crash_fired)
                or (storm_first["died"]
                    and storm_first["replica"] == crash_fired[0][1]),
                f"crash={crash_fired} storm drain: {storm_first}"),
            inv.InvariantResult(
                "migration_degraded_structurally",
                (not fail_fired)
                or any(d["failed"] >= 1 for d in storm["drains"]),
                f"fail={fail_fired} drains={storm['drains']}"),
            inv.InvariantResult(
                "no_envelope_leaks",
                storm["handoff"]["inflight"] == 0,
                f"handoff={storm['handoff']}"),
        ]
        storm["evidence"] = {
            "drains": storm["drains"],
            "ledger": ctx["fleet"].ledger(),
            "dead_replicas": [r.replica_id for r in cl.replicas
                              if not r.alive],
            "handoff": storm["handoff"],
        }
        return out


SCENARIOS: dict = {
    sc.name: sc for sc in (TrafficStorm, KillMidHandoff,
                           RestartWarmStart, DriftStorm,
                           HbmPressureChurn, FabricPartition,
                           ScaleStorm)
}
