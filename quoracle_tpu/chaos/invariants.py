"""Machine-checked recovery invariants (ISSUE 11 tentpole, part c).

A chaos run without enforced postconditions is a demo. Every scenario
(chaos/scenarios.py) declares the subset of these checks it must
satisfy, and the harness evaluates them from the sources of truth the
stack already maintains — client-side result accounting, the flight
recorder ring, MetricsRegistry counters, the consensus audit trail, and
the lockdep ledger — never from chaos-only side channels, so a passing
invariant means the PRODUCTION observability surface proves the
property, not the harness.

The catalog:

* ``no_silent_loss`` — every submitted request produced exactly one
  result: ok, shed (structured admission reject), or failed
  (structured error). ``submitted == ok + shed + failed`` with nothing
  unclassified and no stranded queue state.
* ``structured_failures`` — every failure is STRUCTURED: its error text
  carries a recognized machine-readable prefix, and replica failures
  name replica + phase. A bare traceback string is a failed check.
* ``temp0_equality`` — every surviving (ok) row's text is BIT-IDENTICAL
  to the same request's fault-free run. Recovery paths (handoff
  re-place, tier restore, re-prefill degrade) must be invisible in the
  output at temperature 0.
* ``audit_coherent`` — every consensus audit record emitted during the
  window is internally coherent: a decision names a winner cluster that
  exists and contains members, failures carry kinds, entropy/margin are
  in range.
* ``lockdep_clean`` — the runtime sanitizer (QUORACLE_LOCKDEP=1)
  observed ZERO lock-order inversions during the storm.
* ``slo_burn_bounded`` — overload resolved through the shed ladder, not
  through unbounded latency: every propagated retry hint is bounded by
  the backoff cap and the queues fully drained by scenario end.
* ``fault_schedule`` (determinism) — the per-key ``(point, key, n,
  kind)`` tuples recovered from the ``chaos_fault`` flight events equal
  the plan ledger's, and a re-run with the same seed reproduces them
  exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from quoracle_tpu.serving.admission import BACKOFF_CAP_MS

# error prefixes the serving stack is ALLOWED to fail a row with — the
# closed set that makes "structured failures only" checkable (these are
# the exact strings QueryResult.error carries; web/consensus layers
# parse the same prefixes)
STRUCTURED_ERROR_PREFIXES: tuple = (
    "admission_rejected:",
    "replica_failed:",
    "deadline_exceeded:",
    "context_overflow:",
    "chaos_injected:",
    "scripted failure",
    "generate failed: chaos_injected:",
)


@dataclasses.dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _result(name: str, ok: bool, detail: str = "") -> InvariantResult:
    return InvariantResult(name=name, ok=bool(ok), detail=detail)


def conservation(name: str, total: int, parts: dict) -> InvariantResult:
    """Shared conservation law: ``total`` equals the sum of ``parts``
    with nothing unaccounted. The chaos no-silent-loss check and the
    sim gate's hibernation-tier census (sim/gate.py) are both
    instances of this shape — a population must be fully partitioned
    into named buckets."""
    s = sum(parts.values())
    return _result(
        name, s == total,
        f"total={total} sum={s} parts=" + ",".join(
            f"{k}:{v}" for k, v in sorted(parts.items())))


def classify(result) -> str:
    """ok | shed | failed for one QueryResult-shaped object."""
    if result is None:
        return "missing"
    if getattr(result, "ok", False):
        return "ok"
    err = getattr(result, "error", "") or ""
    if err.startswith("admission_rejected:") \
            or err.startswith("deadline_exceeded:"):
        return "shed"
    return "failed"


def _stranded_rows(backends: Sequence[Any],
                   settle_s: float = 2.0) -> list[str]:
    """Queued/live rows still parked in any backend scheduler. A row's
    future resolves INSIDE its finishing tick, so a caller that just
    collected results can observe the row in the live list for one more
    tick — poll briefly before calling it stranded."""
    import time
    deadline = time.monotonic() + settle_s
    while True:
        stranded = []
        for b in backends:
            stats = getattr(b, "scheduler_stats", None)
            for name, st in (stats() if stats is not None
                             else {}).items():
                if st.get("queued") or st.get("live"):
                    stranded.append(
                        f"{name}: queued={st.get('queued')} "
                        f"live={st.get('live')}")
        if not stranded or time.monotonic() >= deadline:
            return stranded
        time.sleep(0.05)


def no_silent_loss(submitted: int, results: Sequence[Any],
                   backends: Sequence[Any] = ()) -> InvariantResult:
    """submitted == ok + shed + failed, nothing missing, and no backend
    scheduler still holds queued/live rows (a stranded future IS a
    silent loss with extra steps)."""
    counts = {"ok": 0, "shed": 0, "failed": 0, "missing": 0}
    for r in results:
        counts[classify(r)] += 1
    total = counts["ok"] + counts["shed"] + counts["failed"]
    stranded = _stranded_rows(backends)
    ok = (counts["missing"] == 0 and total == submitted
          and len(results) == submitted and not stranded)
    return _result(
        "no_silent_loss", ok,
        f"submitted={submitted} ok={counts['ok']} shed={counts['shed']} "
        f"failed={counts['failed']} missing={counts['missing']}"
        + (f" stranded={stranded}" if stranded else ""))


def structured_failures(results: Sequence[Any]) -> InvariantResult:
    """Every non-ok result's error is a recognized structured shape;
    replica failures name replica and phase."""
    bad = []
    for i, r in enumerate(results):
        if r is None or getattr(r, "ok", False):
            continue
        err = getattr(r, "error", "") or ""
        if not any(err.startswith(p) for p in STRUCTURED_ERROR_PREFIXES):
            bad.append(f"[{i}] unstructured: {err[:120]}")
        elif err.startswith("replica_failed:") and (
                "replica=" not in err or "phase=" not in err):
            bad.append(f"[{i}] replica failure missing attribution: "
                       f"{err[:120]}")
    return _result("structured_failures", not bad, "; ".join(bad[:6]))


def temp0_equality(clean: Sequence[Any],
                   storm: Sequence[Any]) -> InvariantResult:
    """Index-aligned: every storm row that SURVIVED (ok) must match the
    clean run's text for the same request bit-for-bit. (The clean run
    must itself be fully ok — a broken baseline proves nothing.)"""
    if len(clean) != len(storm):
        return _result("temp0_equality", False,
                       f"result count {len(storm)} != clean {len(clean)}")
    broken_base = [i for i, r in enumerate(clean)
                   if not getattr(r, "ok", False)]
    if broken_base:
        return _result("temp0_equality", False,
                       f"clean baseline rows failed: {broken_base[:6]}")
    diverged = [i for i, (a, b) in enumerate(zip(clean, storm))
                if getattr(b, "ok", False) and b.text != a.text]
    survivors = sum(1 for r in storm if getattr(r, "ok", False))
    return _result(
        "temp0_equality", not diverged,
        f"survivors={survivors}/{len(storm)}"
        + (f" diverged={diverged[:6]}" if diverged else " all bit-equal"))


def audit_coherent(records: Sequence[dict]) -> InvariantResult:
    """Internal coherence of the consensus audit trail: decided records
    name a real winner cluster with members; failures carry kinds;
    entropy/margin within range; decide_ids unique."""
    bad = []
    seen_ids = set()
    for rec in records:
        rid = rec.get("decide_id")
        if rid in seen_ids:
            bad.append(f"duplicate decide_id {rid}")
        seen_ids.add(rid)
        clusters = rec.get("clusters") or []
        widx = rec.get("winner_cluster")
        if rec.get("decision") is not None:
            if widx is None or not (0 <= widx < len(clusters)):
                bad.append(f"{rid}: winner_cluster {widx} not in "
                           f"clusters[{len(clusters)}]")
            elif not clusters[widx].get("members"):
                bad.append(f"{rid}: winner cluster has no members")
        ent = rec.get("entropy_bits")
        if ent is not None and ent < 0:
            bad.append(f"{rid}: negative entropy {ent}")
        margin = rec.get("margin")
        if margin is not None and not (0 <= margin <= 1):
            bad.append(f"{rid}: margin {margin} out of [0,1]")
        for m, info in (rec.get("members") or {}).items():
            f = info.get("failure")
            if f is not None and not f.get("kind"):
                bad.append(f"{rid}: {m} failure without kind")
    return _result("audit_coherent", not bad,
                   f"records={len(records)}"
                   + ("; " + "; ".join(bad[:6]) if bad else ""))


def lockdep_clean() -> InvariantResult:
    """Drain the sanitizer ledger: any inversion observed during the
    storm is a latent ABBA deadlock the chaos run just proved
    reachable."""
    from quoracle_tpu.analysis import lockdep
    if not lockdep.enabled():
        return _result("lockdep_clean", False,
                       "sanitizer disabled — run with QUORACLE_LOCKDEP=1")
    inversions = lockdep.LOCKDEP.drain()
    return _result(
        "lockdep_clean", not inversions,
        "; ".join(f"{i['thread']}: {i['acquiring']} while holding "
                  f"{i['violates']}" for i in inversions[:4])
        or "0 inversions")


RATE_LIMIT_HINT_CAP_MS = 3_600_000      # a bucket-refill hint's sanity bound


def slo_burn_bounded(results: Sequence[Any],
                     backends: Sequence[Any] = (),
                     cap_ms: int = BACKOFF_CAP_MS) -> InvariantResult:
    """Overload resolves through bounded, escalating sheds — every
    OVERLOAD retry hint is within (0, cap]; rate-limit sheds carry
    their bucket's refill time instead, bounded only by the one-hour
    sanity cap (a 0.001 req/s tenant is legitimately told to come back
    in minutes). By scenario end no queue still holds work — latency
    debt fully paid or shed, never parked."""
    bad = []
    for i, r in enumerate(results):
        err = getattr(r, "error", "") or ""
        if "retry_after_ms=" in err:
            try:
                v = int(err.split("retry_after_ms=")[1].split(")")[0]
                        .split(",")[0])
            except ValueError:
                bad.append(f"[{i}] unparseable retry hint: {err[:80]}")
                continue
            bound = (RATE_LIMIT_HINT_CAP_MS if "over its rate" in err
                     else cap_ms)
            if not (0 <= v <= bound):
                bad.append(f"[{i}] retry_after_ms {v} outside "
                           f"[0, {bound}]")
    bad.extend(f"{s} (not drained)" for s in _stranded_rows(backends))
    return _result("slo_burn_bounded", not bad, "; ".join(bad[:6]))


def chaos_events(flight_slice: Sequence[dict]) -> list[tuple]:
    """The sorted fault schedule recovered from a flight-ring slice —
    the production-surface twin of ``FaultPlan.schedule()``."""
    return sorted(
        (e["point"], e.get("key", ""), e["n"], e["fault_kind"])
        for e in flight_slice if e.get("kind") == "chaos_fault")


def fault_schedule(plan, flight_slice: Sequence[dict],
                   expected: Optional[list] = None) -> InvariantResult:
    """Determinism: the ``chaos_fault`` flight events recorded during
    the storm carry exactly the plan ledger's schedule; with
    ``expected`` (a previous run's schedule) also assert the re-run
    reproduced it."""
    from_flight = chaos_events(flight_slice)
    ledger = plan.schedule()
    ok = from_flight == ledger
    detail = (f"fired={len(ledger)}"
              + ("" if ok else
                 f"; flight({len(from_flight)}) != ledger({len(ledger)})"))
    if ok and expected is not None:
        ok = ledger == expected
        if not ok:
            detail += (f"; re-run diverged: {len(ledger)} vs "
                       f"expected {len(expected)}")
        else:
            detail += "; re-run reproduced the schedule"
    return _result("fault_schedule", ok, detail)
