"""Chaos plane (ISSUE 11): deterministic fault injection
(chaos/faults.py), machine-checked recovery invariants
(chaos/invariants.py), and the scenario harness that drives the full
disaggregated stack through scripted storms (chaos/scenarios.py)."""

from quoracle_tpu.chaos.faults import (  # noqa: F401
    CHAOS, ChaosPlane, Fault, FaultPlan, FaultRule, InjectedFault,
    INJECTION_POINTS,
)
