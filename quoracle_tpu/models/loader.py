"""HF checkpoint loading: safetensors → the stacked-layer params pytree.

This is the piece that turns the runtime from a random-weight simulator into
a real model server — the counterpart of the reference's provider layer
fetching real hosted models (reference lib/quoracle/models/model_query.ex:222-259).
A checkpoint directory in the standard HF layout (config.json +
*.safetensors [+ index] + tokenizer.json) is mapped onto the TPU-first
layout of models/transformer.py:

  * per-layer weights are STACKED on a leading [L, ...] axis so the forward
    runs one lax.scan'd layer body (transformer.py design);
  * HF nn.Linear stores [out, in]; our einsum contractions are [in, out],
    so every projection is transposed once at load;
  * params load to bf16 for serving (fp32 available for parity tests).

Supported architectures: LlamaForCausalLM, MistralForCausalLM,
GemmaForCausalLM, Qwen2ForCausalLM — the catalog's model families.
Numerical parity with the torch reference implementations is asserted by
tests/test_loader.py on checkpoints generated locally.

No code is taken from the reference (which has no model math at all,
SURVEY.md §2.8); the mapping follows the public HF checkpoint format.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np

from quoracle_tpu.models.config import ModelConfig, register_model

__all__ = [
    "config_from_hf", "load_checkpoint", "load_params",
    "register_hf_checkpoint",
]


# ---------------------------------------------------------------------------
# config.json → ModelConfig
# ---------------------------------------------------------------------------

_FAMILY_DEFAULTS = {
    # architecture → ModelConfig field overrides beyond the shared mapping
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "Qwen2ForCausalLM": {"attn_bias": True},
    "GemmaForCausalLM": {
        "activation": "gelu",
        "tie_embeddings": True,
        "scale_embeddings": True,
        "rmsnorm_plus_one": True,
    },
}


def _ids(v) -> list[int]:
    """eos_token_id may be an int or a list (llama-3 style); normalize."""
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(v)]


def _rope_scaling(hf: dict) -> Optional[tuple]:
    """Map HF rope_scaling config to the hashable ModelConfig form. Raising
    on unmapped schemes beats silently computing wrong frequencies."""
    rs = hf.get("rope_scaling")
    if not rs:
        return None
    kind = rs.get("rope_type") or rs.get("type")
    if kind in ("default", None):
        return None
    if kind == "linear":
        return ("linear", float(rs["factor"]))
    if kind == "llama3":
        return ("llama3", float(rs["factor"]),
                float(rs.get("low_freq_factor", 1.0)),
                float(rs.get("high_freq_factor", 4.0)),
                int(rs.get("original_max_position_embeddings", 8192)))
    raise ValueError(
        f"unsupported rope_scaling type {kind!r} — supported: default, "
        "linear, llama3")


def config_from_hf(hf: dict, name: str,
                   checkpoint_path: Optional[str] = None) -> ModelConfig:
    """Map a HF config.json dict onto the in-tree ModelConfig."""
    archs = hf.get("architectures") or []
    arch = archs[0] if archs else "LlamaForCausalLM"
    if arch not in _FAMILY_DEFAULTS:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: "
            f"{sorted(_FAMILY_DEFAULTS)}")
    over = dict(_FAMILY_DEFAULTS[arch])

    n_heads = hf["num_attention_heads"]
    kv = hf.get("num_key_value_heads") or n_heads
    act = hf.get("hidden_act", "silu")
    if act in ("gelu", "gelu_pytorch_tanh", "gelu_new"):
        over["activation"] = "gelu"
    elif act == "silu":
        over.setdefault("activation", "silu")
    else:
        raise ValueError(f"unsupported hidden_act {act!r}")
    if hf.get("tie_word_embeddings"):
        over["tie_embeddings"] = True
    if hf.get("attention_bias"):
        over["attn_bias"] = True

    window = int(hf.get("max_position_embeddings", 8192))
    eos_ids = _ids(hf.get("eos_token_id"))
    bos_ids = _ids(hf.get("bos_token_id"))
    # Qw2-style configs keep sliding_window populated while explicitly
    # disabling it; honor the switch.
    sliding = hf.get("sliding_window")
    if hf.get("use_sliding_window") is False:
        sliding = None
    # Multimodal checkpoints (make_checkpoint --families vlm, or any dir
    # using the in-tree serialization): a ``vision_config`` section marks
    # the ViT tower (models/vision.py) whose weights live under
    # vision_tower.* / multi_modal_projector.* in the safetensors.
    vision = None
    image_token_id = None
    vc = hf.get("vision_config")
    if vc:
        from quoracle_tpu.models.vision import VisionConfig
        vision = VisionConfig(
            image_size=vc["image_size"],
            patch_size=vc["patch_size"],
            dim=vc["hidden_size"],
            n_layers=vc["num_hidden_layers"],
            n_heads=vc["num_attention_heads"],
            ffn_dim=vc["intermediate_size"],
            out_dim=hf["hidden_size"],
        )
        if hf.get("image_token_id") is None:
            raise ValueError(
                "vision_config present but no image_token_id — the prompt "
                "builder cannot place soft tokens without it")
        image_token_id = int(hf["image_token_id"])
    return ModelConfig(
        name=name,
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=kv,
        ffn_dim=hf["intermediate_size"],
        head_dim=hf.get("head_dim"),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=_rope_scaling(hf),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        sliding_window=sliding,
        context_window=window,
        output_limit=min(4096, window),
        # 0 is a legitimate token id — explicit None checks, not `or`.
        eos_token_id=eos_ids[0] if eos_ids else 2,
        stop_token_ids=tuple(eos_ids[1:]),
        bos_token_id=bos_ids[0] if bos_ids else 1,
        checkpoint_path=checkpoint_path,
        vision=vision,
        image_token_id=image_token_id,
        **over,
    )


# ---------------------------------------------------------------------------
# safetensors → stacked pytree
# ---------------------------------------------------------------------------

class _ShardedReader:
    """Reads tensors by name across single-file or index-sharded layouts.

    Tensors come out as numpy (bf16 via ml_dtypes), loaded lazily per shard
    so host peak memory stays ~one shard + the stack under construction.
    """

    def __init__(self, path: str):
        self.path = path
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.isfile(index):
            with open(index) as f:
                self._name_to_file = json.load(f)["weight_map"]
        else:
            files = sorted(fn for fn in os.listdir(path)
                           if fn.endswith(".safetensors"))
            if not files:
                raise FileNotFoundError(
                    f"no .safetensors files under {path!r}")
            self._name_to_file = None
            self._files = files
        self._handles: dict[str, object] = {}
        self._all_names: Optional[set] = None

    def _open(self, fn: str):
        from safetensors import safe_open
        if fn not in self._handles:
            self._handles[fn] = safe_open(
                os.path.join(self.path, fn), framework="pt", device="cpu")
        return self._handles[fn]

    def names(self) -> set:
        if self._all_names is None:
            if self._name_to_file is not None:
                self._all_names = set(self._name_to_file)
            else:
                self._all_names = set()
                for fn in self._files:
                    self._all_names |= set(self._open(fn).keys())
        return self._all_names

    def get(self, name: str) -> np.ndarray:
        if self._name_to_file is not None:
            h = self._open(self._name_to_file[name])
        else:
            h = None
            for fn in self._files:
                if name in self._open(fn).keys():
                    h = self._open(fn)
                    break
            if h is None:
                raise KeyError(name)
        return _torch_to_numpy(h.get_tensor(name))

    def close(self) -> None:
        self._handles.clear()


def _torch_to_numpy(t) -> np.ndarray:
    """torch tensor → numpy, routing bf16 through ml_dtypes (numpy has no
    native bfloat16)."""
    import torch
    import ml_dtypes
    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _cast(a: np.ndarray, dtype) -> np.ndarray:
    import ml_dtypes  # noqa: F401  (registers bf16 with numpy casting)
    return a.astype(dtype, copy=False)   # no copy when already the dtype


def load_params(path: str, cfg: ModelConfig, dtype=None) -> dict:
    """Read a HF checkpoint directory into the stacked-layer params pytree
    (transformer.init_params structure). ``dtype`` defaults to bf16."""
    import ml_dtypes
    dtype = dtype or ml_dtypes.bfloat16
    r = _ShardedReader(path)
    names = r.names()
    # Some exports prefix everything with "model." — normalize access.
    pre = "model." if "model.embed_tokens.weight" in names else ""

    def g(name: str, transpose: bool = False) -> np.ndarray:
        a = r.get(name)
        if transpose:
            a = a.T
        return _cast(a, dtype)

    L = cfg.n_layers

    def stack(fmt: str, transpose: bool = False) -> np.ndarray:
        return np.stack([g(fmt.format(i=i), transpose) for i in range(L)])

    lp = pre + "layers.{i}."
    layers = {
        "attn_norm": stack(lp + "input_layernorm.weight"),
        "wq": stack(lp + "self_attn.q_proj.weight", transpose=True),
        "wk": stack(lp + "self_attn.k_proj.weight", transpose=True),
        "wv": stack(lp + "self_attn.v_proj.weight", transpose=True),
        "wo": stack(lp + "self_attn.o_proj.weight", transpose=True),
        "mlp_norm": stack(lp + "post_attention_layernorm.weight"),
        "w_gate": stack(lp + "mlp.gate_proj.weight", transpose=True),
        "w_up": stack(lp + "mlp.up_proj.weight", transpose=True),
        "w_down": stack(lp + "mlp.down_proj.weight", transpose=True),
    }
    if cfg.attn_bias:
        layers["bq"] = stack(lp + "self_attn.q_proj.bias")
        layers["bk"] = stack(lp + "self_attn.k_proj.bias")
        layers["bv"] = stack(lp + "self_attn.v_proj.bias")

    params = {
        "embed": g(pre + "embed_tokens.weight"),
        "layers": layers,
        "final_norm": g(pre + "norm.weight"),
    }
    if not cfg.tie_embeddings:
        # HF omits lm_head from the file when tied; when untied it's at the
        # top level regardless of the "model." prefix.
        params["lm_head"] = g("lm_head.weight", transpose=True)
    if cfg.vision is not None:
        # ViT tower + projector (in-tree serialization, make_checkpoint
        # vlm family) → the init_vision_params pytree layout with layers
        # stacked on [L, ...] for the tower's lax.scan.
        VL = cfg.vision.n_layers
        vp = "vision_tower.layers.{i}."

        def vstack(fmt: str, transpose: bool = False) -> np.ndarray:
            return np.stack([g(fmt.format(i=i), transpose)
                             for i in range(VL)])

        params["vision"] = {
            "patch_embed": g("vision_tower.patch_embed.weight",
                             transpose=True),
            "pos_embed": g("vision_tower.pos_embed"),
            "layers": {
                "ln1": vstack(vp + "ln1.weight"),
                "wqkv": vstack(vp + "attn.qkv_proj.weight", transpose=True),
                "wo": vstack(vp + "attn.o_proj.weight", transpose=True),
                "ln2": vstack(vp + "ln2.weight"),
                "w_up": vstack(vp + "mlp.up_proj.weight", transpose=True),
                "w_down": vstack(vp + "mlp.down_proj.weight",
                                 transpose=True),
            },
            "final_ln": g("vision_tower.final_ln.weight"),
            "projector": g("multi_modal_projector.weight", transpose=True),
        }
    r.close()
    return params


def export_hf_checkpoint(params: dict, cfg: ModelConfig, out_dir: str,
                         base_dir: str) -> str:
    """Inverse of load_params: the stacked-layer pytree → an HF checkpoint
    directory (model.safetensors under the HF tensor names + config/
    tokenizer files copied from ``base_dir``). This closes the
    train → serve loop (VERDICT r4 item 5): models/train.py fine-tunes,
    this exports, register_hf_checkpoint serves the result through the
    standard path — a lifecycle the reference cannot express (its models
    are hosted APIs, SURVEY §2.3). Text decoder only (the fine-tuning
    substrate); bf16 on disk like every HF checkpoint we emit."""
    import shutil

    import torch
    from safetensors.torch import save_file

    os.makedirs(out_dir, exist_ok=True)
    for fn in ("config.json", "tokenizer.json", "tokenizer_config.json"):
        src = os.path.join(base_dir, fn)
        if os.path.isfile(src):
            shutil.copy(src, os.path.join(out_dir, fn))

    def t(a, transpose: bool = False) -> "torch.Tensor":
        a = np.asarray(jax.device_get(a), dtype=np.float32)
        if transpose:
            a = a.T
        return torch.from_numpy(np.ascontiguousarray(a)).to(torch.bfloat16)

    lay = params["layers"]
    tensors = {"model.embed_tokens.weight": t(params["embed"]),
               "model.norm.weight": t(params["final_norm"])}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = t(lay["attn_norm"][i])
        tensors[p + "self_attn.q_proj.weight"] = t(lay["wq"][i], True)
        tensors[p + "self_attn.k_proj.weight"] = t(lay["wk"][i], True)
        tensors[p + "self_attn.v_proj.weight"] = t(lay["wv"][i], True)
        tensors[p + "self_attn.o_proj.weight"] = t(lay["wo"][i], True)
        tensors[p + "post_attention_layernorm.weight"] = t(lay["mlp_norm"][i])
        tensors[p + "mlp.gate_proj.weight"] = t(lay["w_gate"][i], True)
        tensors[p + "mlp.up_proj.weight"] = t(lay["w_up"][i], True)
        tensors[p + "mlp.down_proj.weight"] = t(lay["w_down"][i], True)
        if cfg.attn_bias:
            tensors[p + "self_attn.q_proj.bias"] = t(lay["bq"][i])
            tensors[p + "self_attn.k_proj.bias"] = t(lay["bk"][i])
            tensors[p + "self_attn.v_proj.bias"] = t(lay["bv"][i])
    if not cfg.tie_embeddings:
        tensors["lm_head.weight"] = t(params["lm_head"], True)
    save_file(tensors, os.path.join(out_dir, "model.safetensors"),
              metadata={"format": "pt"})
    with open(os.path.join(out_dir, ".complete"), "w") as f:
        f.write("ok\n")
    return out_dir


def to_device(params: dict) -> dict:
    """Move a numpy params pytree onto the default device LEAF BY LEAF,
    dropping each host array as soon as its device copy exists — at 8B bf16
    scale a whole-tree jax.tree.map would hold ~16 GB host + ~16 GB device
    simultaneously; this caps host residency at one stacked param."""
    import jax.numpy as jnp

    def rec(d: dict) -> None:
        for k, v in d.items():
            if isinstance(v, dict):
                rec(v)
            else:
                d[k] = jnp.asarray(v)   # replaces the numpy ref in place
    rec(params)
    return params


def _read_config(path: str, name: Optional[str]) -> ModelConfig:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    return config_from_hf(hf, name or os.path.basename(os.path.normpath(path)),
                          checkpoint_path=path)


def load_checkpoint(path: str, name: Optional[str] = None,
                    dtype=None) -> tuple[ModelConfig, dict]:
    """config.json + safetensors → (ModelConfig, params pytree)."""
    cfg = _read_config(path, name)
    return cfg, load_params(path, cfg, dtype)


def register_hf_checkpoint(path: str, name: Optional[str] = None) -> ModelConfig:
    """Register a checkpoint directory into the model catalog so the pool can
    reference it as ``xla:<name>``. Params load when an engine is built
    (TPUBackend checks cfg.checkpoint_path), not at registration."""
    return register_model(_read_config(path, name))
