"""Local HF-format checkpoint factory.

The bench host has no network, so real released checkpoints cannot be
downloaded — but the checkpoint-serving path (models/loader.py →
HFAutoTokenizer → TPUBackend) must still be exercised end-to-end at bench
scale (VERDICT r2 item 2). This module GENERATES checkpoints in the standard
HF layout entirely offline:

  * ``config.json``      — per-family HF config (Llama/Mistral/Gemma);
  * ``model.safetensors``— bf16 weights under the HF tensor names, random
    with fan-in scaling (same spectrum as transformer.init_params, so
    generation produces finite logits — text quality is irrelevant, the
    bench measures serving compute);
  * ``tokenizer.json``   — a REAL byte-level BPE tokenizer trained with the
    ``tokenizers`` library on local corpus text (this repo's sources by
    default);
  * ``tokenizer_config.json`` — special tokens + a chat template, so
    HFAutoTokenizer serves the checkpoint's own template exactly as it
    would for a released model.

The output directory round-trips through the SAME code path a user's real
downloaded Llama/Mistral/Gemma checkpoint takes (register_hf_checkpoint →
load_params → AutoTokenizer), with torch-parity already asserted by
tests/test_loader.py.

Usage:
    python -m quoracle_tpu.models.make_checkpoint --out checkpoints/ \
        --families llama,mistral,gemma --scale 1b
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

SPECIALS = ["<|pad|>", "<|bos|>", "<|eos|>", "<|system|>", "<|user|>",
            "<|assistant|>", "<|image|>"]
CHAT_TEMPLATE = (
    "{{ bos_token }}{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}\n"
    "{% endfor %}{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)

# HF config.json skeletons per family at bench scale — dimensioned to match
# the catalog's bench models (config.py llama-1b / mistral-1b / gemma-1b) so
# the checkpoint pool mirrors the random-init bench pool exactly.
FAMILY_CONFIGS = {
    "1b": {
        "llama": dict(
            architectures=["LlamaForCausalLM"], vocab_size=32768,
            hidden_size=2048, intermediate_size=5632, num_hidden_layers=16,
            num_attention_heads=16, num_key_value_heads=4,
            max_position_embeddings=8192, rope_theta=500000.0,
            rms_norm_eps=1e-5, hidden_act="silu", tie_word_embeddings=False),
        "mistral": dict(
            architectures=["MistralForCausalLM"], vocab_size=32768,
            hidden_size=2048, intermediate_size=5632, num_hidden_layers=16,
            num_attention_heads=16, num_key_value_heads=4,
            max_position_embeddings=16384, rope_theta=1000000.0,
            rms_norm_eps=1e-5, hidden_act="silu", sliding_window=4096,
            tie_word_embeddings=False),
        "gemma": dict(
            architectures=["GemmaForCausalLM"], vocab_size=32768,
            hidden_size=1792, intermediate_size=7168, num_hidden_layers=14,
            num_attention_heads=14, num_key_value_heads=14, head_dim=128,
            max_position_embeddings=8192, rope_theta=10000.0,
            rms_norm_eps=1e-6, hidden_act="gelu_pytorch_tanh",
            tie_word_embeddings=True),
        # VLM: llama-1b decoder + in-tree ViT tower (LLaVA-style soft
        # tokens). vision_config marks the checkpoint as multimodal; the
        # tower weights serialize under vision_tower.* / multi_modal_
        # projector.* (loader.py layout — in-tree scheme, no released-VLM
        # weight mapping yet, models/vision.py docstring).
        "vlm": dict(
            architectures=["LlamaForCausalLM"], vocab_size=32768,
            hidden_size=2048, intermediate_size=5632, num_hidden_layers=16,
            num_attention_heads=16, num_key_value_heads=4,
            max_position_embeddings=8192, rope_theta=500000.0,
            rms_norm_eps=1e-5, hidden_act="silu", tie_word_embeddings=False,
            vision_config=dict(
                image_size=224, patch_size=14, hidden_size=512,
                num_hidden_layers=6, num_attention_heads=8,
                intermediate_size=2048)),
    },
    # "small": big enough to LEARN rigid formats (tools/finetune.py closes
    # the train->serve loop with it on CPU-only hosts), small enough that a
    # few hundred optimizer steps are minutes, not hours.
    "small": {
        "llama": dict(
            architectures=["LlamaForCausalLM"], vocab_size=2048,
            hidden_size=256, intermediate_size=1024, num_hidden_layers=4,
            num_attention_heads=8, num_key_value_heads=4,
            max_position_embeddings=2048, rope_theta=10000.0,
            rms_norm_eps=1e-5, hidden_act="silu", tie_word_embeddings=False),
    },
    "tiny": {
        "llama": dict(
            architectures=["LlamaForCausalLM"], vocab_size=2048,
            hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=2048, rope_theta=10000.0,
            rms_norm_eps=1e-5, hidden_act="silu", tie_word_embeddings=False),
        "gemma": dict(
            architectures=["GemmaForCausalLM"], vocab_size=2048,
            hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, head_dim=16,
            max_position_embeddings=2048, rope_theta=10000.0,
            rms_norm_eps=1e-6, hidden_act="gelu_pytorch_tanh",
            tie_word_embeddings=True),
        "vlm": dict(
            architectures=["LlamaForCausalLM"], vocab_size=2048,
            hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=2048, rope_theta=10000.0,
            rms_norm_eps=1e-5, hidden_act="silu", tie_word_embeddings=False,
            vision_config=dict(
                image_size=28, patch_size=14, hidden_size=32,
                num_hidden_layers=1, num_attention_heads=2,
                intermediate_size=64)),
    },
}


def default_corpus(max_bytes: int = 8 << 20) -> Iterable[str]:
    """Local training text for the BPE: this repo's own source + docs."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    total = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in (".git", "checkpoints", "__pycache__")]
        for fn in sorted(filenames):
            if not fn.endswith((".py", ".md", ".txt", ".cpp", ".json")):
                continue
            try:
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8", errors="ignore") as f:
                    text = f.read()
            except OSError:
                continue
            total += len(text)
            yield text
            if total > max_bytes:
                return


def make_tokenizer_files(out_dir: str, vocab_size: int,
                         corpus: Optional[Iterable[str]] = None) -> dict:
    """Train a byte-level BPE with the ``tokenizers`` library and write
    tokenizer.json + tokenizer_config.json (special tokens, chat template).
    Returns {token: id} for the specials."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size, special_tokens=list(SPECIALS),
        show_progress=False)
    tok.train_from_iterator(corpus or default_corpus(), trainer)
    tok.save(os.path.join(out_dir, "tokenizer.json"))
    ids = {s: tok.token_to_id(s) for s in SPECIALS}
    with open(os.path.join(out_dir, "tokenizer_config.json"), "w") as f:
        json.dump({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "bos_token": "<|bos|>", "eos_token": "<|eos|>",
            "pad_token": "<|pad|>",
            "chat_template": CHAT_TEMPLATE,
            "model_max_length": 1 << 20,
        }, f, indent=1)
    return ids


def write_weights(out_dir: str, hf: dict, seed: int = 0) -> None:
    """Random bf16 weights under HF tensor names → model.safetensors.

    Tensors are emitted one at a time straight into the save dict (torch
    keeps them materialized until save_file, ~2 GB at 1b scale — fine).
    Fan-in scaling keeps the forward finite, like transformer.init_params.
    """
    import torch
    from safetensors.torch import save_file
    g = torch.Generator().manual_seed(seed)
    D = hf["hidden_size"]
    F = hf["intermediate_size"]
    H = hf["num_attention_heads"]
    KV = hf.get("num_key_value_heads") or H
    HD = hf.get("head_dim") or D // H
    V = hf["vocab_size"]
    gemma = hf["architectures"][0] == "GemmaForCausalLM"

    def w(out_f: int, in_f: int) -> "torch.Tensor":
        return (torch.randn(out_f, in_f, generator=g)
                * in_f ** -0.5).to(torch.bfloat16)

    def norm(n: int) -> "torch.Tensor":
        # HF Gemma RMSNorm computes (1 + w) * x̂ — zero is identity there.
        return (torch.zeros(n) if gemma else torch.ones(n)).to(torch.bfloat16)

    tensors = {"model.embed_tokens.weight": w(V, D),
               "model.norm.weight": norm(D)}
    for i in range(hf["num_hidden_layers"]):
        p = f"model.layers.{i}."
        tensors[p + "input_layernorm.weight"] = norm(D)
        tensors[p + "self_attn.q_proj.weight"] = w(H * HD, D)
        tensors[p + "self_attn.k_proj.weight"] = w(KV * HD, D)
        tensors[p + "self_attn.v_proj.weight"] = w(KV * HD, D)
        tensors[p + "self_attn.o_proj.weight"] = w(D, H * HD)
        tensors[p + "post_attention_layernorm.weight"] = norm(D)
        tensors[p + "mlp.gate_proj.weight"] = w(F, D)
        tensors[p + "mlp.up_proj.weight"] = w(F, D)
        tensors[p + "mlp.down_proj.weight"] = w(D, F)
    if not hf.get("tie_word_embeddings"):
        tensors["lm_head.weight"] = w(V, D)
    vc = hf.get("vision_config")
    if vc:
        # ViT tower + projector under the loader's in-tree VLM layout
        # (loader.load_params vision subtree; models/vision.py structure).
        VD = vc["hidden_size"]
        VF = vc["intermediate_size"]
        patch_dim = vc["patch_size"] ** 2 * 3
        n_patches = (vc["image_size"] // vc["patch_size"]) ** 2
        tensors["vision_tower.patch_embed.weight"] = w(VD, patch_dim)
        tensors["vision_tower.pos_embed"] = w(n_patches, VD)
        for i in range(vc["num_hidden_layers"]):
            p = f"vision_tower.layers.{i}."
            tensors[p + "ln1.weight"] = torch.ones(VD).to(torch.bfloat16)
            tensors[p + "attn.qkv_proj.weight"] = w(3 * VD, VD)
            tensors[p + "attn.o_proj.weight"] = w(VD, VD)
            tensors[p + "ln2.weight"] = torch.ones(VD).to(torch.bfloat16)
            tensors[p + "mlp.up_proj.weight"] = w(VF, VD)
            tensors[p + "mlp.down_proj.weight"] = w(VD, VF)
        tensors["vision_tower.final_ln.weight"] = \
            torch.ones(VD).to(torch.bfloat16)
        tensors["multi_modal_projector.weight"] = w(D, VD)
    save_file(tensors, os.path.join(out_dir, "model.safetensors"),
              metadata={"format": "pt"})


def make_checkpoint(out_dir: str, family: str = "llama", scale: str = "1b",
                    seed: int = 0,
                    corpus: Optional[Iterable[str]] = None) -> str:
    """Generate one complete HF checkpoint directory. Idempotent: an
    existing complete directory is left untouched (bench reuse)."""
    marker = os.path.join(out_dir, ".complete")
    needed = ("config.json", "model.safetensors", "tokenizer.json",
              "tokenizer_config.json")
    if os.path.isfile(marker) and all(
            os.path.isfile(os.path.join(out_dir, f)) for f in needed):
        return out_dir
    os.makedirs(out_dir, exist_ok=True)
    hf = dict(FAMILY_CONFIGS[scale][family])
    ids = make_tokenizer_files(out_dir, hf["vocab_size"], corpus)
    hf["bos_token_id"] = ids["<|bos|>"]
    hf["eos_token_id"] = ids["<|eos|>"]
    hf["pad_token_id"] = ids["<|pad|>"]
    if "vision_config" in hf:
        hf["image_token_id"] = ids["<|image|>"]
    hf["torch_dtype"] = "bfloat16"
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf, f, indent=1)
    write_weights(out_dir, hf, seed=seed)
    with open(marker, "w") as f:
        f.write("ok\n")
    return out_dir


def make_bench_checkpoints(root: str, scale: str = "1b",
                           families: Optional[list[str]] = None) -> list[str]:
    """The bench pool's checkpoint trio under ``root``; returns the dirs."""
    families = families or sorted(FAMILY_CONFIGS[scale])
    return [make_checkpoint(os.path.join(root, f"{fam}-{scale}"),
                            family=fam, scale=scale, seed=i)
            for i, fam in enumerate(families)]


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True, help="output root directory")
    ap.add_argument("--families", default="llama,mistral,gemma")
    ap.add_argument("--scale", default="1b", choices=sorted(FAMILY_CONFIGS))
    args = ap.parse_args()
    dirs = make_bench_checkpoints(args.out, scale=args.scale,
                                  families=args.families.split(","))
    print(json.dumps({"checkpoints": dirs}))


if __name__ == "__main__":
    main()
