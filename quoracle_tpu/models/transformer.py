"""Decoder-only transformer forward pass, pure JAX.

One traced function serves every family in the catalog (Llama/Mistral/Gemma
quirks are ModelConfig data — see models/config.py). Design choices are
TPU-first, not a translation of anything in the reference (which runs no model
math locally, SURVEY.md §2.8):

  * layers are STACKED on a leading axis and iterated with ``lax.scan`` —
    one compiled layer body regardless of depth (fast compiles, XLA-friendly);
  * params live in bf16; layernorm/softmax math in fp32;
  * KV cache is a position-ordered padded buffer updated in-place via
    ``lax.dynamic_update_slice_in_dim``; attention masks by integer lengths,
    so the whole step is shape-static under jit;
  * the same forward serves prefill (T = chunk) and decode (T = 1).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.quant import (
    dequant_weight, is_quantized, kv_quant,
)
from quoracle_tpu.ops.attention import attend


class KVCache(NamedTuple):
    """Per-model KV buffer. k/v: [L, B, S, n_kv, head_dim]; lens: [B]."""

    k: jax.Array
    v: jax.Array
    lens: jax.Array  # int32 valid length per row

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: ModelConfig, batch: int, max_len: int,
               dtype=jnp.bfloat16) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lens=jnp.zeros((batch,), jnp.int32),
    )


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> dict:
    """Random-init params pytree (normal/sqrt(dim)) — tests and bench only.
    Real checkpoints load through models/loader.py (same structure, weights
    from safetensors)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    lk = jax.random.split(k_layers, 7)
    params = {
        "embed": normal(k_embed, (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": normal(lk[0], (L, D, H * HD), D),
            "wk": normal(lk[1], (L, D, KV * HD), D),
            "wv": normal(lk[2], (L, D, KV * HD), D),
            "wo": normal(lk[3], (L, H * HD, D), H * HD),
            "mlp_norm": jnp.ones((L, D), dtype),
            "w_gate": normal(lk[4], (L, D, F), D),
            "w_up": normal(lk[5], (L, D, F), D),
            "w_down": normal(lk[6], (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
    }
    if cfg.attn_bias:
        params["layers"]["bq"] = jnp.zeros((L, H * HD), dtype)
        params["layers"]["bk"] = jnp.zeros((L, KV * HD), dtype)
        params["layers"]["bv"] = jnp.zeros((L, KV * HD), dtype)
    if cfg.rmsnorm_plus_one:
        # Gemma norm weights are a delta around 1; zero-init matches identity.
        params["layers"]["attn_norm"] = jnp.zeros((L, D), dtype)
        params["layers"]["mlp_norm"] = jnp.zeros((L, D), dtype)
        params["final_norm"] = jnp.zeros((D,), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(k_head, (D, cfg.vocab_size), D)
    if cfg.vision is not None:
        from quoracle_tpu.models.vision import init_vision_params
        assert cfg.vision.out_dim == cfg.dim, \
            "vision projector must target the decoder dim"
        params["vision"] = init_vision_params(
            cfg.vision, jax.random.fold_in(k_head, 7), dtype)
    return params


def rmsnorm(x: jax.Array, w: jax.Array, eps: float, plus_one: bool) -> jax.Array:
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    wf = w.astype(jnp.float32)
    if plus_one:
        wf = 1.0 + wf
    return (normed * wf).astype(x.dtype)


def _scale_rope_freqs(freqs: jax.Array, scaling: Optional[tuple]) -> jax.Array:
    """Apply HF-style rope_scaling to inverse frequencies.

    ("linear", factor): freqs / factor.
    ("llama3", factor, low_ff, high_ff, orig_max): long wavelengths divided
    by factor, short kept, smooth ramp between — matching the llama-3.1
    frequency-scaling scheme every 3.1/3.2 checkpoint ships in config.json.
    """
    if scaling is None:
        return freqs
    kind = scaling[0]
    if kind == "linear":
        return freqs / scaling[1]
    if kind == "llama3":
        _, factor, low_ff, high_ff, orig_max = scaling
        wavelen = 2.0 * jnp.pi / freqs
        low_wl = orig_max / low_ff
        high_wl = orig_max / high_ff
        smooth = (orig_max / wavelen - low_ff) / (high_ff - low_ff)
        interp = (1.0 - smooth) * freqs / factor + smooth * freqs
        out = jnp.where(wavelen > low_wl, freqs / factor,
                        jnp.where(wavelen < high_wl, freqs, interp))
        return out
    raise ValueError(f"unsupported rope scaling {kind!r}")


def rope(x: jax.Array, positions: jax.Array, theta: float,
         scaling: Optional[tuple] = None) -> jax.Array:
    """Rotary embedding. x: [B, T, heads, hd]; positions: [B, T]."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    freqs = _scale_rope_freqs(freqs, scaling)
    angles = positions.astype(jnp.float32)[:, :, None, None] * freqs  # [B,T,1,half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _activation(x: jax.Array, kind: str) -> jax.Array:
    if kind == "silu":
        return jax.nn.silu(x)
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {kind!r}")


def _embed_lookup(params: dict, tokens: jax.Array) -> jax.Array:
    """Embedding gather, int8-aware: a quantized embed gathers the int8
    rows plus their per-row scales and dequantizes only the looked-up
    rows (never the whole [V, D] table)."""
    e = params["embed"]
    if is_quantized(e):
        q = e["q8"][tokens].astype(jnp.float32)
        s = e["scale_r"][tokens]
        # activations run at the UNQUANTIZED leaves' dtype (norms stay
        # dense) — bf16 serving, fp32 parity tests
        return (q * s[..., None]).astype(params["final_norm"].dtype)
    return e[tokens]


def _mlp(x: jax.Array, p: dict, cfg: ModelConfig) -> jax.Array:
    """The shared MLP block: rmsnorm → gate·up → down, weights
    dequantized on the fly when quantized (models/quant.py). One
    implementation so the four forward variants can never drift."""
    h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    gate = _activation(
        jnp.einsum("btd,df->btf", h, dequant_weight(p["w_gate"], h.dtype)),
        cfg.activation)
    up = jnp.einsum("btd,df->btf", h, dequant_weight(p["w_up"], h.dtype))
    return x + jnp.einsum("btf,fd->btd", gate * up,
                          dequant_weight(p["w_down"], h.dtype))


def _qkv(x: jax.Array, p: dict, cfg: ModelConfig, B: int,
         T: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The shared attention-input block: rmsnorm → q/k/v projections
    (+ optional bias) reshaped to head layout, weights dequantized on
    the fly when quantized."""
    h = rmsnorm(x, p["attn_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    q = jnp.einsum("btd,dh->bth", h, dequant_weight(p["wq"], h.dtype))
    k = jnp.einsum("btd,dh->bth", h, dequant_weight(p["wk"], h.dtype))
    v = jnp.einsum("btd,dh->bth", h, dequant_weight(p["wv"], h.dtype))
    if cfg.attn_bias:               # Qwen2-style QKV biases
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    q = q.reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    return q, k, v


def _wo(p: dict, cfg: ModelConfig, dtype) -> jax.Array:
    return dequant_weight(p["wo"], dtype).reshape(
        cfg.n_heads, cfg.head_dim, cfg.dim)


def forward_hidden(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, T] int32
    positions: jax.Array,    # [B, T] int32 absolute positions
    cache: KVCache,
    write_offset: jax.Array,  # [B] int32: where this chunk's kv entries land
    kv_lens: jax.Array,       # [B] int32 valid kv count AFTER this chunk
    kv_pos_offset: Optional[jax.Array] = None,  # [B] int32: absolute position
                                                # of kv buffer index 0
    ring: Optional[tuple] = None,   # (mesh, seq_axis, batch_axis, head_axis):
                                    # sequence-parallel prefill — attention
                                    # runs as ring_attend over the chunk
                                    # itself (fresh full-prompt prefill only)
    input_embeds: Optional[jax.Array] = None,   # [B, T, D] overrides the
                                    # embedding lookup (VLM soft tokens).
                                    # Callers pass these FULLY PREPARED —
                                    # scale_embeddings is NOT re-applied
                                    # (image features splice in unscaled,
                                    # matching standard VLM semantics)
) -> tuple[jax.Array, KVCache]:
    """Run the stack over a token chunk, updating the cache; returns final
    hidden states [B, T, D] (pre-head) — see project_logits.

    The kv buffer is position-ordered (a token at absolute position p lives at
    buffer index p), so right-padded prompt rows simply leave garbage beyond
    ``kv_lens[b]`` which the attention validity mask ignores; decode later
    overwrites index ``lens[b]`` with the real next token.

    The caller advances ``cache.lens`` — keeping length bookkeeping out of
    the traced body lets the same trace serve speculative / chunked prefill.
    """
    B, T = tokens.shape
    if input_embeds is not None:
        x = input_embeds                # prepared by the caller (VLM)
    else:
        x = _embed_lookup(params, tokens)   # gather: [B, T, D]
        if cfg.scale_embeddings:
            x = (x.astype(jnp.float32) * (cfg.dim ** 0.5)).astype(x.dtype)

    # Offsets are per-row; rows share one buffer write position only when all
    # offsets are equal. We write per-row with a vmap'd dynamic slice.
    def write_row(buf_l, new_l, off):
        # buf_l: [S, n_kv, hd]; new_l: [T, n_kv, hd]
        return jax.lax.dynamic_update_slice_in_dim(buf_l, new_l, off, axis=0)

    def layer_body(x, scanned):
        p, k_buf, v_buf = scanned  # p: one layer's params; bufs: [B, S, kv, hd]
        q, k, v = _qkv(x, p, cfg, B, T)
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

        k_buf = jax.vmap(write_row)(k_buf, k, write_offset)
        v_buf = jax.vmap(write_row)(v_buf, v, write_offset)

        if ring is not None:
            # Sequence-parallel prefill: the chunk IS the whole (fresh)
            # prompt, so attention is chunk-vs-chunk — K/V shards rotate
            # the ring while each device keeps its Q shard (SURVEY §5
            # long-context; ops/ring_attention.py).
            from quoracle_tpu.ops.ring_attention import ring_attend
            mesh_, seq_ax, batch_ax, head_ax = ring
            attn = ring_attend(mesh_, q, k, v, kv_len=kv_lens,
                               axis_name=seq_ax,
                               sliding_window=cfg.sliding_window,
                               batch_axis=batch_ax, head_axis=head_ax)
        else:
            # attend_auto: pallas flash kernel for long prefill chunks on
            # TPU, dense fused XLA otherwise (decode steps, CPU tests).
            from quoracle_tpu.ops.flash_attention import attend_auto
            attn = attend_auto(q, k_buf, v_buf, positions,
                               kv_len=kv_lens,
                               sliding_window=cfg.sliding_window,
                               kv_pos_offset=kv_pos_offset)
        x = x + jnp.einsum("bthd,hdD->btD", attn, _wo(p, cfg, x.dtype))
        x = _mlp(x, p, cfg)
        return x, (k_buf, v_buf)

    x, (new_k, new_v) = jax.lax.scan(layer_body, x, (params["layers"], cache.k, cache.v))

    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    return x, KVCache(k=new_k, v=new_v, lens=cache.lens)


def forward_hidden_paged(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, 1] int32 (decode step)
    positions: jax.Array,    # [B, 1] int32 absolute positions
    k_pool: jax.Array,       # [L, n_pages, page, n_kv, hd] — read-only
    v_pool: jax.Array,
    tables: jax.Array,       # [B, maxp] int32 page table
    pool_lens: jax.Array,    # [B] int32 valid pool tokens (fixed in decode)
    kv_off: jax.Array,       # [B] int32 absolute position of pool index 0
    tail_k: jax.Array,       # [L, B, Tmax, n_kv, hd] generated-token KV
    tail_v: jax.Array,
    step: jax.Array,         # scalar int32: tail slot this token writes
    shard: Optional[tuple] = None,   # (mesh, tp_axis, dp_axis|None)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Decode-step forward against the PAGED session pool: attention reads
    the row's pages directly (ops/paged_attention.py — ragged, only
    ceil(pool_lens/page) pages stream per row) merged with the dense tail
    of tokens generated this call. The pool is never gathered into a
    contiguous working cache (NOTES_r03 gap 2). Returns (hidden [B, 1, D],
    new tail_k, new tail_v)."""
    from quoracle_tpu.ops.paged_attention import paged_decode_attend
    B, T = tokens.shape
    x = _embed_lookup(params, tokens)
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * (cfg.dim ** 0.5)).astype(x.dtype)

    def layer_body(x, scanned):
        p, kp, vp, tk, tv = scanned
        q, k, v = _qkv(x, p, cfg, B, T)
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        # all rows write the same tail slot (done rows deposit junk there;
        # the causal mask excludes it — their frozen q_pos precedes it)
        tk = jax.lax.dynamic_update_slice_in_dim(tk, k, step, axis=1)
        tv = jax.lax.dynamic_update_slice_in_dim(tv, v, step, axis=1)
        attn = paged_decode_attend(
            q, kp, vp, tables, pool_lens, kv_off, tk, tv,
            tail_len=step + 1, q_pos=positions[:, 0],
            sliding_window=cfg.sliding_window, shard=shard)
        x = x + jnp.einsum("bthd,hdD->btD", attn, _wo(p, cfg, x.dtype))
        x = _mlp(x, p, cfg)
        return x, (tk, tv)

    x, (new_tk, new_tv) = jax.lax.scan(
        layer_body, x, (params["layers"], k_pool, v_pool, tail_k, tail_v))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    return x, new_tk, new_tv


def forward_hidden_paged_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [B, T] int32 right-padded suffix chunk
    positions: jax.Array,    # [B, T] int32 absolute positions
    k_pool: jax.Array,       # [L, n_pages, page, n_kv, hd] (donated by jit)
    v_pool: jax.Array,
    src_tables: jax.Array,   # [B, maxp] pages holding the resident prefix
    prefix_lens: jax.Array,  # [B] int32 resident pool tokens per row
    chunk_lens: jax.Array,   # [B] int32 valid chunk tokens per row
    flat_dst: jax.Array,     # [B, T] int32 flat pool token slot for each
                             # chunk position (OOB sentinel = drop), from
                             # the row's DST page table
    interpret: Optional[bool] = None,
    shard: Optional[tuple] = None,   # (mesh, tp_axis, dp_axis|None)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """PREFILL against the paged session pool: the suffix chunk attends to
    the resident prefix by streaming its pages directly
    (ops/paged_attention.paged_prefill_merge — one kernel launch per layer
    per CHUNK) merged with dense causal intra-chunk attention, and the
    chunk's own KV scatters straight into the row's dst pages. The
    [B, maxp·page] contiguous working cache the gather path materializes
    never exists (VERDICT r4 item 2; NOTES_r03 gap 1). Returns
    (hidden [B, T, D], k_pool, v_pool) with the chunk KV written."""
    from quoracle_tpu.ops.paged_attention import paged_prefill_merge
    B, T = tokens.shape
    n_tok = k_pool.shape[1] * k_pool.shape[2]
    x = _embed_lookup(params, tokens)
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * (cfg.dim ** 0.5)).astype(x.dtype)

    def layer_body(x, scanned):
        p, kp, vp = scanned          # kp/vp: [n_pages, page, kv, hd]
        q, k, v = _qkv(x, p, cfg, B, T)
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        attn = paged_prefill_merge(
            q, k.astype(kp.dtype), v.astype(vp.dtype), kp, vp, src_tables,
            prefix_lens, chunk_lens, sliding_window=cfg.sliding_window,
            interpret=interpret, shard=shard)
        # chunk KV → dst pages in place (padding/overflow slots carry the
        # OOB sentinel and drop). The attention above read the pool BEFORE
        # this write; chunk↔chunk attention used the dense piece, so
        # nothing this layer needs re-reading.
        kf = kp.reshape(n_tok, *kp.shape[2:])
        vf = vp.reshape(n_tok, *vp.shape[2:])
        kf = kf.at[flat_dst].set(k.astype(kp.dtype), mode="drop")
        vf = vf.at[flat_dst].set(v.astype(vp.dtype), mode="drop")
        x = x + jnp.einsum("bthd,hdD->btD", attn.astype(x.dtype),
                           _wo(p, cfg, x.dtype))
        x = _mlp(x, p, cfg)
        return x, (kf.reshape(kp.shape), vf.reshape(vp.shape))

    x, (new_k, new_v) = jax.lax.scan(
        layer_body, x, (params["layers"], k_pool, v_pool))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    return x, new_k, new_v


def forward_hidden_ragged(
    params: dict,
    cfg: ModelConfig,
    tokens: jax.Array,       # [1, Tp] int32 token-major FLATTENED batch
    positions: jax.Array,    # [1, Tp] int32 absolute positions per token
    k_pool: jax.Array,       # [L, n_pages, page, n_kv, hd] (donated by jit)
    v_pool: jax.Array,
    block_tables: jax.Array,  # [NB, maxp] int32 — owning row's page table
    block_meta: jax.Array,    # [NB, 3] int32: kv_len, qpos0, nq
    flat_dst: jax.Array,     # [Tp] int32 flat pool token slot per flattened
                             # token (OOB sentinel = drop), from the owning
                             # row's DST page table
    tq: int,
    interpret: Optional[bool] = None,
    shard: Optional[tuple] = None,   # (mesh, tp_axis)
    k_scale: Optional[jax.Array] = None,   # [L, n_pages, KV, page] f32
    v_scale: Optional[jax.Array] = None,   # per-(token, kv-head) scales
) -> tuple:
    """UNIFIED ragged forward (ISSUE 8): one launch per layer over a
    token-major flattened batch of rows with arbitrary query lengths —
    T=1 decode rows, T=chunk continuations, T=suffix prefills and T=K
    speculative-verify rows all in one grid. Each layer scatters the
    chunk's KV into the rows' pages FIRST, then attention streams each
    block's real pages (ops/paged_attention.ragged_attend_auto) — the
    [B, maxp·page] working cache, the dense intra-chunk piece, and the
    decode tail buffer all cease to exist. Returns
    (hidden [1, Tp, D], k_pool, v_pool) with the chunk KV written.

    With ``k_scale``/``v_scale`` (ISSUE 13) the pools are INT8: each
    layer quantizes the chunk's fresh KV per (token, kv-head)
    (models/quant.kv_quant), scatters int8 payloads into the pages and
    fp32 scales into the page-structured scale pools, and the attention
    dequantizes inside the kernel's streaming loop — returns a 5-tuple
    (hidden, k_pool, v_pool, k_scale, v_scale)."""
    from quoracle_tpu.ops.paged_attention import ragged_attend_auto
    B, Tp = tokens.shape       # B == 1: the flat layout is the batch
    n_pages, page = k_pool.shape[1], k_pool.shape[2]
    n_tok = n_pages * page
    KV = cfg.n_kv_heads
    quant = k_scale is not None
    x = _embed_lookup(params, tokens)
    if cfg.scale_embeddings:
        x = (x.astype(jnp.float32) * (cfg.dim ** 0.5)).astype(x.dtype)

    def layer_body(x, scanned):
        if quant:
            p, kp, vp, ks, vs = scanned  # ks/vs: [n_pages, KV, page]
        else:
            p, kp, vp = scanned          # kp/vp: [n_pages, page, kv, hd]
            ks = vs = None
        q, k, v = _qkv(x, p, cfg, B, Tp)
        q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        # KV → pages BEFORE attention (padding/overflow slots carry the
        # OOB sentinel and drop): intra-chunk visibility is then pure
        # causal masking inside the one kernel — no dense second piece.
        kf = kp.reshape(n_tok, *kp.shape[2:])
        vf = vp.reshape(n_tok, *vp.shape[2:])
        if quant:
            kq, ks_new = kv_quant(k[0])          # [Tp, KV, hd] / [Tp, KV]
            vq, vs_new = kv_quant(v[0])
            kf = kf.at[flat_dst].set(kq, mode="drop")
            vf = vf.at[flat_dst].set(vq, mode="drop")
            # scale slot for token t, head j in the [n_pages, KV, page]
            # pool: ((pid·KV)+j)·page + off — OOB flat_dst (pid =
            # n_pages) stays OOB and drops
            pid, off = flat_dst // page, flat_dst % page
            sidx = ((pid[:, None] * KV
                     + jnp.arange(KV, dtype=jnp.int32)[None, :]) * page
                    + off[:, None])              # [Tp, KV]
            ks = ks.reshape(-1).at[sidx].set(
                ks_new, mode="drop").reshape(ks.shape)
            vs = vs.reshape(-1).at[sidx].set(
                vs_new, mode="drop").reshape(vs.shape)
        else:
            kf = kf.at[flat_dst].set(k[0].astype(kp.dtype), mode="drop")
            vf = vf.at[flat_dst].set(v[0].astype(vp.dtype), mode="drop")
        kp2 = kf.reshape(kp.shape)
        vp2 = vf.reshape(vp.shape)
        attn = ragged_attend_auto(
            q[0], kp2, vp2, block_tables, block_meta, tq=tq,
            sliding_window=cfg.sliding_window, interpret=interpret,
            shard=shard, k_scale=ks, v_scale=vs)[None]   # [1, Tp, H, hd]
        x = x + jnp.einsum("bthd,hdD->btD", attn.astype(x.dtype),
                           _wo(p, cfg, x.dtype))
        x = _mlp(x, p, cfg)
        return x, ((kp2, vp2, ks, vs) if quant else (kp2, vp2))

    if quant:
        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            layer_body, x,
            (params["layers"], k_pool, v_pool, k_scale, v_scale))
        x = rmsnorm(x, params["final_norm"], cfg.norm_eps,
                    cfg.rmsnorm_plus_one)
        return x, new_k, new_v, new_ks, new_vs
    x, (new_k, new_v) = jax.lax.scan(
        layer_body, x, (params["layers"], k_pool, v_pool))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps, cfg.rmsnorm_plus_one)
    return x, new_k, new_v


def project_logits(params: dict, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    """Final hidden states [B, T, D] -> logits [B, T, vocab] fp32.

    Split from the stack so prefill can gather ONE position per row before
    projecting — at llama-3-8b scale a full [B, 8192, 128256] fp32 logits
    tensor is ~4 GB/row and would blow HBM for a value that's 99.99% discarded.
    """
    if cfg.tie_embeddings:
        head = dequant_weight(params["embed"], jnp.float32).T
    else:
        head = dequant_weight(params["lm_head"], jnp.float32)
    logits = jnp.einsum("btd,dv->btv", hidden.astype(jnp.float32),
                        head.astype(jnp.float32))
    if cfg.final_logit_softcap is not None:
        c = cfg.final_logit_softcap
        logits = c * jnp.tanh(logits / c)
    return logits


def forward(params: dict, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, cache: KVCache, write_offset: jax.Array,
            kv_lens: jax.Array) -> tuple[jax.Array, KVCache]:
    """forward_hidden + full-sequence head projection. Convenience for
    tests/training; serving paths gather positions from forward_hidden first."""
    hidden, cache = forward_hidden(params, cfg, tokens, positions, cache,
                                   write_offset, kv_lens)
    return project_logits(params, cfg, hidden), cache


def param_count(params: dict) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
