"""Grammar-constrained decoding: JSON-valid-by-construction sampling.

The reference relies on model compliance plus markdown-unwrap recovery
(reference lib/quoracle/utils/json_extractor.ex) and retries whole consensus
rounds when every response fails to parse. On-device serving can do better
(SURVEY.md §7 hard part 4): mask the logits each decode step so only tokens
that keep the output a syntactically valid JSON object are sampleable —
``all_invalid`` retry rounds from malformed JSON become impossible.

TPU-first design: JSON with a bounded nesting depth is a REGULAR language,
so the constraint compiles to a finite automaton. We build

  1. a char-level DFA for one JSON object (strings with escapes + \\uXXXX,
     numbers, true/false/null, nesting up to ``max_depth``), then
  2. a token-level transition table  table[state, token_id] -> state | -1
     by walking every vocab token's text through the char DFA from every
     reachable state (vectorized over states, so the product build is fast).

At decode time the per-row automaton state rides the lax.while_loop carry;
each step is one gather ``table[state]`` → [B, V] allowed mask + where() on
the logits, then ``state = table[state, token]``. Fully shape-static, no
host sync — exactly what the TPU wants. EOS is only sampleable in accept
states (top-level object closed), so constrained rows terminate cleanly.

This guarantees SYNTACTIC validity. With ``action_enum`` set the grammar is
also SCHEMA-AWARE for the decision shape (VERDICT r2 item 7): the top-level
object must open with ``"action": "<name>"`` where the name walks a trie of
the capability-gated action set, and later top-level keys cannot re-spell
``action`` (duplicate keys would let json.loads override the constrained
value). A constrained row therefore cannot propose an unknown action —
the remaining schema conformance (required params, enums) stays with the
validator layer (actions/validator.py), which now only ever sees parseable
JSON naming a real, allowed action.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

REJECT = -1

# --- char-level DFA ---------------------------------------------------------
# State = (mode, stack) with stack a tuple of "O"/"A" frames (bounded depth).
# Modes (suffix _K marks key-string variants inside objects):

WS_VALUE = "ws_value"        # expect a value (or ws)
STRING = "string"            # inside a "value" string
STR_ESC = "str_esc"          # after backslash
STR_U1, STR_U2, STR_U3, STR_U4 = "str_u1", "str_u2", "str_u3", "str_u4"
KEY = "key"                  # inside a key string
KEY_ESC = "key_esc"
KEY_U1, KEY_U2, KEY_U3, KEY_U4 = "key_u1", "key_u2", "key_u3", "key_u4"
AFTER_KEY = "after_key"      # expect ':' (or ws)
OBJ_FIRST = "obj_first"      # after '{': expect key or '}'
OBJ_NEXT = "obj_next"        # after a member: expect ',' or '}'
OBJ_KEY = "obj_key"          # after ',': expect key
ARR_NEXT = "arr_next"        # after an element: expect ',' or ']'
NUM_SIGN = "num_sign"        # after '-'
NUM_ZERO = "num_zero"        # a leading 0: no further int digits (RFC 8259)
NUM_INT = "num_int"          # integer digits
NUM_DOT = "num_dot"          # after '.'
NUM_FRAC = "num_frac"        # fraction digits
NUM_E = "num_e"              # after e/E
NUM_ESIGN = "num_esign"      # after e+/e-
NUM_EXP = "num_exp"          # exponent digits
DONE = "done"                # top-level object closed (accept; ws allowed)

_WS = " \t\n\r"
_DIGITS = "0123456789"
_HEX = "0123456789abcdefABCDEF"
# chars legal inside a JSON string without escaping (any codepoint except
# '"', '\\', and control chars; we operate on utf-8 BYTES >= 0x20)
_KEYWORDS = {"true", "false", "null"}


def _kw_states():
    """Keyword-progress modes: kw:<word>:<i> after matching word[:i]."""
    out = []
    for w in _KEYWORDS:
        for i in range(1, len(w)):
            out.append(f"kw:{w}:{i}")
    return out


ACTION_KEY = "action"


class CharDFA:
    """Explicit-state JSON automaton over bytes. Built by BFS from the start
    state; transitions computed on demand by `step`.

    ``action_enum``: when set, the top-level object is forced to open with
    ``"action": "<member>"`` (member walked through a prefix trie) and
    subsequent top-level keys may not spell ``action`` again (escapes are
    banned in top-level keys so \\u0061-style respellings can't sneak a
    duplicate in). Nested objects stay fully generic."""

    def __init__(self, max_depth: int = 5,
                 action_enum: Optional[Sequence[str]] = None,
                 limit_ws: bool = True):
        """``limit_ws``: cap inter-token whitespace to ONE char. Strings
        are untouched (a space there is content). This restricts the
        grammar to compact(ish) JSON — for SAMPLING that is strictly
        better: an unbounded-ws grammar lets a model burn its whole budget
        on whitespace runs without ever being forced toward content."""
        self.max_depth = max_depth
        self.limit_ws = limit_ws
        self.action_enum = (tuple(sorted(set(action_enum)))
                            if action_enum else None)
        if self.action_enum:
            self._enum_prefixes = {w[:i] for w in self.action_enum
                                   for i in range(len(w) + 1)}
            self._act_prefixes = {ACTION_KEY[:i]
                                  for i in range(len(ACTION_KEY) + 1)}
        # top level must be an OBJECT (the action-proposal shape), not any
        # bare JSON value
        self.start = (WS_VALUE + ":obj_only", ())
        # enumerate reachable states
        self.states: dict[tuple, int] = {}
        self.trans: Optional[np.ndarray] = None
        self._build()

    # -- single-char transition over abstract states -----------------------

    def _value_start(self, ch: str, stack: tuple):
        """Transitions out of WS_VALUE (expecting a value)."""
        if ch in _WS:
            return (WS_VALUE, stack)
        if ch == '"':
            return (STRING, stack)
        if ch == "{":
            if len(stack) >= self.max_depth:
                return None
            return (OBJ_FIRST, stack + ("O",))
        if ch == "[":
            if len(stack) >= self.max_depth:
                return None
            # an array may be empty: ']' closes it immediately
            return (WS_VALUE + ":arr0", stack + ("A",))
        if ch == "-":
            return (NUM_SIGN, stack)
        if ch == "0":
            return (NUM_ZERO, stack)   # leading zero ends the int part
        if ch in _DIGITS:
            return (NUM_INT, stack)
        for w in _KEYWORDS:
            if ch == w[0]:
                return (f"kw:{w}:1", stack)
        return None

    def _close_value(self, stack: tuple):
        """A value just finished; what mode follows depends on the frame."""
        if not stack:
            return (DONE, ())
        return (OBJ_NEXT if stack[-1] == "O" else ARR_NEXT, stack)

    # modes where a 0x20 space is string CONTENT, not whitespace
    _STRINGY_PREFIXES = ("key1:", "kw:")

    def _stringy(self, mode: str) -> bool:
        return mode in (STRING, KEY, STR_ESC, KEY_ESC, STR_U1, STR_U2,
                        STR_U3, STR_U4, KEY_U1, KEY_U2, KEY_U3, KEY_U4) \
            or mode.startswith(self._STRINGY_PREFIXES)

    # ws-tag sentinel: \x00 cannot appear in any mode name (enum prefixes
    # are action-name chars, key1 progress is capped to "action"-prefixes)
    _WS_TAG = "\x00w"

    def step(self, state: tuple, ch: str) -> Optional[tuple]:
        mode, stack = state
        if self.limit_ws:
            if mode.endswith(self._WS_TAG):   # one ws char consumed already
                if ch in _WS:
                    return None
                return self.step((mode[:-len(self._WS_TAG)], stack), ch)
            if ch in _WS and not self._stringy(mode):
                nxt = self._step_raw(state, ch)
                if nxt is None:
                    return None
                nm, ns = nxt
                # the number-closing path re-enters step() and may have
                # tagged the state already
                if nm.endswith(self._WS_TAG) or self._stringy(nm):
                    return nxt
                return (nm + self._WS_TAG, ns)
        return self._step_raw(state, ch)

    def _step_raw(self, state: tuple, ch: str) -> Optional[tuple]:
        mode, stack = state

        # ---- action-enum modes (schema-aware top-level object) ----------
        if self.action_enum is not None:
            if mode == WS_VALUE + ":obj_only":
                if ch in _WS:
                    return (mode, stack)
                if ch == "{":
                    return ("act_ws", ("O",))
                return None
            if mode == "act_ws":           # expect the forced "action" key
                if ch in _WS:
                    return (mode, stack)
                if ch == '"':
                    return ("actkey:0", stack)
                return None
            if mode.startswith("actkey:"):
                i = int(mode[7:])
                if i == len(ACTION_KEY):
                    return ("act_colon", stack) if ch == '"' else None
                return (f"actkey:{i + 1}", stack) \
                    if ch == ACTION_KEY[i] else None
            if mode == "act_colon":
                if ch in _WS:
                    return (mode, stack)
                if ch == ":":
                    return ("act_valws", stack)
                return None
            if mode == "act_valws":
                if ch in _WS:
                    return (mode, stack)
                if ch == '"':
                    return ("enum:", stack)
                return None
            if mode.startswith("enum:"):   # walk the action-name trie
                prefix = mode[5:]
                if ch == '"' and prefix in self.action_enum:
                    return (OBJ_NEXT, stack)
                if prefix + ch in self._enum_prefixes:
                    return (f"enum:{prefix + ch}", stack)
                return None
            if mode.startswith("key1:"):   # later top-level keys: ≠ action
                prog = mode[5:]
                if ch == '"':
                    return None if prog == ACTION_KEY else (AFTER_KEY, stack)
                if ch == "\\":
                    return None            # no escapes in top-level keys
                if ord(ch) >= 0x20:
                    nxt = prog + ch
                    marker = nxt if nxt in self._act_prefixes else "x"
                    return (f"key1:{marker}", stack)
                return None

        # value start (including the empty-array / object-only specials)
        if mode == WS_VALUE or mode.startswith(WS_VALUE):
            if mode == WS_VALUE + ":arr0" and ch == "]":
                return self._close_value(stack[:-1])
            if mode == WS_VALUE + ":obj_only" and ch not in _WS + "{":
                return None
            nxt = self._value_start(ch, stack)
            if nxt is None:
                return None
            # preserve the arr0/obj_only marker across leading whitespace
            if nxt[0] == WS_VALUE and mode != WS_VALUE:
                return (mode, stack)
            return nxt

        # strings (value + key variants share logic)
        if mode in (STRING, KEY):
            is_key = mode == KEY
            if ch == '"':
                return (AFTER_KEY, stack) if is_key \
                    else self._close_value(stack)
            if ch == "\\":
                return (KEY_ESC if is_key else STR_ESC, stack)
            if ord(ch) >= 0x20:
                return (mode, stack)
            return None
        if mode in (STR_ESC, KEY_ESC):
            is_key = mode == KEY_ESC
            if ch in '"\\/bfnrt':
                return (KEY if is_key else STRING, stack)
            if ch == "u":
                return (KEY_U1 if is_key else STR_U1, stack)
            return None
        for seq, nxt_mode, final in (
                ((STR_U1, STR_U2, STR_U3, STR_U4), None, STRING),
                ((KEY_U1, KEY_U2, KEY_U3, KEY_U4), None, KEY)):
            if mode in seq:
                if ch not in _HEX:
                    return None
                i = seq.index(mode)
                return (final if i == 3 else seq[i + 1], stack)

        # keywords
        if mode.startswith("kw:"):
            _, w, i = mode.split(":")
            i = int(i)
            if ch != w[i]:
                return None
            if i + 1 == len(w):
                return self._close_value(stack)
            return (f"kw:{w}:{i + 1}", stack)

        # numbers — a number ends on a delimiter, which must ALSO be
        # processed (ws/,/}/]) from the closed-value state
        if mode in (NUM_SIGN, NUM_DOT, NUM_ESIGN, NUM_E):
            if mode == NUM_E and ch in "+-":
                return (NUM_ESIGN, stack)
            if mode == NUM_SIGN and ch == "0":
                return (NUM_ZERO, stack)   # -0 also ends the int part
            if ch in _DIGITS:
                return {NUM_SIGN: NUM_INT, NUM_DOT: NUM_FRAC,
                        NUM_ESIGN: NUM_EXP, NUM_E: NUM_EXP}[mode], stack
            return None
        if mode in (NUM_INT, NUM_ZERO, NUM_FRAC, NUM_EXP):
            if ch in _DIGITS:
                if mode == NUM_ZERO:
                    return None            # RFC 8259: no leading zeros
                return (mode, stack)
            if mode in (NUM_INT, NUM_ZERO) and ch == ".":
                return (NUM_DOT, stack)
            if mode in (NUM_INT, NUM_ZERO, NUM_FRAC) and ch in "eE":
                return (NUM_E, stack)
            closed = self._close_value(stack)
            return self.step(closed, ch)   # delimiter handled by next mode

        # object plumbing
        if mode == OBJ_FIRST:
            if ch in _WS:
                return (mode, stack)
            if ch == "}":
                return self._close_value(stack[:-1])
            if ch == '"':
                return (KEY, stack)
            return None
        if mode == OBJ_KEY:
            if ch in _WS:
                return (mode, stack)
            if ch == '"':
                if self.action_enum is not None and stack == ("O",):
                    return ("key1:", stack)   # top-level: guard dup "action"
                return (KEY, stack)
            return None
        if mode == AFTER_KEY:
            if ch in _WS:
                return (mode, stack)
            if ch == ":":
                return (WS_VALUE, stack)
            return None
        if mode == OBJ_NEXT:
            if ch in _WS:
                return (mode, stack)
            if ch == ",":
                return (OBJ_KEY, stack)
            if ch == "}":
                return self._close_value(stack[:-1])
            return None
        if mode == ARR_NEXT:
            if ch in _WS:
                return (mode, stack)
            if ch == ",":
                return (WS_VALUE, stack)
            if ch == "]":
                return self._close_value(stack[:-1])
            return None

        if mode == DONE:
            return (DONE, ()) if ch in _WS else None
        return None

    # -- enumeration -------------------------------------------------------

    _CHARS = [chr(c) for c in range(0x20, 0x7F)] + list("\t\n\r") \
        + [chr(0xFFFD)]   # replacement char stands in for any non-ascii byte

    def _build(self) -> None:
        from collections import deque
        idx = {self.start: 0}
        q = deque([self.start])
        while q:
            s = q.popleft()
            for ch in self._CHARS:
                t = self.step(s, ch)
                if t is not None and t not in idx:
                    idx[t] = len(idx)
                    q.append(t)
        n = len(idx)
        trans = np.full((n, len(self._CHARS)), REJECT, np.int32)
        for s, i in idx.items():
            for ci, ch in enumerate(self._CHARS):
                t = self.step(s, ch)
                if t is not None:
                    trans[i, ci] = idx[t]
        accept = np.zeros(n, bool)
        for s, i in idx.items():
            accept[i] = s[0] in (DONE, DONE + "\x00w")
        self.states = idx
        self.trans, self.accept = self._minimize(trans, accept)
        start_class = self._class_of[idx[self.start]]
        self.states = {s: self._class_of[i] for s, i in idx.items()}
        # keep self.start mapping coherent
        self.start_id = start_class

    def _minimize(self, trans: np.ndarray, accept: np.ndarray):
        """Moore partition refinement — the raw product construction is
        state-heavy (keyword progress × stack configs), and the table's
        device footprint is n_states × vocab, so minimizing here cuts HBM
        several-fold for 128k vocabs."""
        n = trans.shape[0]
        # initial classes: accept vs not (REJECT is its own implicit class)
        cls = accept.astype(np.int64)
        while True:
            # signature = (class, classes of all transitions)
            tcls = np.where(trans >= 0, cls[np.clip(trans, 0, None)], -1)
            sig = np.concatenate([cls[:, None], tcls], axis=1)
            _, new_cls = np.unique(sig, axis=0, return_inverse=True)
            if np.array_equal(new_cls, cls):
                break
            cls = new_cls
        m = int(cls.max()) + 1
        new_trans = np.full((m, trans.shape[1]), REJECT, np.int32)
        new_accept = np.zeros(m, bool)
        for i in range(n):
            c = cls[i]
            new_accept[c] = accept[i]
            new_trans[c] = np.where(trans[i] >= 0,
                                    cls[np.clip(trans[i], 0, None)], REJECT)
        self._class_of = cls
        return new_trans, new_accept

    def char_index(self, ch: str) -> int:
        try:
            return self._CHARS.index(ch)
        except ValueError:
            # Control chars beyond \t\n\r are forbidden EVERYWHERE in JSON
            # (strings require \u escapes for them) — they must not fall
            # into the string-safe replacement bucket.
            if ord(ch) < 0x20:
                return -1
            return len(self._CHARS) - 1   # non-ascii → replacement bucket


# --- token-level table ------------------------------------------------------

class JsonTokenTable:
    """table[state, token] -> next state (or REJECT). Built once per
    tokenizer; vectorized over states so 32k-128k vocabs build in seconds."""

    def __init__(self, token_texts: list[str], eos_id: int,
                 max_depth: int = 4, extra_stop_ids: tuple = (),
                 action_enum: Optional[Sequence[str]] = None):
        dfa = CharDFA(max_depth=max_depth, action_enum=action_enum)
        n_states = dfa.trans.shape[0]     # minimized class count
        vocab = len(token_texts)
        table = np.full((n_states, vocab), REJECT, np.int32)

        all_states = np.arange(n_states, dtype=np.int32)
        reject_row = np.full(n_states, REJECT, np.int32)
        for tid, text in enumerate(token_texts):
            if not text:
                continue                   # specials: never sampleable
            cur = all_states
            dead = False
            for ch in text:
                ci = dfa.char_index(ch)
                if ci < 0:            # forbidden char: token never legal
                    dead = True
                    break
                nxt = np.where(cur >= 0, dfa.trans[np.clip(cur, 0, None), ci],
                               REJECT)
                cur = nxt
                if not np.any(cur >= 0):
                    dead = True
                    break
            table[:, tid] = reject_row if dead else cur
        # EOS: sampleable exactly in accept states; self-loop so done rows
        # stay valid.
        for sid in np.nonzero(dfa.accept)[0]:
            for stop in (eos_id, *extra_stop_ids):
                if 0 <= stop < vocab:
                    table[sid, stop] = sid
        assert n_states < 32767, "state space exceeds int16"
        # Pad the state axis to a bucket so differently-sized enum grammars
        # share one decode compilation (the table is a traced jit arg; its
        # SHAPE keys the compile cache). Pad rows are all-REJECT.
        padded = n_states
        for b in (128, 256, 384, 512, 640, 768, 1024, 1536, 2048, 4096,
                  8192):
            if n_states <= b:
                padded = b
                break
        if padded > n_states:
            table = np.concatenate(
                [table, np.full((padded - n_states, vocab), REJECT,
                                np.int32)], axis=0)
        self.table = table.astype(np.int16)   # halves the device footprint
        self.start_state = int(dfa.start_id)
        self.n_states = n_states
        self.accept = dfa.accept

    @classmethod
    def for_tokenizer(cls, tokenizer, vocab_size: int, eos_id: int,
                      extra_stop_ids: tuple = (),
                      action_enum: Optional[Sequence[str]] = None,
                      ) -> "JsonTokenTable":
        texts = []
        for tid in range(vocab_size):
            try:
                texts.append(tokenizer.decode([tid]))
            except Exception:
                texts.append("")
        # EOS/BOS often decode to ""/text; force specials empty so only the
        # accept-state rule can allow EOS.
        for sid in {eos_id, getattr(tokenizer, "bos_id", -1),
                    getattr(tokenizer, "pad_id", -1), *extra_stop_ids}:
            if 0 <= sid < vocab_size:
                texts[sid] = ""
        return cls(texts, eos_id, extra_stop_ids=extra_stop_ids,
                   action_enum=action_enum)
