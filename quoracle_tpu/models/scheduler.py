"""Decode-level continuous batching across agents (VERDICT r4 item 4).

The baton batcher (models/runtime.py) coalesces concurrent agents' rounds
at ROUND granularity: rows that arrive while a member is mid-generate wait
for the whole call. Here rows join and leave a shared decode loop at CHUNK
granularity instead — the classic continuous-batching scheme (reference
never executes attention, SURVEY §2.8; the pattern is Orca/vLLM's,
re-derived for XLA's static shapes):

  * each engine gets ONE worker thread running a chunked loop: every
    iteration batches all live rows into a single ``engine.generate``
    call bounded at ``chunk`` tokens;
  * a row's cross-chunk state is exactly its KV SESSION plus the grammar
    state: the continuation prompt (prior prompt + tokens emitted so far)
    token-extends the session, so each chunk re-prefills ONE token (the
    last sampled, never-forwarded one) and decodes ``chunk`` more;
    ``GenResult.json_state`` → ``initial_json_state`` resumes constrained
    rows mid-JSON (states travel relative to their grammar block);
  * between chunks, finished rows retire (futures resolve) and queued
    rows are admitted into free slots — a new agent's row starts decoding
    ``chunk`` tokens after the CURRENT CHUNK, not after every other
    agent's full round;
  * a row's FIRST chunk goes through the engine's radix prefix cache
    (models/prefix_cache.py): a new session whose prompt starts with a
    cached page-aligned prefix (the fleet's shared system/task preamble)
    prefills only its suffix, and same-chunk admissions sharing an
    uncached prefix are wave-split so the batch prefills it once. A
    scheduler-owned session is dropped when its row retires, but the
    prefix pages it prefilled stay adoptable in the cache until LRU
    eviction reclaims them.

Static-shape discipline: on the bucketed paths batch sizes ride the
engine's BATCH_BUCKETS and ``chunk`` is a fixed decode bound, so steady
state compiles exactly two programs (prefill bucket × decode chunk) per
batch bucket. With the UNIFIED ragged kernel engaged (ISSUE 8 — the TPU
default), ticks are admitted truly RAGGED: the engine lays every row's
suffix out token-major, device work and compile keys scale with the
tick's total real tokens (one token-budget bucket), and the batch-bucket
× prompt-bucket program matrix collapses to one (chunk, decode) program
pair per token budget — CompileRegistry asserts the collapse in tier-1,
and the per-tick real-vs-padded token counters
(quoracle_sched_{real,padded}_tokens_total) quantify the reclaimed
padding. Sampled rows draw fresh RNG per chunk — the stream differs from
a one-shot call (same distribution); temperature-0 rows are bit-identical
to one-shot (tests/test_scheduler.py equality).

Admission ORDER is a policy (ISSUE 4): the batcher queues through a
``serving/qos.AdmissionPolicy`` — FIFO by default, weighted-fair DRR with
an aging floor under QoS — and an optional
``serving/admission.AdmissionController`` sheds at submit (structured
reject with ``retry_after_ms``) while deadline-expired rows are failed at
admit instead of decoded. QoS reorders *scheduling* only: what a row
computes once admitted is untouched, so temp-0 equality holds with QoS on
or off (tests/test_qos.py).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional, Sequence

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.infra import costobs, fleetobs, introspect, treeobs
from quoracle_tpu.infra.flightrec import FLIGHT
from quoracle_tpu.infra.telemetry import (
    QOS_ADMIT_WAIT_MS, SCHED_ADMIT_WAIT_MS, SCHED_QUEUE_DEPTH,
    SCHED_ROWS_TOTAL, SCHED_SLOTS_BUSY, TRACER,
)
from quoracle_tpu.models.generate import GenResult
from quoracle_tpu.serving.admission import (
    AdmissionError, DeadlineExceededError,
)
from quoracle_tpu.serving.qos import (
    AdmissionPolicy, FifoPolicy, class_name, coerce_priority,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class _Row:
    """One agent row riding the shared decode loop."""

    prompt: list
    temperature: float
    top_p: float
    max_new: int
    session_id: str
    constrain: bool
    action_enum: Optional[Sequence[str]]
    future: Future
    emitted: list = dataclasses.field(default_factory=list)
    json_state: Optional[int] = None
    n_cached_first: Optional[int] = None
    owns_session: bool = False          # scheduler-created → drop at end
    t_submit: float = 0.0
    # QoS (ISSUE 4): class + tenant attribution and the absolute
    # (monotonic) deadline after which the row is failed, not decoded.
    priority: int = 1                   # Priority.AGENT
    tenant: str = "default"
    deadline_s: Optional[float] = None
    # Speculative serving attribution (ISSUE 6): draft/verify rounds this
    # row rode and how many draft tokens the target accepted — surfaced
    # on the retiring GenResult for per-decide speedup attribution.
    spec_rounds: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Fleet observability (ISSUE 15): the submitter's trace context —
    # queue-wait and decode spans emitted from the worker thread parent
    # onto it, so a row's lifecycle lands in the SAME trace that placed
    # it (possibly opened on another host). t_admit anchors the decode
    # span so queue wait is never double-counted in the decomposition.
    trace: Optional[Any] = None
    t_admit: float = 0.0
    # Chip economics (ISSUE 17): task/decide attribution keys carried
    # down from the consensus layer, and this row's accumulated share
    # of measured device wall across every chunk it rode.
    task_id: Optional[str] = None
    decide: Optional[str] = None
    chip_ms: float = 0.0
    # Wait-state decomposition (ISSUE 18): the row's integer-ns wait
    # ledger, opened at submit while the introspect plane is on (None
    # when off — the gated fast path allocates nothing). Closed at
    # retire; the named waits + exact remainder ride the sched.decode
    # span as ``waits_ns``.
    waits: Optional[Any] = None
    # Session-graph observability (ISSUE 20): the submitting agent's
    # tree context dict (treeobs.TreeContext.to_dict), carried so the
    # retire site can book this row's wait decomposition to the right
    # tree node — on whichever peer the row lands after a handoff.
    tree: Optional[dict] = None


class ContinuousBatcher:
    """Per-engine chunked decode loop with admission between chunks.

    ``submit()`` returns a Future[GenResult]; rows from any number of
    callers (agents) batch into the same device steps. Sessionless
    submissions get a scheduler-owned session (dropped on completion) —
    the session IS the row's cross-chunk KV state.
    """

    def __init__(self, engine, chunk: int = 32, max_slots: int = 8,
                 admit_wait_s: float = 0.002,
                 policy: Optional[AdmissionPolicy] = None,
                 admission=None, slo=None, speculator=None):
        """``policy`` orders admission (default: the original FIFO;
        serving/qos.WeightedFairPolicy for DRR + aging). ``admission``
        is an optional serving/admission.AdmissionController consulted
        on every submit — sheds fail the row's future with a structured
        AdmissionError instead of growing the queue. ``slo`` is an
        optional serving/slo.SLOTracker fed per-class retire latency.
        ``speculator`` (models/speculative.BatchedSpeculator, ISSUE 6)
        turns eligible rows' decode ticks into batched draft/verify
        rounds; ineligible rows decode vanilla in the same tick and
        temp-0 outputs stay bit-identical either way."""
        self.engine = engine
        self.chunk = chunk
        self.max_slots = max_slots
        self.admit_wait_s = admit_wait_s
        self._policy = policy if policy is not None else FifoPolicy()
        self.admission = admission
        self.slo = slo
        self.speculator = speculator
        self._live: list[_Row] = []
        self._seq = 0
        self._lock = named_lock("batcher")
        self._wake = threading.Event()
        self._stop = False
        # health telemetry (ISSUE 3): monotonic progress/outcome counters.
        # ``steps`` is the stall watchdog's progress signal — frozen steps
        # with live rows means the decode loop is wedged.
        self.steps = 0
        self.retired = 0
        self.failed = 0
        self._model = engine.cfg.name
        self._thread = threading.Thread(
            target=self._loop, name=f"batcher-{engine.cfg.name}",
            daemon=True)
        self._thread.start()

    def submit(self, prompt: Sequence[int], *, temperature: float = 1.0,
               top_p: float = 1.0, max_new_tokens: int = 256,
               session_id: Optional[str] = None,
               constrain_json: bool = False,
               action_enum: Optional[Sequence[str]] = None,
               priority=None, tenant: str = "default",
               deadline_s: Optional[float] = None,
               initial_json_state: Optional[int] = None,
               task_id: Optional[str] = None,
               decide: Optional[str] = None,
               tree: Optional[dict] = None) -> Future:
        """``initial_json_state`` resumes a constrained row MID-GRAMMAR:
        the prompt's tail already contains generated JSON (a prefill-tier
        replica's first token after a KV handoff, serving/cluster.py) and
        decoding must continue from that grammar state, not from the
        block start — exactly the state the chunked loop already threads
        between its own chunks via GenResult.json_state."""
        row = _Row(prompt=list(prompt), temperature=temperature,
                   top_p=top_p, max_new=max(1, max_new_tokens),
                   session_id=session_id or self._own_session_id(),
                   constrain=constrain_json, action_enum=action_enum,
                   future=Future(), t_submit=time.monotonic(),
                   priority=int(coerce_priority(priority)),
                   tenant=tenant, deadline_s=deadline_s,
                   json_state=initial_json_state,
                   task_id=task_id, decide=decide,
                   tree=(tree if treeobs.enabled() else None),
                   # trace capture only while something listens — the
                   # un-traced fast path stays allocation-identical
                   trace=(fleetobs.TraceContext.current()
                          if TRACER.active() else None))
        row.owns_session = session_id is None
        if introspect.enabled():
            row.waits = introspect.WaitClock()
        # Per-row admission check: an over-window prompt must fail ONLY
        # its own future — inside a shared chunk the engine's
        # ContextOverflowError would poison every live row's in-flight
        # work (the engine applies the same bound at generate()).
        if len(row.prompt) >= self.engine.max_seq:
            from quoracle_tpu.models.generate import ContextOverflowError
            row.future.set_exception(ContextOverflowError(
                f"prompt of {len(row.prompt)} tokens >= max_seq "
                f"{self.engine.max_seq} for model {self.engine.cfg.name}"))
            return row.future
        # QoS admission (ISSUE 4): shed BEFORE the row can queue — a
        # structured reject on the row's OWN future (same idiom as the
        # overflow check above), never silent queue growth. The
        # controller may clamp the class to the tenant's floor.
        if self.admission is not None:
            t_adm = (time.monotonic_ns()
                     if row.waits is not None else 0)
            try:
                row.priority = int(self.admission.admit(
                    tenant=row.tenant, priority=row.priority,
                    deadline_s=row.deadline_s,
                    queue_depth=self._policy.qsize()))
                if row.waits is not None:
                    row.waits.note("admission",
                                   time.monotonic_ns() - t_adm)
            except AdmissionError as e:
                row.future.set_exception(e)
                self.failed += 1
                SCHED_ROWS_TOTAL.inc(model=self._model, status="failed")
                # error-budget score (ISSUE 17): a shed burns the
                # tenant class's budget — observed signal only
                costobs.BUDGET.record(row.tenant,
                                      class_name(row.priority),
                                      ok=False, t=time.monotonic())
                return row.future
        # Reject-after-closed UNDER THE LOCK (ISSUE 3 satellite): close()
        # flips _stop under this same lock, so a row can only enter the
        # queue strictly BEFORE the flip — and close()'s drain (which runs
        # after) is then guaranteed to see it. The old unlocked
        # check-put-recheck dance left a window where a concurrently
        # submitted row landed after the drain and stranded its future.
        with self._lock:
            if self._stop:
                raise RuntimeError("ContinuousBatcher is closed")
            self._policy.put(row)
            depth = self._policy.qsize()
        SCHED_QUEUE_DEPTH.set(depth, model=self._model)
        self._wake.set()
        # Tiered-KV prefetch (ISSUE 7): a row resuming a HIBERNATED
        # session warms it now, overlapping the page-in with its queue
        # wait. Best-effort and non-blocking (try-acquire inside): a
        # busy engine skips it and the sessioned generate restores
        # synchronously at lookup instead.
        if session_id is not None:
            prefetch = getattr(self.engine, "prefetch_session", None)
            if prefetch is not None:
                try:
                    prefetch(session_id)
                except Exception:   # noqa: BLE001 — warm-up only
                    pass
        return row.future

    def close(self) -> None:
        with self._lock:
            self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # mid-chunk device call still running; give it one longer
            # grace period — touching _live while the worker owns it
            # would race its set_result calls (InvalidStateError)
            self._thread.join(timeout=50)
        # never strand a waiter: still-queued rows fail loudly instead of
        # leaving callers blocked on futures forever. LIVE rows are failed
        # by the worker's own exit cleanup (it owns _live); only a worker
        # confirmed dead can't do that, so take over just in that case.
        err = RuntimeError("ContinuousBatcher closed")
        leftovers = []
        if not self._thread.is_alive():
            leftovers = list(self._live)
            self._live = []
        leftovers.extend(self._policy.drain())
        for row in leftovers:
            if not row.future.done():
                row.future.set_exception(err)
                self.failed += 1
                SCHED_ROWS_TOTAL.inc(model=self._model, status="failed")
            self._drop_row_sessions(row)
        # Zero the live gauges (ISSUE 4 satellite): the queue is drained
        # and no slot can ever be busy again — leaving the last-set
        # values would show phantom depth/occupancy on /metrics scrapes
        # after shutdown.
        SCHED_QUEUE_DEPTH.set(0, model=self._model)
        SCHED_SLOTS_BUSY.set(0, model=self._model)

    def _own_session_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"__cb{self._seq}"

    # -- health telemetry (ISSUE 3) ------------------------------------

    def stats(self) -> dict:
        """Point-in-time health snapshot for /api/resources (racy reads
        of worker-owned state — a snapshot, not an invariant)."""
        padding = getattr(self.engine, "padding_stats", None)
        return {
            "queued": self._policy.qsize(),
            "live": len(self._live),
            "max_slots": self.max_slots,
            "chunk": self.chunk,
            "steps": self.steps,
            "retired": self.retired,
            "failed": self.failed,
            "closed": self._stop,
            "qos": self._policy.snapshot(),
            # padding-waste accounting (ISSUE 8): real vs padded chunk
            # tokens per tick — what ragged admission reclaims
            "padding": padding() if padding is not None else None,
            "speculative": (self.speculator.stats()
                            if self.speculator is not None else None),
        }

    def progress(self) -> tuple[bool, int]:
        """Stall-watchdog source (runtime.StallWatchdog): (work pending?,
        monotonic progress counter). Active with a frozen counter past
        the deadline = the decode loop is wedged."""
        active = (not self._stop
                  and (bool(self._live) or self._policy.qsize() > 0))
        return active, self.steps

    # ------------------------------------------------------------------

    def _admit(self) -> None:
        admitted = 0
        while len(self._live) < self.max_slots:
            row = self._policy.pop()
            if row is None:
                break
            now = time.monotonic()
            # Deadline-aware drop (ISSUE 4): a row whose deadline passed
            # while queued is failed AT ADMIT — decoding tokens nobody
            # will wait for would steal the slot from a live request.
            if row.deadline_s is not None and now >= row.deadline_s:
                if not row.future.done():
                    row.future.set_exception(DeadlineExceededError(
                        f"deadline passed after "
                        f"{(now - row.t_submit) * 1000:.0f}ms in queue",
                        tenant=row.tenant, priority=row.priority))
                self._drop_row_sessions(row)
                self.failed += 1
                SCHED_ROWS_TOTAL.inc(model=self._model, status="failed")
                from quoracle_tpu.infra.telemetry import QOS_SHED_TOTAL
                QOS_SHED_TOTAL.inc(cls=class_name(row.priority),
                                   tenant=row.tenant, reason="deadline")
                FLIGHT.record("qos_deadline_drop", model=self._model,
                              cls=class_name(row.priority),
                              tenant=row.tenant,
                              waited_ms=round(
                                  (now - row.t_submit) * 1000, 1))
                costobs.BUDGET.record(row.tenant,
                                      class_name(row.priority),
                                      ok=False, t=now)
                continue
            wait_ms = (now - row.t_submit) * 1000
            SCHED_ADMIT_WAIT_MS.observe(wait_ms, model=self._model)
            QOS_ADMIT_WAIT_MS.observe(wait_ms,
                                      cls=class_name(row.priority))
            row.t_admit = now
            if row.waits is not None:
                # batch-queue wait = submit→admit minus the admission
                # call's own wall (already booked as "admission")
                row.waits.note(
                    "queue",
                    int(wait_ms * 1e6)
                    - row.waits.waits.get("admission", 0))
            if TRACER.active():
                # retroactive queue-wait span, parented on the
                # submitter's (possibly remote) trace context
                TRACER.emit("sched.queue_wait", wait_ms,
                            parent=row.trace,
                            ts=time.time() - wait_ms / 1000.0,
                            session=row.session_id, model=self._model,
                            cls=class_name(row.priority))
            self._live.append(row)
            admitted += 1
        if admitted:
            FLIGHT.record("sched_admit", model=self._model, rows=admitted,
                          live=len(self._live))
        SCHED_QUEUE_DEPTH.set(self._policy.qsize(), model=self._model)
        SCHED_SLOTS_BUSY.set(len(self._live), model=self._model)

    def _loop(self) -> None:
        while not self._stop:
            self._admit()
            if not self._live:
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            # Sampled decode-tick span (ISSUE 15 satellite): 1-in-N
            # ticks (QUORACLE_TRACE_DECODE_SAMPLE, keyed on the
            # monotonic step counter — deterministic, no RNG) so
            # serving decode traffic cannot starve consensus traces
            # out of the bounded span rings.
            t_tick = (time.monotonic()
                      if TRACER.active() and fleetobs.sample_tick(
                          self.steps) else None)
            n_rows = len(self._live)
            try:
                self._live = self._step(self._live)
            except Exception:             # noqa: BLE001 — isolate, don't
                self._live = self._isolate_failure(self._live)  # nuke all
            if t_tick is not None:
                TRACER.emit("sched.decode_tick",
                            (time.monotonic() - t_tick) * 1000,
                            model=self._model, rows=n_rows,
                            step=self.steps)
            self.steps += 1               # watchdog progress signal
            introspect.beat(f"sched.tick:{self._model}")
            self._chaos_tick()
        # worker exit (close()): the worker owns _live, so it fails any
        # remaining rows itself — close() only takes over when this
        # thread is confirmed dead
        err = RuntimeError("ContinuousBatcher closed")
        for row in self._live:
            if not row.future.done():
                row.future.set_exception(err)
                self.failed += 1
                SCHED_ROWS_TOTAL.inc(model=self._model, status="failed")
            self._drop_row_sessions(row)
        self._live = []
        # gauge reset on the worker-exit path too (ISSUE 4 satellite):
        # whichever of close()/worker runs last, the scrape reads zero
        SCHED_SLOTS_BUSY.set(0, model=self._model)

    def _chaos_tick(self) -> None:
        """Chaos seam (ISSUE 11): per-tick fault hook in the decode
        loop. ``demote`` forces the eviction ladder to hibernate every
        demotable session MID-TRAFFIC (the still-live rows restore by
        page-in next tick — PR 7's invariants under hostile
        interleaving); ``delay`` stretches the tick. Worker-thread
        exceptions here must never kill the loop — the faults this seam
        injects are tier churn, not thread death."""
        from quoracle_tpu.chaos.faults import (
            CHAOS, chaos_demote_churn,
        )
        if not CHAOS.armed():
            return
        try:
            d = CHAOS.fire("sched.tick", model=self._model)
            if d is not None and d.kind == "demote":
                chaos_demote_churn(self.engine)
        except Exception:                 # noqa: BLE001 — isolate
            logger.exception("chaos tick hook failed")

    def _isolate_failure(self, rows: list) -> list:
        """A shared chunk raised. One poisoned row must not discard every
        other agent's partial work: rerun each row as its own single-row
        chunk — rows that fail alone get THEIR error, the rest survive
        with their emitted state intact. Engine-wide failures (device
        dead) fail every row with its own raise, same end state as the
        old all-rows-fail path."""
        survivors: list = []
        for row in rows:
            if row.future.done():
                # _step resolved this row (and dropped its session) before
                # the exception hit a later row — nothing left to rerun
                continue
            try:
                survivors.extend(self._step([row]))
            except Exception as e:        # noqa: BLE001 — per-row capture
                if not row.future.done():
                    row.future.set_exception(e)
                self._drop_row_sessions(row)
                self.failed += 1
                SCHED_ROWS_TOTAL.inc(model=self._model, status="failed")
                FLIGHT.record("sched_row_failed", model=self._model,
                              session=row.session_id, error=repr(e))
        return survivors

    def _drop_row_sessions(self, row) -> None:
        """Owned-session cleanup for a terminal row — the engine session
        AND (under speculative serving) the draft engine's shadow session
        the speculator keyed by the same id."""
        if row.owns_session:
            self.engine.drop_session(row.session_id)
            if self.speculator is not None:
                self.speculator.drop_session(row.session_id)

    def _finish_row(self, row, finish_reason: str,
                    json_state: int = -1) -> None:
        """Resolve a finished row's future from its accumulated state and
        account the retirement (shared by the vanilla and speculative
        paths — one retire semantics, zero drift)."""
        if not row.future.done():           # close() may have failed it
            row.future.set_result(GenResult(
                token_ids=list(row.emitted),
                text=self.engine.tokenizer.decode(row.emitted),
                n_prompt_tokens=len(row.prompt),
                n_gen_tokens=len(row.emitted),
                latency_s=time.monotonic() - row.t_submit,
                finish_reason=finish_reason,
                n_cached_tokens=row.n_cached_first or 0,
                json_state=json_state,
                spec_rounds=row.spec_rounds,
                spec_drafted_tokens=row.spec_drafted,
                spec_accepted_tokens=row.spec_accepted,
                chip_ms=round(row.chip_ms, 6),
            ))
        self._drop_row_sessions(row)
        self.retired += 1
        # error-budget score (ISSUE 17): a retire past its deadline is
        # an SLO miss; everything else is budget-ok
        t_done = time.monotonic()
        costobs.BUDGET.record(
            row.tenant, class_name(row.priority),
            ok=not (row.deadline_s is not None and t_done > row.deadline_s),
            t=t_done)
        SCHED_ROWS_TOTAL.inc(model=self._model, status="retired")
        # Wait-state decomposition (ISSUE 18): close the row's wait
        # ledger at retire — the named waits + exact remainder sum to
        # the row's observed wall by construction — and ride it on the
        # decode span so /api/timeline aggregates it per trace.
        closed = None
        if row.waits is not None:
            closed = row.waits.close()
            introspect.record_row_waits(self._model, closed)
            introspect.beat(f"sched.retired:{self._model}")
            # Session-graph rollup (ISSUE 20): the same exact-sum wait
            # decomposition, booked to the tree node this row belongs
            # to — on THIS peer's registry; the front door federates.
            if row.tree is not None and treeobs.enabled():
                treeobs.charge_row_waits(row.tree, closed)
        if TRACER.active():
            # one decode span per row lifetime, anchored at admission
            # so queue wait is never double-counted in the TTFT
            # decomposition (fleetobs.assemble_timeline)
            dur_ms = (time.monotonic()
                      - (row.t_admit or row.t_submit)) * 1000
            extra = ({"wall_ns": closed["wall_ns"],
                      "waits_ns": closed["waits_ns"]}
                     if closed is not None else {})
            TRACER.emit("sched.decode", dur_ms, parent=row.trace,
                        ts=time.time() - dur_ms / 1000.0,
                        session=row.session_id, model=self._model,
                        tokens=len(row.emitted), finish=finish_reason,
                        **extra)
        if self.slo is not None:
            # per-class tail tracking (serving/slo.py): feeds the
            # INTERACTIVE-burn → BATCH-demotion control loop
            self.slo.observe(
                row.priority,
                (time.monotonic() - row.t_submit) * 1000)
        FLIGHT.record("sched_retire", model=self._model,
                      session=row.session_id,
                      n_tokens=len(row.emitted),
                      finish=finish_reason)

    def _step(self, rows: list) -> list:
        """One decode tick. Under speculative serving (ISSUE 6) the tick
        splits: eligible rows ride batched draft/verify rounds
        (models/speculative.BatchedSpeculator) while ineligible rows —
        nucleus-sampled, window-edge, or disengaged-member rows — decode
        vanilla in the same tick. Both kinds retire through _finish_row;
        temp-0 outputs are bit-identical either way."""
        spec = self.speculator
        spec_rows: list = []
        spec_ids: set = set()
        finishes: dict = {}
        if spec is not None:
            spec.tick_vanilla()         # re-probe countdown while off
            for r in rows:
                reason = spec.ineligible_reason(
                    len(r.prompt) + len(r.emitted), r.temperature,
                    r.top_p)
                if reason is None:
                    spec_rows.append(r)
                    spec_ids.add(id(r))
                else:
                    spec.note_fallback(reason)
            if spec_rows:
                t_sp = (time.monotonic_ns()
                        if any(r.waits is not None for r in spec_rows)
                        else None)
                if t_sp is not None:
                    introspect.drain_inner_waits()
                finishes, leftover = self._spec_step(spec_rows)
                if t_sp is not None:
                    self._book_step_waits(
                        spec_rows, time.monotonic_ns() - t_sp)
                if leftover:            # speculator failed mid-tick:
                    lids = set(map(id, leftover))   # decode those vanilla
                    spec_rows = [r for r in spec_rows
                                 if id(r) not in lids]
                    spec_ids -= lids
        plain = [r for r in rows if id(r) not in spec_ids]
        still = self._plain_step(plain) if plain else []
        for row in spec_rows:
            fin = finishes.get(id(row))
            finished = (fin == "stop"
                        or len(row.emitted) >= row.max_new
                        or (len(row.prompt) + len(row.emitted)
                            >= self.engine.max_seq - 1))
            if finished:
                self._finish_row(
                    row, "stop" if fin == "stop" else "length",
                    json_state=(row.json_state
                                if row.json_state is not None else -1))
            else:
                still.append(row)
        return still

    def _spec_step(self, rows: list) -> tuple[dict, list]:
        """Speculative sub-tick: repeated draft/verify rounds until every
        row has committed ~chunk tokens, finished, or become ineligible.
        Returns ({id(row): "stop" | None}, leftover) where ``leftover``
        rows hit a speculator error and must decode vanilla this tick —
        their committed progress (rows + sessions mutate in place) is
        already consistent, so the fallback is seamless."""
        spec = self.speculator
        finishes: dict = {}
        active = list(rows)
        baseline = {id(r): len(r.emitted) for r in rows}
        try:
            while active:
                for rid, fin in spec.run_round(active).items():
                    if fin is not None:
                        finishes[rid] = fin
                active = [
                    r for r in active
                    if finishes.get(id(r)) is None
                    and len(r.emitted) < r.max_new
                    and len(r.emitted) - baseline[id(r)] < self.chunk
                    and spec.ineligible_reason(
                        len(r.prompt) + len(r.emitted), r.temperature,
                        r.top_p) is None]
        except Exception as e:    # noqa: BLE001 — isolate, don't kill rows
            spec.note_fallback("error", len(active))
            FLIGHT.record("spec_error", model=self._model, error=repr(e))
            leftover = [r for r in active if finishes.get(id(r)) is None]
            return finishes, leftover
        return finishes, []

    def _row_key(self, row) -> tuple:
        """Chip-economics attribution key (ISSUE 17): the scheduler's
        integer priority renders as its QoS class name so ledger
        rollups share the budget plane's vocabulary."""
        return (str(row.tenant or "-"), class_name(row.priority),
                str(row.task_id or "-"), str(row.decide or "-"))

    def _book_step_waits(self, rows: list, step_ns: int) -> None:
        """Partition one device call's wall across its rows' wait
        ledgers (ISSUE 18). Every row in the batch waits the WHOLE call
        concurrently, so each is booked the full wall — split into the
        KV-restore and contended-lock walls this thread accumulated
        inside the call, with the rest as device dispatch."""
        restore_ns, lock_ns = introspect.drain_inner_waits()
        dispatch_ns = max(0, step_ns - restore_ns - lock_ns)
        for r in rows:
            if r.waits is None:
                continue
            r.waits.note("dispatch", dispatch_ns)
            r.waits.note("kv_restore", restore_ns)
            r.waits.note("lock", lock_ns)

    def _plain_step(self, rows: list) -> list:
        prompts = [r.prompt + r.emitted for r in rows]
        budgets = [min(self.chunk, r.max_new - len(r.emitted))
                   for r in rows]
        t_step = (time.monotonic_ns()
                  if any(r.waits is not None for r in rows) else None)
        if t_step is not None:
            introspect.drain_inner_waits()
        # declare this chunk's attribution keys on the worker thread —
        # the engine's charge site consumes them (one call, one set)
        costobs.set_row_keys([self._row_key(r) for r in rows])
        results = self.engine.generate(
            prompts,
            temperature=[r.temperature for r in rows],
            top_p=[r.top_p for r in rows],
            max_new_tokens=budgets,
            session_ids=[r.session_id for r in rows],
            constrain_json=[r.constrain for r in rows],
            action_enums=[r.action_enum for r in rows],
            initial_json_state=[r.json_state for r in rows],
        )
        if t_step is not None:
            self._book_step_waits(rows, time.monotonic_ns() - t_step)
        still = []
        for row, res, budget in zip(rows, results, budgets):
            if row.n_cached_first is None:
                row.n_cached_first = res.n_cached_tokens
            row.chip_ms += res.chip_ms
            row.emitted.extend(res.token_ids)
            row.json_state = (res.json_state
                              if res.json_state >= 0 else row.json_state)
            finished = (res.finish_reason == "stop"
                        or len(res.token_ids) < budget
                        or len(row.emitted) >= row.max_new
                        # context exhausted: the next continuation prompt
                        # (prompt+emitted) would reach the window and the
                        # whole shared batch would ContextOverflow — retire
                        # at the window edge instead (the engine clamps
                        # row_limit the same way, so when remaining space
                        # is an exact chunk multiple only this check fires)
                        or (len(row.prompt) + len(row.emitted)
                            >= self.engine.max_seq - 1))
            if finished:
                self._finish_row(row, res.finish_reason, res.json_state)
            else:
                still.append(row)
        return still
