"""On-device text-conditioned diffusion image generation.

The reference's generate_images action fans out to HOSTED image models over
HTTPS (reference lib/quoracle/models/image_query.ex:1-12 — Task.async_stream
over configured image models, 60s timeout, cost recording). This module is
the TPU-native equivalent behind the same ``ImageBackend`` seam
(models/images.py): a small pixel-space UNet denoiser + DDIM sampler, fully
jitted — the timestep loop is a ``lax.scan`` over precomputed alphas, conv
stacks run channels-last on the MXU, shapes are static.

Like the LLM pool, the model serves whatever weights it is given: random
init produces textured-noise images (the honest no-network analog of the
bench's generated LLM checkpoints — the serving path, batching, cost
accounting, and determinism are real; picture quality needs trained
weights, which need a network). Weights load/store as a flat pytree, so a
trained checkpoint drops in without code changes.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import time
import uuid
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from quoracle_tpu.models.images import GeneratedImage, ImageBackend, write_png


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    image_size: int = 64          # model output; host resizes to request
    base_ch: int = 48
    ch_mult: tuple = (1, 2, 4)
    emb_ch: int = 192             # time + text embedding width
    vocab_size: int = 512         # prompt tokens (byte-level)
    groups: int = 8
    train_steps: int = 1000      # beta schedule length
    sample_steps: int = 30       # DDIM steps per image


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * fan_in ** -0.5)


def init_diffusion_params(cfg: DiffusionConfig, key: jax.Array) -> dict:
    ks = iter(jax.random.split(key, 64))
    C = cfg.base_ch
    chans = [C * m for m in cfg.ch_mult]

    def res(cin, cout):
        return {
            "n1": jnp.ones((cin,)), "c1": _conv_init(next(ks), 3, 3, cin,
                                                     cout),
            "temb": (jax.random.normal(next(ks), (cfg.emb_ch, cout))
                     * cfg.emb_ch ** -0.5),
            "n2": jnp.ones((cout,)), "c2": _conv_init(next(ks), 3, 3, cout,
                                                      cout),
            "skip": (_conv_init(next(ks), 1, 1, cin, cout)
                     if cin != cout else None),
        }

    p = {
        "text_embed": (jax.random.normal(next(ks),
                                         (cfg.vocab_size, cfg.emb_ch))
                       * cfg.emb_ch ** -0.5),
        "temb_w1": (jax.random.normal(next(ks), (cfg.emb_ch, cfg.emb_ch))
                    * cfg.emb_ch ** -0.5),
        "temb_w2": (jax.random.normal(next(ks), (cfg.emb_ch, cfg.emb_ch))
                    * cfg.emb_ch ** -0.5),
        "stem": _conv_init(next(ks), 3, 3, 3, chans[0]),
        "down": [], "downs": [],
        "mid": res(chans[-1], chans[-1]),
        "up": [], "ups": [],
        "out_n": jnp.ones((chans[0],)),
        "out_c": _conv_init(next(ks), 3, 3, chans[0], 3) * 0.1,
    }
    for i in range(len(chans) - 1):
        p["down"].append(res(chans[i], chans[i]))
        p["downs"].append(_conv_init(next(ks), 3, 3, chans[i], chans[i + 1]))
    for i in range(len(chans) - 1, 0, -1):
        p["ups"].append(_conv_init(next(ks), 3, 3, chans[i], chans[i - 1]))
        p["up"].append(res(2 * chans[i - 1], chans[i - 1]))
    return p


def _gn(x, w, groups):
    """GroupNorm (no bias), channels-last [B, H, W, C]."""
    B, H, W, C = x.shape
    g = x.reshape(B, H, W, groups, C // groups)
    mu = jnp.mean(g, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(g, axis=(1, 2, 4), keepdims=True)
    g = (g - mu) * jax.lax.rsqrt(var + 1e-5)
    return g.reshape(B, H, W, C) * w


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _resblock(x, p, temb, groups):
    h = _conv(jax.nn.silu(_gn(x, p["n1"], groups)), p["c1"])
    h = h + (temb @ p["temb"])[:, None, None, :]
    h = _conv(jax.nn.silu(_gn(h, p["n2"], groups)), p["c2"])
    if p["skip"] is not None:
        x = _conv(x, p["skip"])
    return x + h


def _upsample(x):
    B, H, W, C = x.shape
    return jax.image.resize(x, (B, 2 * H, 2 * W, C), "nearest")


def denoise(params: dict, cfg: DiffusionConfig, x: jax.Array,
            t: jax.Array, text_emb: jax.Array) -> jax.Array:
    """Predict noise eps for x_t. x [B, S, S, 3]; t [B] in [0, 1);
    text_emb [B, emb_ch]."""
    half = cfg.emb_ch // 2
    freqs = jnp.exp(-jnp.arange(half) / half * 9.21)      # 1 .. 1e-4
    ang = t[:, None] * cfg.train_steps * freqs[None, :]
    temb = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
    temb = jax.nn.silu(temb @ params["temb_w1"]) + text_emb
    temb = jax.nn.silu(temb @ params["temb_w2"])

    h = _conv(x, params["stem"])
    skips = []
    for rb, dw in zip(params["down"], params["downs"]):
        h = _resblock(h, rb, temb, cfg.groups)
        skips.append(h)
        h = _conv(h, dw, stride=2)
    h = _resblock(h, params["mid"], temb, cfg.groups)
    for rb, uw in zip(params["up"], params["ups"]):
        h = _conv(_upsample(h), uw)
        h = jnp.concatenate([h, skips.pop()], axis=-1)
        h = _resblock(h, rb, temb, cfg.groups)
    return _conv(jax.nn.silu(_gn(h, params["out_n"], cfg.groups)),
                 params["out_c"])


@functools.partial(jax.jit, static_argnames=("cfg",))
def ddim_sample(params: dict, cfg: DiffusionConfig, prompt_ids: jax.Array,
                rng: jax.Array) -> jax.Array:
    """DDIM sampling loop (lax.scan over the step schedule, one compiled
    denoiser body). prompt_ids [B, T] int32 (0-padded) → images
    [B, S, S, 3] in [0, 1]."""
    B = prompt_ids.shape[0]
    emb = params["text_embed"][prompt_ids]               # [B, T, E]
    nz = (prompt_ids > 0).astype(jnp.float32)[..., None]
    text_emb = (emb * nz).sum(1) / jnp.maximum(nz.sum(1), 1.0)

    betas = jnp.linspace(1e-4, 0.02, cfg.train_steps)
    abar = jnp.cumprod(1.0 - betas)
    idx = jnp.linspace(cfg.train_steps - 1, 0,
                       cfg.sample_steps).astype(jnp.int32)
    a_t = abar[idx]
    a_prev = jnp.concatenate([abar[idx[1:]], jnp.ones((1,))])

    x0 = jax.random.normal(rng, (B, cfg.image_size, cfg.image_size, 3))

    def step(x, sched):
        t_i, a, ap = sched
        eps = denoise(params, cfg, x, jnp.full((B,), t_i / cfg.train_steps),
                      text_emb)
        x0_pred = (x - jnp.sqrt(1.0 - a) * eps) * jax.lax.rsqrt(a)
        x0_pred = jnp.clip(x0_pred, -3.0, 3.0)
        x = jnp.sqrt(ap) * x0_pred + jnp.sqrt(1.0 - ap) * eps
        return x, None

    x, _ = jax.lax.scan(step, x0, (idx.astype(jnp.float32), a_t, a_prev))
    return jnp.clip(x * 0.5 + 0.5, 0.0, 1.0)


class DiffusionImageBackend(ImageBackend):
    """ImageBackend serving the in-tree diffusion model on-device.

    Prompt conditioning uses byte-level token ids (same id scheme as
    ByteTokenizer) so no tokenizer asset is required; per-image seeds are
    prompt-derived and deterministic, matching the procedural backend's
    reproducibility contract.
    """

    def __init__(self, cfg: Optional[DiffusionConfig] = None,
                 params: Optional[dict] = None, seed: int = 0,
                 models: Sequence[str] = ("xla:diffusion-v0",),
                 cost_per_image: float = 0.0):
        self.cfg = cfg or DiffusionConfig()
        self.params = (params if params is not None
                       else init_diffusion_params(self.cfg,
                                                  jax.random.PRNGKey(seed)))
        self.models = list(models)
        self.cost_per_image = cost_per_image

    def _prompt_ids(self, prompt: str, max_len: int = 64) -> np.ndarray:
        ids = [min(b + 3, self.cfg.vocab_size - 1)
               for b in prompt.encode("utf-8")[:max_len]]
        out = np.zeros((max_len,), np.int32)
        out[:len(ids)] = ids
        return out

    def generate(self, prompt: str, *, count: int = 1,
                 size: str = "256x256",
                 out_dir: Optional[str] = None) -> list[GeneratedImage]:
        try:
            w, h = (int(x) for x in size.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad size {size!r}; expected WxH")
        w, h = max(8, min(w, 1024)), max(8, min(h, 1024))
        out_dir = out_dir or "/tmp"
        os.makedirs(out_dir, exist_ok=True)
        n = max(1, min(count, 8))
        seed = int.from_bytes(
            hashlib.sha256(prompt.encode()).digest()[:4], "big")
        ids = jnp.asarray(np.stack([self._prompt_ids(prompt)] * n))
        imgs = ddim_sample(self.params, self.cfg, ids,
                           jax.random.PRNGKey(seed))
        imgs = np.asarray(imgs)                          # [n, S, S, 3]
        out = []
        for i in range(n):
            # nearest-neighbor resize to the requested size host-side
            S = self.cfg.image_size
            yi = (np.arange(h) * S // h)
            xi = (np.arange(w) * S // w)
            px = (imgs[i][yi][:, xi] * 255).astype(np.uint8)
            path = os.path.join(
                out_dir,
                f"img-{uuid.uuid4().hex[:10]}-{int(time.time())}.png")
            write_png(path, px.tobytes(), w, h)
            out.append(GeneratedImage(
                path=path, model=self.models[i % len(self.models)],
                width=w, height=h, cost=self.cost_per_image))
        return out
