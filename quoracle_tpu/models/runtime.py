"""ModelBackend: the seam between the consensus pipeline and model execution.

This interface replaces the reference's entire provider layer — where
ModelQuery fanned out one HTTPS task per model
(reference lib/quoracle/models/model_query.ex:51,88-131), here
``query()`` receives the whole round and batches rows per pool member into
single generate steps on the TPU. Two implementations:

  * TPUBackend  — real serving: one GenerateEngine per pool member + an
    EmbeddingEncoder; zero external calls.
  * MockBackend — deterministic, scripted; the test seam the reference gets
    from mock: model specs + injectable model_query_fn
    (reference consensus/manager.ex:17-21, per_model_query.ex:84,227).

Both are handed to components explicitly (no globals), preserving the
reference's cardinal DI rule (root AGENTS.md:5-33).
"""

from __future__ import annotations

import abc
import dataclasses
import logging
import time
from typing import Any, Callable, Optional, Sequence

import numpy as np

from quoracle_tpu.chaos.faults import CHAOS, InjectedFault
from quoracle_tpu.models.config import (
    OUTPUT_FLOOR, ModelConfig, get_model_config,
)
from quoracle_tpu.infra.telemetry import TRACER
from quoracle_tpu.models.generate import (
    ContextOverflowError, GenerateEngine, splice_session_prompt,
)
from quoracle_tpu.models.tokenizer import Tokenizer, get_tokenizer
from quoracle_tpu.serving.admission import (
    AdmissionError, DeadlineExceededError,
)

logger = logging.getLogger(__name__)


def _row_key(r: dict) -> tuple:
    """Chip-economics attribution key (ISSUE 17) for one generate-row
    dict — integer QoS priorities render as class names so the ledger
    shares the budget plane's vocabulary."""
    from quoracle_tpu.serving.qos import class_name
    return (str(r.get("tenant") or "-"),
            class_name(r.get("priority") if r.get("priority") is not None
                       else 1),
            str(r.get("task_id") or "-"), str(r.get("decide") or "-"))


@dataclasses.dataclass
class QueryRequest:
    """One model's slice of a consensus round."""
    model_spec: str                    # "xla:llama-3-8b"
    messages: list[dict]               # chat messages (system injected already)
    temperature: float = 1.0
    top_p: float = 1.0
    max_tokens: Optional[int] = None   # None = dynamic (window - input, capped)
    # KV residency key (normally the agent id): rows with a session reuse
    # the prompt prefix already resident in that session's cache and refill
    # only the suffix (GenerateEngine sessions; SURVEY §7 hard part 2).
    session_id: Optional[str] = None
    # Grammar-masked sampling: the response is a syntactically valid JSON
    # object by construction (models/constrained.py; SURVEY §7 hard part 4).
    constrain_json: bool = False
    # Schema-aware variant: constrain the top-level "action" value to this
    # capability-gated set (None = syntax-only). Only read when
    # constrain_json is True.
    action_enum: Optional[tuple] = None
    # -- serving QoS (ISSUE 4) ----------------------------------------
    # Multi-tenant attribution + scheduling class (serving/qos.Priority;
    # None = AGENT) + a relative latency budget: a row still queued when
    # ``deadline_ms`` has elapsed since query() entry is failed at admit
    # (DeadlineExceededError → a "deadline_exceeded:" member miss), not
    # decoded. QoS moves WHEN rows run, never what they compute.
    tenant: str = "default"
    priority: Optional[int] = None
    deadline_ms: Optional[float] = None
    # -- fleet observability (ISSUE 15) -------------------------------
    # Compact trace context ({"trace_id", "span_id"}, infra/fleetobs.
    # TraceContext.to_dict) stamped by the sender so a peer process can
    # rebind TRACER and its spans land in the SAME trace. None = root
    # locally (the un-traced behavior). Observability only: never read
    # by generate/sampling paths, so temp-0 bits are identical with or
    # without it.
    trace: Optional[dict] = None
    # -- chip economics (ISSUE 17) -------------------------------------
    # Attribution keys for the ChipLedger: the owning task/agent-tree
    # (the PR 5 audit's task_id) and the decide id within it. Read only
    # by the costobs charge path — never by generate/sampling.
    task_id: Optional[str] = None
    decide: Optional[str] = None
    # -- session-graph observability (ISSUE 20) ------------------------
    # Compact tree context (infra/treeobs.TreeContext.to_dict: tree /
    # node / parent ids + depth + spawn ordinal) stamped at the agent
    # spawn that issued this request, riding rows and wire headers like
    # ``trace`` above. Read only by treeobs charge sites — never by
    # generate/sampling, so temp-0 bits are identical with or without
    # it.
    tree: Optional[dict] = None


@dataclasses.dataclass
class Usage:
    prompt_tokens: int = 0
    completion_tokens: int = 0
    cost: float = 0.0


@dataclasses.dataclass
class QueryResult:
    model_spec: str
    text: str = ""
    usage: Usage = dataclasses.field(default_factory=Usage)
    latency_ms: float = 0.0
    # Per-phase device timing (SURVEY §5 tracing): prefill is MXU-bound,
    # decode is HBM-bound — a single latency hides which one regressed.
    prefill_ms: float = 0.0
    decode_ms: float = 0.0
    # Prompt tokens served from resident KV (session resume or a radix
    # prefix-cache hit, models/prefix_cache.py) instead of re-prefilled.
    cached_tokens: int = 0
    # Speculative serving attribution (ISSUE 6): draft/verify rounds this
    # row rode and draft tokens the target accepted — rolls up into
    # ConsensusOutcome.spec_{accepted_tokens,rounds} for per-decide
    # speedup attribution at /api/consensus.
    spec_rounds: int = 0
    spec_accepted_tokens: int = 0
    # Chip economics (ISSUE 17): this result's measured share of device
    # wall (infra/costobs.ChipLedger row shares, ms). 0.0 with
    # accounting off or on self-driving paths (v1 spec decoder).
    chip_ms: float = 0.0
    error: Optional[str] = None        # None = success
    permanent_error: bool = False      # parity: only auth-type errors are
                                       # permanent (model_query.ex:322-332)

    @property
    def ok(self) -> bool:
        return self.error is None


class ModelBackend(abc.ABC):
    """What the consensus layer depends on. All methods are synchronous and
    thread-safe; the agent runtime calls them from executor threads."""

    @abc.abstractmethod
    def query(self, requests: Sequence[QueryRequest]) -> list[QueryResult]: ...

    @abc.abstractmethod
    def embed(self, texts: Sequence[str]) -> list[np.ndarray]: ...

    @abc.abstractmethod
    def count_tokens(self, model_spec: str, text: str) -> int: ...

    def count_message_tokens(self, model_spec: str, messages: Sequence[dict]) -> int:
        from quoracle_tpu.models.tokenizer import _stringify_content
        total = 0
        for m in messages:
            content = m.get("content", "")
            if not isinstance(content, str):
                content = _stringify_content(content)
            total += self.count_tokens(model_spec, content) + 4  # role overhead
        return total

    @abc.abstractmethod
    def context_window(self, model_spec: str) -> int: ...

    @abc.abstractmethod
    def output_limit(self, model_spec: str) -> int: ...

    def drop_session(self, session_id: str,
                     model_specs: Optional[Sequence[str]] = None) -> None:
        """Release resident KV state for a conversation (called on agent
        termination / pool switch). ``model_specs`` limits the drop to those
        members' engines — a pool switch keeps unchanged members' still-valid
        prefixes resident. No-op for backends without KV residency."""

    def attach_bus(self, bus) -> None:
        """Optional: give the backend an event bus to broadcast serving
        telemetry on (TOPIC_SERVING — prefix-cache hit/miss/evict counters,
        phase timings). No-op for backends without serving internals."""

    def watchdog_sources(self) -> list:
        """(name, progress_fn) pairs for the Runtime's stall watchdog
        (runtime.StallWatchdog); each fn returns (active, progress
        counter). Empty for backends without decode loops to watch."""
        return []

    def scheduler_stats(self) -> dict:
        """Per-member continuous-batcher health snapshots for
        /api/resources (queue depth, live rows, retired/failed counts).
        Empty for backends without a scheduler."""
        return {}

    def qos_stats(self) -> dict:
        """Serving-QoS snapshot for /api/qos (admission controller,
        per-member weighted-fair queues, SLO tracker). ``enabled`` False
        for backends without QoS wiring."""
        return {"enabled": False}

    def spec_stats(self) -> dict:
        """Speculative-serving snapshot for /api/models and the
        /telemetry view (ISSUE 6): per-member acceptance, adaptive-K
        state, and fallback attribution. ``enabled`` False for backends
        without draft models."""
        return {"enabled": False}

    def kv_stats(self) -> dict:
        """Tiered-KV snapshot for /api/kv (ISSUE 7): per-engine tier
        occupancy (HBM pages / host bytes / disk entries) and the
        demote/restore counters. ``enabled`` False for backends without
        tiering."""
        return {"enabled": False}

    def prefetch_sessions(self, session_id: str) -> int:
        """Warm hibernated KV for a conversation before it runs (the
        agent-tick prefetch hook, ISSUE 7): best-effort page-in on every
        engine holding a host-tier copy. Returns engines warmed. No-op
        for backends without tiering."""
        return 0


# ---------------------------------------------------------------------------
# TPU backend
# ---------------------------------------------------------------------------

def _encode_multimodal(engine, messages) -> tuple[list[int], Optional[object]]:
    """VLM prompt construction: the first image part in the conversation
    becomes ``n_patches`` placeholder ids at its position in the rendered
    chat (the engine's VLM prefill splices projected patches there); any
    further images degrade to the textual "[image]" marker. Returns
    (ids, preprocessed HWC image or None).

    Reference parity: ImageDetector collects base64/URL image parts into
    the provider payload (reference agent/consensus/image_detector.ex);
    here the payload is the in-tree vision tower's pixel input."""
    import base64

    cfg = engine.cfg
    tok = engine.tokenizer
    SENT = "\x00IMG\x00"
    image = None
    flat = []
    for m in messages:
        content = m.get("content", "")
        if isinstance(content, str):
            flat.append({"role": m.get("role", "user"), "content": content})
            continue
        parts_txt = []
        for part in content if isinstance(content, (list, tuple)) else []:
            if not isinstance(part, dict):
                parts_txt.append(str(part))
                continue
            if part.get("type") in ("image", "image_base64", "image_url"):
                data = (part.get("data") or part.get("image_base64")
                        or part.get("base64"))
                if image is None and data:
                    try:
                        from quoracle_tpu.native.image import (
                            preprocess_for_vision,
                        )
                        image = preprocess_for_vision(
                            base64.b64decode(data),
                            size=cfg.vision.image_size)
                        parts_txt.append(SENT)
                        continue
                    except Exception:
                        logger.warning(
                            "image part could not be decoded; degrading "
                            "to [image]")
                parts_txt.append("[image]")
            else:
                parts_txt.append(str(part.get("text", "")))
        flat.append({"role": m.get("role", "user"),
                     "content": "\n".join(parts_txt)})
    rendered = tok.render_chat(flat)
    if image is not None and SENT in rendered:
        pre, post = rendered.split(SENT, 1)
        ids = (tok.encode(pre, add_bos=True)
               + [cfg.image_token_id] * cfg.vision.n_patches
               + tok.encode(post))
        return ids, image
    return tok.encode(rendered, add_bos=True), None


class _MemberBatcher:
    """Baton batching for one pool member: concurrent consensus rounds
    (different agents, same model) coalesce into ONE engine.generate.

    The serve lock's holder drains EVERYTHING queued while it served —
    contention itself is the batching signal, so an uncontended call pays
    zero added latency (no timer window). bench config 3 measures the win:
    3 agents' rows batched cost 1.3× one agent's round instead of 3×.
    """

    def __init__(self, engine: GenerateEngine):
        from quoracle_tpu.analysis.lockdep import named_lock
        self.engine = engine
        self._serve = named_lock("member.serve")
        self._plock = named_lock("member.pending")
        # pending SUBMISSIONS (one per query() caller), not flattened rows:
        # a merged-batch failure can then retry per submission, keeping one
        # agent's pathological round from poisoning its neighbors'.
        self._pending: list[tuple[list[dict], list]] = []

    def submit(self, rows: list[dict]) -> list:
        """rows: per-row generate kwargs dicts. Returns Futures resolving
        to (GenResult, prefill_ms, decode_ms) — phase timings snapshot at
        serve time (a later batch would overwrite the engine's last_*)."""
        from concurrent.futures import Future, wait
        futs = [Future() for _ in rows]
        with self._plock:
            self._pending.append((rows, futs))
        while not all(f.done() for f in futs):
            if self._serve.acquire(blocking=False):
                try:
                    self._drain(mine=futs)
                finally:
                    self._serve.release()
            else:
                # another thread holds the baton; it will drain us — the
                # short timeout covers the narrow window where it swept
                # pending just before our enqueue
                wait(futs, timeout=0.005)
        return futs

    def _generate(self, subs: list[tuple[list[dict], list]]) -> None:
        pairs = [(r, f) for sub_rows, sub_futs in subs
                 for r, f in zip(sub_rows, sub_futs)]
        # Deadline-aware drop at serve time (ISSUE 4): a row whose
        # budget elapsed while waiting for the baton is failed here —
        # the batch runs without it rather than decoding dead work.
        live: list = []
        now = time.monotonic()
        for r, f in pairs:
            dl = r.get("deadline_s")
            if dl is not None and now >= dl:
                if not f.done():
                    f.set_exception(DeadlineExceededError(
                        "deadline passed before the member batch served "
                        "this row", tenant=r.get("tenant"),
                        priority=r.get("priority")))
            else:
                live.append((r, f))
        if not live:
            return
        rows = [r for r, _ in live]
        # chip-economics attribution (ISSUE 17): declare the merged
        # batch's row keys on the serving thread for the engine's
        # charge site (dicts carry tenant/priority/task_id/decide)
        from quoracle_tpu.infra import costobs
        costobs.set_row_keys([_row_key(r) for r in rows])
        gens = self.engine.generate(
            [r["prompt"] for r in rows],
            temperature=[r["temperature"] for r in rows],
            top_p=[r["top_p"] for r in rows],
            max_new_tokens=[r["budget"] for r in rows],
            session_ids=([r["session_id"] for r in rows]
                         if any(r["session_id"] for r in rows) else None),
            constrain_json=([r["constrain_json"] for r in rows]
                            if any(r["constrain_json"] for r in rows)
                            else None),
            action_enums=([r["action_enum"] for r in rows]
                          if any(r["action_enum"] for r in rows) else None),
            images=([r["image"] for r in rows]
                    if any(r["image"] is not None for r in rows)
                    else None))
        phases = (self.engine.last_prefill_s * 1000,
                  self.engine.last_decode_s * 1000)
        for (_, f), g in zip(live, gens):
            f.set_result((g, *phases))

    def _drain(self, mine: list) -> None:
        # Serve until OUR futures are done (plus whatever queued alongside
        # them); once they are, stop — remaining submitters poll the baton
        # themselves, so one thread never becomes the pool's permanent
        # server while its own round sits finished.
        while not all(f.done() for f in mine):
            with self._plock:
                subs, self._pending = self._pending[:], []
            if not subs:
                return
            # QoS (ISSUE 4): serve urgent submissions first. All of a
            # drain's submissions still merge into one generate, so this
            # only matters when a failure forces the per-submission
            # retry — the stable sort keeps arrival order within a class.
            subs.sort(key=lambda s: min(
                (r.get("priority") or 1 for r in s[0]), default=1))
            try:
                self._generate(subs)
            except Exception:
                # merged batch failed: retry per SUBMISSION so only the
                # genuinely failing caller's rows error
                for sub in subs:
                    try:
                        self._generate([sub])
                    except Exception as e:
                        for f in sub[1]:
                            if not f.done():
                                f.set_exception(e)


class TPUBackend(ModelBackend):
    """Serves a pool of catalog models resident on the chip/mesh.

    With exact tokenizers there is no 12% estimation margin (reference
    per_model_query.ex:20-24) — max_tokens = window - exact_input, floored.
    """

    def __init__(self, pool: Sequence[str], *, seed: int = 0,
                 embed_model: Optional[str] = None,
                 engines: Optional[dict[str, GenerateEngine]] = None,
                 embedder=None, init_params_fn=None,
                 submeshes: Optional[Sequence] = None,
                 overlap: bool = True,
                 continuous: bool = False, continuous_chunk: int = 32,
                 continuous_slots: int = 8,
                 draft_map: Optional[dict] = None, draft_k: int = 6,
                 qos=None, host_kv_mb: int = 0,
                 disk_kv_dir: Optional[str] = None,
                 disk_kv_gb: float = 8.0,
                 quantize_weights: bool = False,
                 quantize_kv: bool = False):
        """``submeshes``: one jax Mesh per pool member (parallel.mesh.
        pool_submeshes) — each member's engine serves tp-sharded on its own
        chips, and ``overlap`` runs members concurrently from host threads
        instead of the sequential loop (SURVEY §7 hard part 1). None =
        single-device engines.

        ``continuous`` replaces round-granularity baton batching with
        DECODE-level continuous batching (models/scheduler.py): each
        member runs a chunked decode loop that concurrent agents' text
        rows join and leave at ``continuous_chunk``-token boundaries, up
        to ``continuous_slots`` rows per step. Image rows (which skip KV
        sessions by design) stay on the baton path. Under continuous
        mode the per-call prefill/decode phase split is not meaningful
        (many rows share each device step) and is reported as 0.

        ``qos`` turns on serving QoS (ISSUE 4): pass True for defaults
        or a serving/qos.QoSConfig. Each member's continuous batcher
        then admits through a weighted-fair DRR queue (aging floor
        included), a shared AdmissionController sheds under overload
        with structured ``retry_after_ms`` rejects, and a shared
        SLOTracker demotes bulk-class weight while the INTERACTIVE
        latency tail is over target."""
        import jax
        from quoracle_tpu.models.embeddings import EmbeddingEncoder
        from quoracle_tpu.models.transformer import init_params

        self.pool = list(pool)
        self.engines: dict[str, GenerateEngine] = dict(engines or {})
        self.overlap = overlap
        self._bus = None          # attach_bus: serving-telemetry broadcasts
        init_fn = init_params_fn or init_params
        # Int8 quantized serving (ISSUE 13, models/quant.py): applied
        # uniformly to every engine this backend builds — pool members
        # AND their draft engines — so a member's whole decode stack
        # (draft, verify, vanilla) shares one numeric regime and the
        # quantized self-consistency gates hold across modes.
        self.quantize_weights = bool(quantize_weights)
        self.quantize_kv = bool(quantize_kv)

        def build_engine(spec: str, i: int, mesh=None) -> GenerateEngine:
            cfg = get_model_config(spec)
            if cfg.checkpoint_path:
                # Real weights: HF safetensors → stacked pytree
                # (models/loader.py); the catalog entry carries the path
                # (register_hf_checkpoint). With a mesh, leave params as
                # host numpy — the engine's shard_params places them
                # directly; going through to_device first would park a
                # whole replicated copy on one chip.
                from quoracle_tpu.models.loader import load_params, to_device
                params = load_params(cfg.checkpoint_path, cfg)
                if mesh is None:
                    params = to_device(params)
            else:
                params = init_fn(cfg, jax.random.PRNGKey(seed + i))
            return GenerateEngine(cfg, params, get_tokenizer(spec),
                                  seed=seed + i, mesh=mesh,
                                  quantize_weights=self.quantize_weights,
                                  quantize_kv=self.quantize_kv)

        for i, spec in enumerate(self.pool):
            if spec in self.engines:
                continue
            mesh = submeshes[i % len(submeshes)] if submeshes else None
            self.engines[spec] = build_engine(spec, i, mesh)

        # Tiered KV (ISSUE 7, serving/kvtier.py): HBM eviction demotes
        # hibernating sessions to a per-member host-RAM page store
        # (``host_kv_mb`` each) and prefix-cache blocks persist to a
        # checksummed disk store under ``disk_kv_dir`` that warm-starts
        # the next process. Pool members only — draft engines' shadow
        # sessions are derived state, cheaper to re-draft than to park.
        self.kv_tiered = bool(host_kv_mb or disk_kv_dir)
        if self.kv_tiered:
            for spec in self.pool:
                self.engines[spec].attach_tier(
                    host_mb=host_kv_mb or 256, disk_dir=disk_kv_dir,
                    disk_gb=disk_kv_gb)

        # Speculative serving (models/speculative.py): draft_map routes a
        # member's decode through draft-K/verify-one-chunk decoding —
        # output stays token-exact at temperature 0. Draft engines load
        # like members but never serve as pool members themselves. Two
        # integrations by dispatch mode (ISSUE 6):
        #   * continuous=True — the PRODUCTION path: one BatchedSpeculator
        #     per drafted member rides the ContinuousBatcher's decode
        #     ticks (batched draft scan + one chunked multi-row verify per
        #     round against the paged session KV; adaptive K with vanilla
        #     fallback). Built below, handed to the batcher.
        #   * baton mode — the v1 batch-1 dense-cache SpeculativeDecoder
        #     serves single uncontended text rows as before.
        self.draft_map = dict(draft_map or {})
        self._spec_decoders: dict = {}
        self._speculators: dict = {}
        if draft_map:
            from quoracle_tpu.models.speculative import (
                BatchedSpeculator, SpeculativeDecoder,
            )
            for j, (tspec, dspec) in enumerate(sorted(draft_map.items())):
                if tspec not in self.engines:
                    raise KeyError(f"draft_map target {tspec!r} is not in "
                                   f"the pool")
                if dspec not in self.engines:
                    self.engines[dspec] = build_engine(
                        dspec, len(self.pool) + 100 + j)
                te, de = self.engines[tspec], self.engines[dspec]
                if continuous:
                    self._speculators[tspec] = BatchedSpeculator(
                        te, de, k=draft_k)
                else:
                    self._spec_decoders[tspec] = SpeculativeDecoder(
                        te.cfg, te.params, de.cfg, de.params, te.tokenizer,
                        k=draft_k, max_seq=te.max_seq)

        # One baton batcher per POOL member (draft engines never serve
        # directly): concurrent agents' rounds coalesce
        self._batchers = {spec: _MemberBatcher(self.engines[spec])
                          for spec in self.pool}
        self.continuous = continuous
        self._cbatchers = {}
        # Serving QoS (ISSUE 4): ONE controller + SLO tracker shared
        # across members (overload and tail burn are system conditions),
        # one weighted-fair queue per member. qos=True → defaults.
        self.qos_controller = None
        self.slo = None
        qos_policies: dict[str, Any] = {}
        if qos:
            from quoracle_tpu.serving.admission import AdmissionController
            from quoracle_tpu.serving.qos import (
                QoSConfig, WeightedFairPolicy,
            )
            from quoracle_tpu.serving.slo import SLOTracker
            qcfg = qos if isinstance(qos, QoSConfig) else QoSConfig()
            self.slo = SLOTracker(targets_ms=qcfg.slo_targets_ms)
            # HBM-headroom signal (ISSUE 7): with tiering on, pages held
            # by demotable sessions/cache leaves are RECLAIMABLE without
            # loss — the controller sees raw headroom plus that margin,
            # so bulk classes are not shed for memory the tier ladder
            # can free on demand.
            from quoracle_tpu.infra.resources import (
                effective_headroom_fraction,
            )
            self.qos_controller = AdmissionController(
                config=qcfg.admission, tenants=qcfg.tenants,
                headroom_fn=(lambda: effective_headroom_fraction(self))
                if self.kv_tiered else None)
            qos_policies = {
                spec: WeightedFairPolicy(
                    weights=qcfg.weights, quantum=qcfg.quantum,
                    aging_floor_s=qcfg.aging_floor_s,
                    weight_fn=self.slo.weight_multiplier, model=spec)
                for spec in self.pool}
        if continuous:
            from quoracle_tpu.models.scheduler import ContinuousBatcher
            self._cbatchers = {
                spec: ContinuousBatcher(self.engines[spec],
                                        chunk=continuous_chunk,
                                        max_slots=continuous_slots,
                                        policy=qos_policies.get(spec),
                                        admission=self.qos_controller,
                                        slo=self.slo,
                                        speculator=self._speculators.get(
                                            spec))
                for spec in self.pool}
            if self.qos_controller is not None:
                for spec, pol in qos_policies.items():
                    self.qos_controller.register_depth_source(
                        spec, pol.qsize)

        if embedder is not None:
            self.embedder = embedder
        else:
            espec = embed_model or self.pool[0]
            if espec in self.engines:
                e = self.engines[espec]
                eparams, ecfg, etok = e.params, e.cfg, e.tokenizer
            else:
                ecfg = get_model_config(espec)
                eparams = init_fn(ecfg, jax.random.PRNGKey(seed + 101))
                etok = get_tokenizer(espec)
            self.embedder = EmbeddingEncoder(ecfg, eparams, etok)

    def close(self) -> None:
        """Stop the continuous batcher threads (no-op otherwise). Queued
        rows fail loudly rather than stranding waiters — scheduler.close()
        semantics. Tiered engines drain their queued disk spills so a
        clean shutdown hands its successor every persisted prefix (an
        abrupt kill loses at most the queue — the store is an
        optimization, never state)."""
        for cb in self._cbatchers.values():
            cb.close()
        for eng in self.engines.values():
            tier = getattr(eng.sessions, "tier", None)
            if tier is not None:
                try:
                    tier.flush_spills()
                except Exception:         # noqa: BLE001 — best-effort
                    pass

    # -- ModelBackend --

    def query(self, requests: Sequence[QueryRequest]) -> list[QueryResult]:
        """Group rows by pool member; one batched generate per member.

        Members OVERLAP: each member's generate is dispatched from its own
        host thread, so on sub-meshed slices the three models decode
        concurrently on their own chips (SURVEY.md §7 hard part 1; replaces
        the reference's Task.async-per-model HTTPS fan-out,
        per_model_query.ex:312-342). On a single chip the dispatches
        serialize on the device queue — same latency as the sequential loop.
        """
        by_model: dict[str, list[int]] = {}
        for i, r in enumerate(requests):
            by_model.setdefault(r.model_spec, []).append(i)

        results: list[Optional[QueryResult]] = [None] * len(requests)
        groups = list(by_model.items())
        # Span propagation across the member-thread hop: the consensus
        # round's span is thread-local to THIS thread, so capture it here
        # and rebind it inside each member thread (telemetry.TRACER.use).
        parent = TRACER.current()
        if self.overlap and len(groups) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(groups),
                                    thread_name_prefix="pool-member") as ex:
                list(ex.map(lambda g: self._query_member(
                    g[0], g[1], requests, results, parent), groups))
        else:
            for spec, idxs in groups:
                self._query_member(spec, idxs, requests, results, parent)
        self._broadcast_serving(by_model)
        return [r for r in results if r is not None]

    def attach_bus(self, bus) -> None:
        self._bus = bus

    def watchdog_sources(self) -> list:
        return [(f"decode-loop:{spec}", cb.progress)
                for spec, cb in self._cbatchers.items()]

    def scheduler_stats(self) -> dict:
        return {spec: cb.stats() for spec, cb in self._cbatchers.items()}

    def swap_draft(self, tspec: str, engine, name: Optional[str] = None):
        """Hot-swap the draft engine behind ``tspec``'s continuous-mode
        speculator (ISSUE 19 promotion path) and return the incumbent
        engine for instant rollback. The caller owns both engines'
        lifecycles — the swapped-out incumbent is NOT closed (a rollback
        re-installs the same object), and ``close()`` never reaches a
        swapped-in engine. Draft KV is derived state: rows cold
        re-prefill into the new draft's sessions on their next round."""
        speculator = self._speculators.get(tspec)
        if speculator is None:
            raise KeyError(f"no continuous speculator for {tspec!r} "
                           f"(draft_map: {sorted(self.draft_map)})")
        old = speculator.swap_draft(engine)
        self.draft_map[tspec] = name or engine.cfg.name
        return old

    def spec_stats(self) -> dict:
        if not self._speculators and not self._spec_decoders:
            return {"enabled": False}
        members = {spec: s.stats() for spec, s in self._speculators.items()}
        for spec, dec in self._spec_decoders.items():
            # v1 batch-1 decoders have no rolling scorecard — report the
            # wiring so /api/models shows which members are drafted
            members.setdefault(spec, {
                "mode": "batch1", "draft": dec.dc.name, "k": dec.k,
            })
        return {"enabled": True, "draft_map": dict(self.draft_map),
                "members": members}

    def kv_stats(self) -> dict:
        if not self.kv_tiered:
            return {"enabled": False}
        members = {}
        for spec in self.pool:
            e = self.engines[spec]
            st = e.sessions
            tier = st.tier
            if tier is None:
                continue
            with st.lock:
                free = len(st._free)
                n_sessions = len(st._sessions)
                occ = st.prefix_cache.occupancy()
            members[spec] = {
                "hbm": {
                    "pages": st.n_pages,
                    "free_pages": free,
                    "used_pages": st.n_pages - 1 - free,
                    "sessions": n_sessions,
                    "prefix_cache": occ,
                },
                # compression posture (ISSUE 13): /api/kv's compression
                # column — int8 members report their per-token byte
                # rate vs the bf16 rate they would otherwise pay
                "quant": e.quant_stats(),
                **tier.stats(),
            }
        return {"enabled": True, "members": members}

    def prefetch_sessions(self, session_id: str) -> int:
        if not self.kv_tiered:
            return 0
        warmed = 0
        for spec in self.pool:
            if self.engines[spec].prefetch_session(session_id):
                warmed += 1
        return warmed

    def qos_stats(self) -> dict:
        if self.qos_controller is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "admission": self.qos_controller.stats(),
            "slo": self.slo.stats() if self.slo is not None else None,
            "queues": {spec: cb.stats().get("qos")
                       for spec, cb in self._cbatchers.items()},
        }

    def _broadcast_serving(self, by_model: dict) -> None:
        """One TOPIC_SERVING event per query round: each queried member's
        phase timings + radix-prefix-cache counters, for the dashboard's
        ring-buffer replay (infra/event_history.py) and SSE tail. Never
        raises into the serving path."""
        if self._bus is None:
            return
        try:
            from quoracle_tpu.infra.bus import TOPIC_SERVING
            members = {}
            for spec in by_model:
                e = self.engines.get(spec)
                if e is None:
                    continue
                members[spec] = {
                    "prefill_tokens": e.last_prefill_tokens,
                    "prefill_ms": round(e.last_prefill_s * 1000, 1),
                    "decode_ms": round(e.last_decode_s * 1000, 1),
                    "kv_free_pages": e.sessions.free_pages(),
                    "prefix_cache": e.sessions.prefix_cache.stats(),
                }
            self._bus.broadcast(TOPIC_SERVING, {
                "event": "serving_round", "ts": time.time(),
                "members": members})
        except Exception:                 # noqa: BLE001 — telemetry only
            logger.exception("serving telemetry broadcast failed")

    def _query_member(self, spec: str, idxs: list[int],
                      requests: Sequence[QueryRequest],
                      results: list[Optional[QueryResult]],
                      parent=None) -> None:
        """One pool member's slice of the round, wrapped in a
        ``backend.member`` span (rebinding ``parent`` — the consensus
        round span captured on the query() thread). The member's device
        prefill/decode phases enter the trace retroactively from the
        QueryResult timings (the actual fences live in generate.py)."""
        with TRACER.use(parent):
            with TRACER.span("backend.member", model=spec) as msp:
                self._query_member_impl(spec, idxs, requests, results)
                done = [results[i] for i in idxs
                        if results[i] is not None and results[i].ok]
                msp.attrs.update(
                    n_rows=len(idxs),
                    cached_tokens=sum(r.cached_tokens for r in done))
                if done and (done[0].prefill_ms or done[0].decode_ms):
                    # phase timings are per-batch (identical across the
                    # member's rows) — one retroactive span per phase
                    TRACER.emit("generate.prefill", done[0].prefill_ms,
                                parent=msp, phase="prefill", model=spec)
                    TRACER.emit("generate.decode", done[0].decode_ms,
                                parent=msp, phase="decode", model=spec)

    def _query_member_impl(self, spec: str, idxs: list[int],
                           requests: Sequence[QueryRequest],
                           results: list[Optional[QueryResult]]) -> None:
        """Writes into disjoint ``results`` positions — safe from
        concurrent member threads."""
        if spec not in self.engines or spec not in self._batchers:
            # not a pool member — includes draft engines, which load into
            # self.engines but never serve directly
            for i in idxs:
                results[i] = QueryResult(
                    model_spec=spec, error=f"unknown model {spec!r}",
                    permanent_error=True)
            return
        # Chaos seam (ISSUE 11): member crash / slow / garbage at the
        # per-member query entry — a crash fails this member's rows with
        # the structured InjectedFault text (the consensus layer counts
        # it like any transport failure), a garbage directive perturbs
        # the member's OUTPUT after serving (drift-detection food).
        try:
            chaos = CHAOS.fire("pool.member", model=spec)
        except InjectedFault as e:
            for i in idxs:
                results[i] = QueryResult(model_spec=spec, error=str(e))
            return
        t0 = time.monotonic()
        rows, live_idxs = self._build_rows(spec, idxs, requests, results,
                                           t0)
        if not live_idxs:
            return
        self._dispatch_rows(spec, rows, live_idxs, results, t0)
        if chaos is not None and chaos.kind == "garbage":
            for i in live_idxs:
                r = results[i]
                if r is not None and r.ok:
                    results[i] = dataclasses.replace(
                        r, text=f"{r.text} [chaos-garbage:{chaos.n}]")

    def _build_rows(self, spec: str, idxs: list[int],
                    requests: Sequence[QueryRequest],
                    results: list, t0: float) -> tuple[list[dict],
                                                       list[int]]:
        """Row preparation for one member: chat-template encode (VLM
        splice included), session-token splice, per-row overflow /
        deadline checks (failed rows get their QueryResult written into
        ``results`` here), and the output-budget math. Split out of
        ``_query_member_impl`` so the cluster plane (serving/cluster.py)
        prepares IDENTICAL rows for its disaggregated prefill→decode
        flow — one row-construction semantics, zero drift."""
        engine = self.engines[spec]
        rows: list[dict] = []
        live_idxs: list[int] = []
        max_seq = engine.max_seq
        for i in idxs:
            r = requests[i]
            has_image = engine.cfg.vision is not None and any(
                isinstance(m.get("content"), (list, tuple))
                and any(isinstance(p, dict) and p.get("type") in
                        ("image", "image_base64", "image_url")
                        for p in m["content"])
                for m in r.messages)
            if has_image:
                ids, img = _encode_multimodal(engine, r.messages)
            else:
                # text-only requests keep the tokenizer's own chat template
                # (HF checkpoints) — only image-carrying prompts need the
                # placeholder-splicing render
                ids, img = engine.tokenizer.encode_chat(r.messages), None
                if r.session_id:
                    # Token-level session splice: share the session's ACTUAL
                    # ids (prompt + sampled response) as the prompt prefix so
                    # the retained response KV resumes too — re-encoding the
                    # assistant text would break the token match at the
                    # previous prompt's end (generate.splice_session_prompt).
                    sess_toks = engine.session_tokens(r.session_id)
                    if not sess_toks and spec in self._spec_decoders:
                        # speculative sessions live in the decoder, not
                        # the engine — splice against ITS resident ids
                        sess_toks = self._spec_decoders[
                            spec].session_tokens(r.session_id)
                    if sess_toks:
                        spliced = splice_session_prompt(
                            engine.tokenizer, sess_toks, ids)
                        # dropped-id decode asymmetries can inflate the
                        # spliced length — never let the splice push a
                        # fitting prompt over the window
                        if spliced is not None and len(spliced) < max_seq:
                            ids = spliced
            if len(ids) >= max_seq:
                # Per-ROW overflow: only the oversized row errors; the
                # rest of the group still runs (the condensation layer
                # retries this model after condensing).
                results[i] = QueryResult(
                    model_spec=spec,
                    error=f"context_overflow: prompt {len(ids)} tokens "
                          f">= window {max_seq}")
                continue
            window, out_lim = engine.cfg.context_window, engine.cfg.output_limit
            floor = min(OUTPUT_FLOOR, out_lim)
            budget = min(out_lim, max(floor, window - len(ids)))
            # QoS deadline: the relative budget anchors at query() entry
            # (t0) — time already burned tokenizing/splicing counts.
            deadline_s = (t0 + r.deadline_ms / 1000.0
                          if r.deadline_ms is not None else None)
            if deadline_s is not None and time.monotonic() >= deadline_s:
                # already dead at build time — covers every dispatch path
                # (speculative, baton, continuous) with one check
                results[i] = QueryResult(
                    model_spec=spec,
                    error=f"deadline_exceeded: {r.deadline_ms:.0f}ms "
                          f"budget elapsed before dispatch")
                continue
            rows.append({
                "prompt": ids, "temperature": r.temperature,
                "top_p": r.top_p,
                "budget": min(r.max_tokens, budget) if r.max_tokens
                          else budget,
                "session_id": r.session_id,
                "constrain_json": r.constrain_json,
                "action_enum": r.action_enum, "image": img,
                "priority": r.priority, "tenant": r.tenant,
                "deadline_s": deadline_s,
                "task_id": r.task_id, "decide": r.decide,
                "tree": r.tree,
            })
            live_idxs.append(i)
        return rows, live_idxs

    def _dispatch_rows(self, spec: str, rows: list[dict],
                       live_idxs: list[int], results: list,
                       t0: float) -> None:
        """Serve prepared rows through this backend's dispatch mode
        (continuous / speculative batch-1 / baton)."""
        engine = self.engines[spec]
        if self.continuous:
            self._query_member_continuous(spec, rows, live_idxs, results,
                                          t0)
            return
        dec = self._spec_decoders.get(spec)
        if (dec is not None and len(rows) == 1
                and rows[0]["image"] is None
                and (rows[0]["temperature"] <= 0
                     or rows[0]["top_p"] >= 1.0)
                # TRY-acquire: under concurrent agents the member
                # batcher's cross-agent coalescing beats serialized
                # speculation (batched decode already amortizes weight
                # streaming) — contention falls through to the baton
                # path; an uncontended single agent speculates
                # the decoder asserts prompt + max_new < max_seq (its
                # dense cache sizing); the OUTPUT_FLOOR-inflated budget
                # must be clamped like generate.py's per-row limits, and
                # a prompt leaving <1 token of room falls through to the
                # baton path's proper context_overflow handling
                and len(rows[0]["prompt"]) + 1 < engine.max_seq
                and dec.lock.acquire(blocking=False)):
            r0 = rows[0]
            i0 = live_idxs[0]
            cfg = engine.cfg
            budget = min(r0["budget"],
                         engine.max_seq - len(r0["prompt"]) - 1)
            try:
                g = dec.generate(
                    r0["prompt"], temperature=r0["temperature"],
                    top_p=r0["top_p"], max_new_tokens=budget,
                    constrain_json=bool(r0["constrain_json"]),
                    action_enum=r0["action_enum"],
                    session_id=r0["session_id"])
            except ContextOverflowError as e:
                results[i0] = QueryResult(model_spec=spec,
                                          error=f"context_overflow: {e}")
                return
            except Exception as e:    # noqa: BLE001 — row-level error
                results[i0] = QueryResult(model_spec=spec,
                                          error=f"generate failed: {e}")
                return
            finally:
                dec.lock.release()
            latency_ms = (time.monotonic() - t0) * 1000
            cost = (g.n_prompt_tokens * cfg.input_cost_per_mtok
                    + g.n_gen_tokens * cfg.output_cost_per_mtok) / 1e6
            results[i0] = QueryResult(
                model_spec=spec, text=g.text,
                usage=Usage(g.n_prompt_tokens, g.n_gen_tokens, cost),
                latency_ms=latency_ms,
                # draft/verify interleave: a prefill/decode split is not
                # meaningful (same convention as continuous mode)
                prefill_ms=0.0, decode_ms=0.0,
                cached_tokens=getattr(g, "n_cached_tokens", 0),
                spec_rounds=g.rounds,
                spec_accepted_tokens=g.accepted)
            return
        # The member's baton batcher may merge these rows with concurrent
        # agents' rounds into one generate.
        futs = self._batchers[spec].submit(rows)
        cfg = engine.cfg
        for i, f in zip(live_idxs, futs):
            try:
                g, prefill_ms, decode_ms = f.result()
            except ContextOverflowError as e:
                results[i] = QueryResult(model_spec=spec,
                                         error=f"context_overflow: {e}")
                continue
            except DeadlineExceededError as e:
                results[i] = QueryResult(model_spec=spec,
                                         error=f"deadline_exceeded: {e}")
                continue
            except AdmissionError as e:
                results[i] = QueryResult(
                    model_spec=spec,
                    error=f"admission_rejected: {e} "
                          f"(retry_after_ms={e.retry_after_ms})")
                continue
            except Exception as e:
                results[i] = QueryResult(model_spec=spec,
                                         error=f"generate failed: {e}")
                continue
            latency_ms = (time.monotonic() - t0) * 1000
            cost = (g.n_prompt_tokens * cfg.input_cost_per_mtok
                    + g.n_gen_tokens * cfg.output_cost_per_mtok) / 1e6
            results[i] = QueryResult(
                model_spec=spec, text=g.text,
                usage=Usage(g.n_prompt_tokens, g.n_gen_tokens, cost),
                latency_ms=latency_ms,
                prefill_ms=prefill_ms, decode_ms=decode_ms,
                cached_tokens=g.n_cached_tokens,
                chip_ms=getattr(g, "chip_ms", 0.0))

    def _query_member_continuous(self, spec: str, rows: list[dict],
                                 live_idxs: list[int],
                                 results: list, t0: float) -> None:
        """Continuous mode: text rows join the member's shared decode loop
        (models/scheduler.py) at chunk boundaries; image rows — which skip
        KV sessions by design — take a direct engine call."""
        engine = self.engines[spec]
        cfg = engine.cfg
        cb = self._cbatchers[spec]
        futs = []
        for r in rows:
            if r["image"] is not None:
                from concurrent.futures import Future
                f = Future()
                try:
                    # Sessionless image calls never touch the page pool
                    # (generate.py: paged stays False without session_ids)
                    # and the grammar cache now has its own lock
                    # (_grammar_lock), so a long VLM round runs WITHOUT
                    # engine._paged_lock — holding it here stalled every
                    # concurrent text agent's sessioned chunks for the
                    # whole image generate (ADVICE r4).
                    g = engine.generate(
                        [r["prompt"]], temperature=r["temperature"],
                        top_p=r["top_p"], max_new_tokens=r["budget"],
                        constrain_json=[r["constrain_json"]],
                        action_enums=[r["action_enum"]],
                        images=[r["image"]])[0]
                    f.set_result(g)
                except Exception as e:    # noqa: BLE001 — per-row capture
                    f.set_exception(e)
                futs.append(f)
            else:
                futs.append(cb.submit(
                    r["prompt"], temperature=r["temperature"],
                    top_p=r["top_p"], max_new_tokens=r["budget"],
                    session_id=r["session_id"],
                    constrain_json=r["constrain_json"],
                    action_enum=r["action_enum"],
                    priority=r["priority"], tenant=r["tenant"],
                    deadline_s=r["deadline_s"],
                    task_id=r.get("task_id"), decide=r.get("decide"),
                    tree=r.get("tree")))
        for i, f in zip(live_idxs, futs):
            try:
                g = f.result()
            except ContextOverflowError as e:
                results[i] = QueryResult(model_spec=spec,
                                         error=f"context_overflow: {e}")
                continue
            except DeadlineExceededError as e:
                results[i] = QueryResult(model_spec=spec,
                                         error=f"deadline_exceeded: {e}")
                continue
            except AdmissionError as e:   # structured shed, row-level
                results[i] = QueryResult(
                    model_spec=spec,
                    error=f"admission_rejected: {e} "
                          f"(retry_after_ms={e.retry_after_ms})")
                continue
            except Exception as e:        # noqa: BLE001 — row-level error
                results[i] = QueryResult(model_spec=spec,
                                         error=f"generate failed: {e}")
                continue
            latency_ms = (time.monotonic() - t0) * 1000
            cost = (g.n_prompt_tokens * cfg.input_cost_per_mtok
                    + g.n_gen_tokens * cfg.output_cost_per_mtok) / 1e6
            results[i] = QueryResult(
                model_spec=spec, text=g.text,
                usage=Usage(g.n_prompt_tokens, g.n_gen_tokens, cost),
                latency_ms=latency_ms, prefill_ms=0.0, decode_ms=0.0,
                cached_tokens=g.n_cached_tokens,
                spec_rounds=getattr(g, "spec_rounds", 0),
                spec_accepted_tokens=getattr(g, "spec_accepted_tokens",
                                             0),
                chip_ms=getattr(g, "chip_ms", 0.0))

    def embed(self, texts: Sequence[str]) -> list[np.ndarray]:
        return self.embedder.embed(texts)

    def drop_session(self, session_id: str,
                     model_specs: Optional[Sequence[str]] = None) -> None:
        keep = None if model_specs is None else set(model_specs)
        for spec, engine in self.engines.items():
            if keep is None or spec in keep:
                # the ENGINE's drop serializes with in-flight sessioned
                # generates — a bare store drop could free pages a running
                # batch still references
                engine.drop_session(session_id)
        for spec, dec in self._spec_decoders.items():
            if keep is None or spec in keep:
                # speculative sessions hold two full-size dense caches —
                # a dead session must not wait for LRU eviction, and a
                # reused id must not splice against the stale ctx
                dec.drop_session(session_id)

    def count_tokens(self, model_spec: str, text: str) -> int:
        return self.engines[model_spec].tokenizer.count(text)

    def context_window(self, model_spec: str) -> int:
        return get_model_config(model_spec).context_window

    def output_limit(self, model_spec: str) -> int:
        return get_model_config(model_spec).output_limit


# ---------------------------------------------------------------------------
# Mock backend (tests)
# ---------------------------------------------------------------------------

class MockBackend(ModelBackend):
    """Deterministic scripted backend.

    ``respond`` maps a QueryRequest to response text; default echoes a valid
    wait-action JSON so agent loops terminate. Per-model scripts let consensus
    tests drive disagreement/malformed/invalid scenarios the way the
    reference's MockResponseGenerator does
    (reference agent/consensus/mock_response_generator.ex:31-45).
    Every call is recorded for assertion (the reference's message-capture
    ``model_query_fn`` seam).
    """

    DEFAULT_POOL = ["mock:consensus-model-1", "mock:consensus-model-2",
                    "mock:consensus-model-3"]

    def __init__(self, respond: Optional[Callable[[QueryRequest], str]] = None,
                 scripts: Optional[dict[str, list[str]]] = None,
                 embedder=None, context_window_tokens: int = 128_000,
                 output_limit_tokens: int = 4096,
                 latency_ms: float = 0.0):
        from quoracle_tpu.models.embeddings import HashingEmbedder
        self._respond = respond
        self._scripts = {k: list(v) for k, v in (scripts or {}).items()}
        self._embedder = embedder or HashingEmbedder()
        self._window = context_window_tokens
        self._output_limit = output_limit_tokens
        self._latency_ms = latency_ms
        self.calls: list[QueryRequest] = []

    def query(self, requests: Sequence[QueryRequest]) -> list[QueryResult]:
        out = []
        for r in requests:
            self.calls.append(r)
            # Chaos seam (ISSUE 11): the SAME pool.member injection
            # point as TPUBackend, so member crash/slow/garbage
            # scenarios (drift storms feeding PR 5 detection) run on the
            # mock pool in tier-1 at zero device cost.
            try:
                chaos = CHAOS.fire("pool.member", model=r.model_spec)
            except InjectedFault as e:
                out.append(QueryResult(model_spec=r.model_spec,
                                       error=str(e)))
                continue
            # same span shape as the TPU backend so span-linkage tests
            # (and trace consumers) see decide → round → member on mocks
            with TRACER.span("backend.member", model=r.model_spec):
                script = self._scripts.get(r.model_spec)
                if script:
                    text = script.pop(0)
                elif self._respond is not None:
                    text = self._respond(r)
                else:
                    text = ('{"action": "wait", "params": {"duration": 1}, '
                            '"reasoning": "mock default"}')
            if chaos is not None and chaos.kind == "garbage":
                # a VALID but divergent proposal (a real registered
                # action, different from the healthy members' answer):
                # clusters away from them → dissent, which is what the
                # drift detector keys on. An unknown action would book
                # as a parse failure instead — a different signal.
                text = ('{"action": "orient", "params": '
                        '{"current_understanding": '
                        f'"chaos divergence {chaos.n}", '
                        '"progress_assessment": "diverging"}, '
                        '"wait": 30, '
                        '"reasoning": "chaos-injected divergence"}')
            if text == "__error__":
                out.append(QueryResult(model_spec=r.model_spec,
                                       error="scripted failure"))
                continue
            n_in = self.count_message_tokens(r.model_spec, r.messages)
            out.append(QueryResult(
                model_spec=r.model_spec, text=text,
                usage=Usage(n_in, max(1, len(text) // 4), 0.0),
                latency_ms=self._latency_ms))
        return out

    def embed(self, texts: Sequence[str]) -> list[np.ndarray]:
        return self._embedder.embed(texts)

    def count_tokens(self, model_spec: str, text: str) -> int:
        return max(1, len(text) // 4)

    def context_window(self, model_spec: str) -> int:
        return self._window

    def output_limit(self, model_spec: str) -> int:
        return self._output_limit
