"""Vision tower: a TPU-native ViT encoder feeding the decoder as soft
tokens (BASELINE config 5: image inputs → VLM member in the consensus
pool).

The reference has no local vision compute — images ride HTTPS to hosted
multimodal models (reference lib/quoracle/agent/consensus/image_detector.ex
collects base64/URL image parts into the provider payload). Here the tower
runs in-tree: ``native/image.py`` preprocesses (decode/resize/normalize,
C++ fast path), this module embeds patches and runs a pre-LN ViT
(lax.scan over stacked layers, like models/transformer.py), and a linear
projector maps patch embeddings into the decoder's embedding space —
the LLaVA-style soft-prompt interface. The decoder sees the image as
``n_patches`` placeholder tokens whose embeddings are replaced by the
projected patches (models/generate.py VLM prefill).

No weight-layout mapping to released VLM checkpoints yet — the tower is
an in-tree architecture (random or locally-trained weights); the serving
path, cost accounting, and consensus integration are real.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 4
    ffn_dim: int = 1024
    out_dim: int = 2048           # decoder embedding dim
    norm_eps: float = 1e-5

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


def init_vision_params(cfg: VisionConfig, key: jax.Array,
                       dtype=jnp.bfloat16) -> dict:
    k = jax.random.split(key, 8)
    L, D, F, P = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.patch_dim

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "patch_embed": normal(k[0], (P, D), P),
        "pos_embed": normal(k[1], (cfg.n_patches, D), D),
        "layers": {
            "ln1": jnp.ones((L, D), dtype),
            "wqkv": normal(k[2], (L, D, 3 * D), D),
            "wo": normal(k[3], (L, D, D), D),
            "ln2": jnp.ones((L, D), dtype),
            "w_up": normal(k[4], (L, D, F), D),
            "w_down": normal(k[5], (L, F, D), F),
        },
        "final_ln": jnp.ones((D,), dtype),
        "projector": normal(k[6], (D, cfg.out_dim), D),
    }


def _ln(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)
            * w.astype(jnp.float32)).astype(x.dtype)


def patchify(pixels: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, 3] float → [B, n_patches, patch*patch*3]."""
    B, H, W, C = pixels.shape
    ph, pw = H // patch, W // patch
    x = pixels.reshape(B, ph, patch, pw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, ph * pw, patch * patch * C)


def vision_encode(params: dict, cfg: VisionConfig,
                  pixels: jax.Array) -> jax.Array:
    """[B, H, W, 3] (preprocessed, ~N(0,1) channels) → soft tokens
    [B, n_patches, out_dim] in the DECODER's embedding space."""
    x = patchify(pixels.astype(jnp.float32), cfg.patch_size)
    x = jnp.einsum("bpd,dk->bpk", x,
                   params["patch_embed"].astype(jnp.float32))
    x = (x + params["pos_embed"].astype(jnp.float32)[None]).astype(
        params["patch_embed"].dtype)
    B, P, D = x.shape
    H, HD = cfg.n_heads, cfg.dim // cfg.n_heads

    def layer(x, p):
        h = _ln(x, p["ln1"], cfg.norm_eps)
        qkv = jnp.einsum("bpd,dk->bpk", h, p["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, P, H, HD)
        k = k.reshape(B, P, H, HD)
        v = v.reshape(B, P, H, HD)
        scores = jnp.einsum("bphd,bqhd->bhpq", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * (HD ** -0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bhpq,bqhd->bphd", probs,
                         v.astype(jnp.float32)).reshape(B, P, D)
        x = x + jnp.einsum("bpd,dk->bpk", att.astype(x.dtype), p["wo"])
        h = _ln(x, p["ln2"], cfg.norm_eps)
        up = jax.nn.gelu(jnp.einsum("bpd,df->bpf", h, p["w_up"]),
                         approximate=True)
        x = x + jnp.einsum("bpf,fd->bpd", up, p["w_down"])
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = _ln(x, params["final_ln"], cfg.norm_eps)
    return jnp.einsum("bpd,dk->bpk", x, params["projector"])


def splice_image_embeds(embeds: jax.Array, tokens: jax.Array,
                        image_embeds: jax.Array,
                        image_token_id: int) -> jax.Array:
    """Replace the embeddings of image-placeholder tokens with projected
    patches. ``embeds`` [B, T, D]; ``image_embeds`` [B, P, D]; row b's i-th
    placeholder (in sequence order) takes patch i. Rows without
    placeholders pass through; placeholders beyond P clamp to the last
    patch (prompt-construction bug guard, masked anyway)."""
    mask = tokens == image_token_id                    # [B, T]
    idx = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0,
                   image_embeds.shape[1] - 1)          # [B, T]
    gathered = jnp.take_along_axis(
        image_embeds, idx[:, :, None].astype(jnp.int32), axis=1)
    return jnp.where(mask[:, :, None], gathered.astype(embeds.dtype),
                     embeds)
