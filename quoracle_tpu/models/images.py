"""Image generation backend seam.

The reference fans image generation out to configured hosted image models
(reference lib/quoracle/models/image_query.ex:1-12 — Task.async_stream over
image models, 60s timeout, cost recording). The TPU-native seam is one
``ImageBackend.generate`` call; a real on-device diffusion model plugs in
behind it, and the default ProceduralImageBackend produces deterministic
placeholder PNGs (stdlib-only writer) so the action, cost pipeline, and
tests work end to end without a diffusion checkpoint.
"""

from __future__ import annotations

import abc
import dataclasses
import hashlib
import os
import struct
import time
import uuid
import zlib
from typing import Optional, Sequence


@dataclasses.dataclass
class GeneratedImage:
    path: str
    model: str
    width: int
    height: int
    cost: float = 0.0


def write_png(path: str, pixels: bytes, width: int, height: int) -> None:
    """Minimal RGB PNG writer (no PIL dependency)."""
    def chunk(tag: bytes, data: bytes) -> bytes:
        return (struct.pack(">I", len(data)) + tag + data
                + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))
    raw = b"".join(b"\x00" + pixels[y * width * 3:(y + 1) * width * 3]
                   for y in range(height))
    png = (b"\x89PNG\r\n\x1a\n"
           + chunk(b"IHDR", struct.pack(">IIBBBBB", width, height, 8, 2,
                                        0, 0, 0))
           + chunk(b"IDAT", zlib.compress(raw, 6))
           + chunk(b"IEND", b""))
    with open(path, "wb") as f:
        f.write(png)


class ImageBackend(abc.ABC):
    @abc.abstractmethod
    def generate(self, prompt: str, *, count: int = 1,
                 size: str = "256x256",
                 out_dir: Optional[str] = None) -> list[GeneratedImage]: ...


class ProceduralImageBackend(ImageBackend):
    """Deterministic prompt-seeded gradient/noise placeholder images."""

    def __init__(self, models: Sequence[str] = ("procedural:v0",),
                 cost_per_image: float = 0.0):
        self.models = list(models)
        self.cost_per_image = cost_per_image

    def generate(self, prompt: str, *, count: int = 1,
                 size: str = "256x256",
                 out_dir: Optional[str] = None) -> list[GeneratedImage]:
        try:
            w, h = (int(x) for x in size.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad size {size!r}; expected WxH")
        w, h = max(8, min(w, 1024)), max(8, min(h, 1024))
        out_dir = out_dir or "/tmp"
        os.makedirs(out_dir, exist_ok=True)
        images = []
        for i in range(max(1, min(count, 8))):
            seed = hashlib.sha256(f"{prompt}:{i}".encode()).digest()
            r0, g0, b0, r1, g1, b1 = seed[:6]
            rows = bytearray()
            for y in range(h):
                fy = y / max(1, h - 1)
                for x in range(w):
                    fx = x / max(1, w - 1)
                    n = seed[(x * 31 + y * 17) % len(seed)] / 255.0 * 0.25
                    rows.append(min(255, int(r0 + (r1 - r0) * fx + n * 40)))
                    rows.append(min(255, int(g0 + (g1 - g0) * fy + n * 40)))
                    rows.append(min(255, int(b0 + (b1 - b0) * (fx + fy) / 2
                                             + n * 40)))
            path = os.path.join(
                out_dir, f"img-{uuid.uuid4().hex[:10]}-{int(time.time())}.png")
            write_png(path, bytes(rows), w, h)
            images.append(GeneratedImage(
                path=path, model=self.models[i % len(self.models)],
                width=w, height=h, cost=self.cost_per_image))
        return images
