"""Model catalog: architecture configs + serving metadata.

Replaces the reference's LLMDB catalog (reference
lib/quoracle/models/llm_db_model_loader.ex) — context windows, output limits and
pricing lived in an external hex package there; here the catalog is the single
in-tree registry of models the TPU runtime can serve, keyed by the same
``provider:model`` spec format the reference uses (reference
lib/quoracle/models/local_model_helper.ex:13-19 is the precedent for an in-tree
provider bypass; ours is the ``xla:`` provider).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Minimum room a consensus round must leave for the response (reference
# per_model_query.ex:17-18 — 4096 output floor). Effective per-model floor is
# min(OUTPUT_FLOOR, output_limit); shared by TPUBackend.query and
# TokenManager.dynamic_max_tokens so both layers agree on when a history
# "fits".
OUTPUT_FLOOR = 4096


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture + serving config for one decoder-only transformer.

    Covers the Llama/Mistral/Gemma/Qwen families (RMSNorm, RoPE, GQA/MQA,
    gated MLP). Per-family quirks are expressed as data, not subclasses, so a
    single traced forward function serves every family.
    """

    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    head_dim: Optional[int] = None  # defaults to dim // n_heads
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    activation: str = "silu"  # "silu" (llama/mistral) or "gelu" (gemma)
    tie_embeddings: bool = False
    # Gemma multiplies token embeddings by sqrt(dim) (data, not code, per-family).
    scale_embeddings: bool = False
    # Gemma's RMSNorm computes (1 + w) * normed(x).
    rmsnorm_plus_one: bool = False
    # Sliding-window attention size (Mistral); None = full causal.
    sliding_window: Optional[int] = None
    # Optional logit soft-capping (Gemma-2 style); None = off.
    final_logit_softcap: Optional[float] = None
    # QKV projection biases (Qwen2-style).
    attn_bias: bool = False
    # RoPE frequency scaling, hashable: ("linear", factor) or
    # ("llama3", factor, low_freq_factor, high_freq_factor, original_max_pos).
    # None = unscaled. (Kept a tuple so ModelConfig stays hashable for jit.)
    rope_scaling: Optional[tuple] = None

    # --- serving metadata (what the reference pulled from LLMDB) ---
    context_window: int = 8192
    output_limit: int = 4096
    # Cost per 1M tokens (USD) for budget accounting parity with the
    # reference's cost pipeline; on-TPU serving is "free" but agents still
    # budget, so these are nominal accounting rates.
    input_cost_per_mtok: float = 0.05
    output_cost_per_mtok: float = 0.15
    eos_token_id: int = 2
    bos_token_id: int = 1
    # Additional stop ids beyond eos_token_id — llama-3-instruct style
    # checkpoints end chat turns with <|eot_id|> while config.eos lists
    # several ids; decode stops on ANY of {eos_token_id} | stop_token_ids.
    stop_token_ids: tuple = ()
    # HF checkpoint directory for real weights (models/loader.py); None =
    # random-init (tests/bench). The directory's tokenizer files are used too.
    checkpoint_path: Optional[str] = None
    # Recommended tensor-parallel width on a v5e-8 sub-mesh (must divide
    # n_kv_heads so KV shards carry whole GQA groups — parallel/mesh.py).
    # The pool-sizing math (parallel/mesh.py pool_sizing) turns this + the
    # param count into the explicit HBM budget VERDICT r4 item 4 asks for.
    recommended_tp: int = 1
    # VLM member (BASELINE config 5): an in-tree ViT tower whose projected
    # patches splice into the prompt at ``image_token_id`` placeholders
    # (models/vision.py). None = text-only model. VisionConfig is a frozen
    # dataclass, so ModelConfig stays hashable for jit.
    vision: Optional["VisionConfig"] = None          # noqa: F821
    image_token_id: Optional[int] = None

    def __post_init__(self):
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.dim // self.n_heads)
        assert self.n_heads % self.n_kv_heads == 0, "GQA requires n_heads % n_kv_heads == 0"

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    @property
    def n_params(self) -> int:
        """Exact decoder parameter count (embeddings + per-layer attn/mlp/
        norms + final norm + untied head) — the input to the HBM budget."""
        hd = self.head_dim
        embed = self.vocab_size * self.dim
        q = self.dim * self.n_heads * hd + (self.n_heads * hd
                                            if self.attn_bias else 0)
        kv = 2 * (self.dim * self.n_kv_heads * hd
                  + (self.n_kv_heads * hd if self.attn_bias else 0))
        o = self.n_heads * hd * self.dim
        mlp = 3 * self.dim * self.ffn_dim          # gate + up + down
        norms = 2 * self.dim
        per_layer = q + kv + o + mlp + norms
        head = 0 if self.tie_embeddings else self.vocab_size * self.dim
        total = embed + self.n_layers * per_layer + self.dim + head
        if self.vision is not None:
            # ViT tower + projector come out of the same HBM budget
            # (models/vision.py init_vision_params structure)
            v = self.vision
            v_layer = (2 * v.dim                    # ln1 + ln2
                       + v.dim * 3 * v.dim          # wqkv
                       + v.dim * v.dim              # wo
                       + 2 * v.dim * v.ffn_dim)     # w_up + w_down
            total += (v.patch_dim * v.dim           # patch_embed
                      + v.n_patches * v.dim         # pos_embed
                      + v.n_layers * v_layer
                      + v.dim                       # final_ln
                      + v.dim * v.out_dim)          # projector
        return total

    def kv_bytes_per_token(self, tp: int = 1, dtype_bytes: int = 2) -> int:
        """KV cache bytes per resident token PER TP SHARD (whole GQA
        groups per shard: kv heads divide across tp)."""
        return 2 * (self.n_kv_heads // tp) * self.head_dim * \
            self.n_layers * dtype_bytes


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, ModelConfig] = {}


def register_model(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_model_config(spec: str) -> ModelConfig:
    """Look up by model spec. Accepts ``xla:name`` or bare ``name``.

    Mirrors the reference's ``provider:model`` spec parsing
    (reference lib/quoracle/models/model_query.ex model_spec format).
    """
    name = spec.split(":", 1)[1] if ":" in spec else spec
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {spec!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_models() -> list[str]:
    return sorted(_REGISTRY)


# --- production-scale pool (the BASELINE.json north-star trio) ---

LLAMA3_8B = register_model(ModelConfig(
    name="llama-3-8b",
    vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, rope_theta=500000.0, norm_eps=1e-5,
    context_window=8192, output_limit=4096,
    eos_token_id=128001, bos_token_id=128000,
    # 8.0B params -> 16.1 GB bf16; tp=4 on a v5e-8 leaves ~4 GB/chip
    # weights + page pool + tail headroom (pool_sizing prints the table)
    recommended_tp=4,
))

MISTRAL_7B = register_model(ModelConfig(
    name="mistral-7b",
    vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    ffn_dim=14336, rope_theta=1000000.0, norm_eps=1e-5,
    context_window=32768, output_limit=8192, sliding_window=4096,
    # 7.2B params -> 14.5 GB bf16; tp=2 fits 7.3 GB/chip weights with the
    # 4096-token sliding window bounding resident KV per session
    recommended_tp=2,
))

GEMMA_7B = register_model(ModelConfig(
    name="gemma-7b",
    vocab_size=256000, dim=3072, n_layers=28, n_heads=16, n_kv_heads=16,
    ffn_dim=24576, head_dim=256, rope_theta=10000.0, norm_eps=1e-6,
    activation="gelu", tie_embeddings=True, scale_embeddings=True,
    rmsnorm_plus_one=True,
    context_window=8192, output_limit=4096,
    # 8.5B params (tied embeddings) -> 17.1 GB bf16; tp=2 -> 8.5 GB/chip:
    # tight but fits with a reduced page pool (MHA KV is the pressure —
    # 16 kv heads x 256 head_dim; pool_sizing flags the headroom)
    recommended_tp=2,
))

# --- bench-scale models (fit a single v5e chip with headroom; same families) ---

LLAMA_1B = register_model(ModelConfig(
    name="llama-1b",
    vocab_size=32768, dim=2048, n_layers=16, n_heads=16, n_kv_heads=4,
    ffn_dim=5632, rope_theta=500000.0,
    context_window=8192, output_limit=4096,
))

MISTRAL_1B = register_model(ModelConfig(
    name="mistral-1b",
    vocab_size=32768, dim=2048, n_layers=16, n_heads=16, n_kv_heads=4,
    ffn_dim=5632, rope_theta=1000000.0, sliding_window=4096,
    context_window=16384, output_limit=4096,
))

GEMMA_1B = register_model(ModelConfig(
    name="gemma-1b",
    vocab_size=32768, dim=1792, n_layers=14, n_heads=14, n_kv_heads=14,
    ffn_dim=7168, head_dim=128, activation="gelu", tie_embeddings=True,
    scale_embeddings=True, rmsnorm_plus_one=True, norm_eps=1e-6,
    context_window=8192, output_limit=4096,
))

# --- tiny test models (CPU-mesh friendly; divisible by 2 and 4 for tp tests) ---

TINY = register_model(ModelConfig(
    name="tiny",
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, context_window=512, output_limit=128,
))

TINY_GEMMA = register_model(ModelConfig(
    name="tiny-gemma",
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
    ffn_dim=128, activation="gelu", tie_embeddings=True,
    scale_embeddings=True, rmsnorm_plus_one=True,
    context_window=512, output_limit=128,
))

def _tiny_vision():
    from quoracle_tpu.models.vision import VisionConfig
    return VisionConfig(image_size=28, patch_size=14, dim=32, n_layers=1,
                        n_heads=2, ffn_dim=64, out_dim=64)


TINY_VLM = register_model(ModelConfig(
    name="tiny-vlm",
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, context_window=512, output_limit=128,
    vision=_tiny_vision(), image_token_id=3,
))

TINY_POOL = ["xla:tiny", "xla:tiny-gemma"]
BENCH_POOL = ["xla:llama-1b", "xla:mistral-1b", "xla:gemma-1b"]
NORTH_STAR_POOL = ["xla:llama-3-8b", "xla:mistral-7b", "xla:gemma-7b"]
