"""On-device embedding encoder.

Replaces the reference's HTTP embedding provider
(reference lib/quoracle/models/embeddings.ex) with an XLA encoder: mean-pooled
final hidden states of a catalog model, L2-normalized. Embeddings sit on the
consensus CRITICAL PATH (semantic-similarity merge rules call the embedder
during clustering — reference consensus/aggregator.ex:246-289), so this must
be a fast local call: one jitted batched encode, SHA-256 LRU cache in front
(same 1h TTL / 1000 entries semantics as the reference's ETS cache), long
texts token-chunked and averaged (reference embeddings.ex TokenChunker).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.tokenizer import Tokenizer
from quoracle_tpu.models.transformer import forward_hidden, init_cache
from quoracle_tpu.utils.cache import TTLCache, text_key


class EmbeddingEncoder:
    """Batched text -> unit vector encoder over a catalog model's hidden states."""

    BATCH_BUCKETS = (1, 4, 16, 64)

    def __init__(self, cfg: ModelConfig, params: dict, tokenizer: Tokenizer,
                 max_tokens: int = 512, cache: Optional[TTLCache] = None,
                 chunk_tokens: int = 256):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.max_tokens = max_tokens
        self.chunk_tokens = min(chunk_tokens, max_tokens)
        self.cache = cache if cache is not None else TTLCache()
        self._encode = self._build_encode()

    @property
    def dim(self) -> int:
        return self.cfg.dim

    def _build_encode(self):
        cfg = self.cfg

        @jax.jit
        def encode(params, tokens, lens):
            B, T = tokens.shape
            cache = init_cache(cfg, B, T)
            positions = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
            hidden, _ = forward_hidden(
                params, cfg, tokens, positions, cache,
                write_offset=jnp.zeros((B,), jnp.int32), kv_lens=lens)
            mask = (positions < lens[:, None]).astype(jnp.float32)[..., None]
            pooled = jnp.sum(hidden.astype(jnp.float32) * mask, axis=1) \
                / jnp.maximum(jnp.sum(mask, axis=1), 1.0)
            return pooled / jnp.maximum(
                jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)

        return encode

    def _encode_token_batch(self, token_lists: list[list[int]]) -> np.ndarray:
        n = len(token_lists)
        B = next((b for b in self.BATCH_BUCKETS if n <= b), n)
        T = max(8, max(len(t) for t in token_lists))
        T = 1 << (T - 1).bit_length()  # pow2 bucket
        tokens = np.zeros((B, T), np.int32)
        lens = np.ones((B,), np.int32)
        for i, t in enumerate(token_lists):
            tokens[i, :len(t)] = t
            lens[i] = max(1, len(t))
        out = self._encode(self.params, jnp.asarray(tokens), jnp.asarray(lens))
        return np.asarray(out)[:n]

    def embed(self, texts: Sequence[str]) -> list[np.ndarray]:
        """Cached batched embedding. Long texts are chunked and averaged."""
        results: dict[int, np.ndarray] = {}
        pending: list[tuple[int, list[list[int]]]] = []  # (text idx, chunks)
        for i, text in enumerate(texts):
            key = text_key(text, namespace=self.cfg.name)
            hit = self.cache.get(key)
            if hit is not None:
                results[i] = hit
                continue
            ids = self.tokenizer.encode(text or " ")
            chunks = [ids[j:j + self.chunk_tokens]
                      for j in range(0, len(ids), self.chunk_tokens)] or [[0]]
            pending.append((i, chunks))

        if pending:
            flat: list[list[int]] = []
            spans: list[tuple[int, int, int]] = []  # (text idx, start, count)
            for i, chunks in pending:
                spans.append((i, len(flat), len(chunks)))
                flat.extend(chunks)
            vecs = self._encode_token_batch(flat)
            for i, start, count in spans:
                v = vecs[start:start + count].mean(axis=0)
                v = v / max(float(np.linalg.norm(v)), 1e-9)
                results[i] = v
                self.cache.put(text_key(texts[i], namespace=self.cfg.name), v)

        return [results[i] for i in range(len(texts))]


def cosine_similarity(a: np.ndarray, b: np.ndarray) -> float:
    na, nb = float(np.linalg.norm(a)), float(np.linalg.norm(b))
    if na == 0.0 or nb == 0.0:
        return 0.0
    return float(np.dot(a, b) / (na * nb))


@functools.lru_cache(maxsize=None)
def _hash_basis(dim: int, seed: int = 7) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal((256, dim)).astype(np.float32)


class HashingEmbedder:
    """Deterministic, model-free embedder for tests (injectable the way the
    reference injects ``embedding_fn`` — aggregator.ex:250-267): byte-ngram
    counts projected through a fixed random basis. Similar strings land close;
    no device work."""

    def __init__(self, dim: int = 64):
        self._dim = dim

    @property
    def dim(self) -> int:
        return self._dim

    def embed(self, texts: Sequence[str]) -> list[np.ndarray]:
        basis = _hash_basis(self._dim)
        out = []
        for text in texts:
            counts = np.zeros(256, np.float32)
            data = text.encode("utf-8", errors="replace")
            for b in data:
                counts[b] += 1.0
            for a, b2 in zip(data, data[1:]):
                counts[(a * 31 + b2) % 256] += 0.5
            v = counts @ basis
            n = float(np.linalg.norm(v))
            out.append(v / n if n > 0 else v)
        return out
