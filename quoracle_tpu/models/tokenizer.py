"""Tokenizers for the in-tree model pool.

Replaces the reference's tiktoken Rust NIF, which only *estimated* token counts
with a cl100k approximation plus a 12% safety margin (reference
lib/quoracle/agent/token_manager.ex:19-24, per_model_query.ex:20-24). Here each
served model counts with its *own* tokenizer, so context budgeting is exact and
the margin drops to zero.

Three implementations behind one interface:
  * ByteTokenizer   — reversible byte-level vocab; tests, bench, tiny models.
  * HFTokenizer     — wraps a ``tokenizers``-format tokenizer.json when real
                      checkpoints are used.
  * native C++ BPE  — see native/ (drop-in via the same interface).

All are stateless after construction and safe to share across threads.
"""

from __future__ import annotations

import abc
from typing import Sequence

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_N_SPECIALS = 3


class Tokenizer(abc.ABC):
    """Interface the runtime, TokenManager, and consensus layers depend on."""

    pad_id: int = PAD_ID
    bos_id: int = BOS_ID
    eos_id: int = EOS_ID

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int]) -> str: ...

    def decode_raw(self, ids: Sequence[int]) -> str:
        """Decode for TEXT-PREFIX comparison (session splicing): must be
        consistent under concatenation of the same template's renderings —
        template marker tokens must not silently vanish on tokenizers where
        they re-encode losslessly (HF specials). Byte-level tokenizers keep
        their default decode: their specials have no textual form on either
        side of the comparison, so dropping them is consistent."""
        return self.decode(ids)

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    def count(self, text: str) -> int:
        return len(self.encode(text))

    # -- chat templating ----------------------------------------------------
    # The reference sends provider-formatted message arrays over HTTPS; here
    # we render them to a prompt string ourselves. One neutral template for
    # every family keeps prompt-parity tests model-independent.

    def render_chat(self, messages: Sequence[dict]) -> str:
        parts = []
        for m in messages:
            role = m.get("role", "user")
            content = m.get("content", "")
            if not isinstance(content, str):
                content = _stringify_content(content)
            parts.append(f"<|{role}|>\n{content}\n")
        parts.append("<|assistant|>\n")
        return "".join(parts)

    def encode_chat(self, messages: Sequence[dict]) -> list[int]:
        return self.encode(self.render_chat(messages), add_bos=True)


# Single multimodal-content stringifier for the whole stack: chat rendering,
# backend token counting, and TokenManager budgeting must all flatten content
# identically or their counts drift apart.
from quoracle_tpu.utils.normalize import stringify_content as _stringify_content


class ByteTokenizer(Tokenizer):
    """Byte-level reversible tokenizer: id = byte + 3 specials offset."""

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = [b + _N_SPECIALS for b in text.encode("utf-8")]
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # Ids beyond the byte range can appear when a model's vocab is larger
        # than the tokenizer's (tiny random-weight test models); skip them.
        data = bytes(i - _N_SPECIALS for i in ids
                     if _N_SPECIALS <= i < 256 + _N_SPECIALS)
        return data.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return 256 + _N_SPECIALS


class HFAutoTokenizer(Tokenizer):
    """The REAL tokenizer of a served checkpoint: transformers AutoTokenizer
    loaded from the checkpoint directory (local files only — this runtime
    never fetches). Uses the model's own chat template when the checkpoint
    ships one, so served prompts are formatted exactly as the model was
    trained; falls back to the neutral template otherwise.

    Replaces the reference's per-provider formatting + tiktoken estimate
    (reference token_manager.ex:19-24) with exact counts from the model's
    own vocab — the SURVEY §2.8 requirement.
    """

    def __init__(self, path: str):
        import os
        from transformers import AutoTokenizer
        if not any(os.path.isfile(os.path.join(path, f)) for f in
                   ("tokenizer.json", "vocab.json", "tokenizer_config.json")):
            # AutoTokenizer's own failure here is an obscure conversion
            # crash; fail with an actionable message instead.
            raise ValueError(
                f"checkpoint dir {path!r} has no tokenizer files "
                "(tokenizer.json / vocab.json) — a served checkpoint must "
                "ship its own tokenizer")
        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        # No invented specials: when the checkpoint's tokenizer defines no
        # bos, prepending the in-tree default id would inject an arbitrary
        # vocab token into every prompt.
        self._has_bos = self._tok.bos_token_id is not None
        self.bos_id = self._tok.bos_token_id \
            if self._has_bos else BOS_ID
        self.eos_id = self._tok.eos_token_id \
            if self._tok.eos_token_id is not None else EOS_ID
        self.pad_id = self._tok.pad_token_id \
            if self._tok.pad_token_id is not None else self.eos_id
        self._has_template = bool(getattr(self._tok, "chat_template", None))

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return [self.bos_id] + ids if add_bos and self._has_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def decode_raw(self, ids: Sequence[int]) -> str:
        # Keep template markers: the splice suffix must re-encode them back
        # to their special ids (added-token matching is independent of
        # add_special_tokens), or a spliced prompt would lose its chat
        # structure after the resumed region.
        return self._tok.decode(list(ids), skip_special_tokens=False,
                                clean_up_tokenization_spaces=False)

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode_chat(self, messages: Sequence[dict]) -> list[int]:
        msgs = [{"role": m.get("role", "user"),
                 "content": m.get("content", "")
                 if isinstance(m.get("content", ""), str)
                 else _stringify_content(m.get("content"))}
                for m in messages]
        if self._has_template:
            return list(self._tok.apply_chat_template(
                msgs, add_generation_prompt=True, tokenize=True))
        return self.encode(self.render_chat(msgs), add_bos=True)


class HFTokenizer(Tokenizer):
    """Binding over a HuggingFace ``tokenizers`` file (tokenizer.json)."""

    def __init__(self, path: str, bos_id: int = BOS_ID, eos_id: int = EOS_ID):
        from tokenizers import Tokenizer as _HF
        self._tok = _HF.from_file(path)
        self.bos_id = bos_id
        self.eos_id = eos_id

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def decode_raw(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=False)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


_TOK_CACHE: dict[tuple, Tokenizer] = {}


def get_tokenizer(model_name: str, tokenizer_path: str | None = None) -> Tokenizer:
    """Tokenizer for a catalog model. Tiny/bench models use bytes; real
    checkpoints use their own tokenizer files (HFAutoTokenizer).

    bos/eos ids come from the model's catalog entry so the tokenizer and the
    engine's stop condition always agree (the engine stops on
    ``ModelConfig.eos_token_id``). The cache key includes the resolved
    checkpoint path — re-registering a name with different weights (or
    registering AFTER a first lookup) must not pin a stale tokenizer."""
    from quoracle_tpu.models.config import get_model_config
    ckpt = None
    try:
        cfg = get_model_config(model_name)
        bos, eos, vocab = cfg.bos_token_id, cfg.eos_token_id, cfg.vocab_size
        ckpt = cfg.checkpoint_path
    except KeyError:
        bos, eos, vocab = BOS_ID, EOS_ID, 32768
    key = (model_name, tokenizer_path, ckpt, bos, eos, vocab)
    cached = _TOK_CACHE.get(key)
    if cached is not None:
        return cached
    if ckpt:                         # real checkpoint → its real tokenizer
        tok = HFAutoTokenizer(ckpt)
        _TOK_CACHE[key] = tok
        return tok
    if tokenizer_path:
        tok = HFTokenizer(tokenizer_path, bos_id=bos, eos_id=eos)
        _TOK_CACHE[key] = tok
        return tok
    tok: Tokenizer
    try:
        # Learned byte-level BPE sized to the model's vocab (tiny test
        # models get the byte-only prefix). Both the C++ and the Python
        # implementation read the same committed merges artifact.
        from quoracle_tpu.native.tokenizer import NativeBPETokenizer
        import os
        from quoracle_tpu.native.tokenizer import MERGES_PATH
        if os.path.isfile(MERGES_PATH):
            tok = NativeBPETokenizer.for_vocab(vocab)
        else:
            tok = ByteTokenizer()
    except ImportError:
        tok = ByteTokenizer()
    tok.bos_id, tok.eos_id = bos, eos
    _TOK_CACHE[key] = tok
    return tok


# lru_cache-compatible reset hook (tests and hot-reload paths use it)
get_tokenizer.cache_clear = _TOK_CACHE.clear
