"""Tokenizers for the in-tree model pool.

Replaces the reference's tiktoken Rust NIF, which only *estimated* token counts
with a cl100k approximation plus a 12% safety margin (reference
lib/quoracle/agent/token_manager.ex:19-24, per_model_query.ex:20-24). Here each
served model counts with its *own* tokenizer, so context budgeting is exact and
the margin drops to zero.

Three implementations behind one interface:
  * ByteTokenizer   — reversible byte-level vocab; tests, bench, tiny models.
  * HFTokenizer     — wraps a ``tokenizers``-format tokenizer.json when real
                      checkpoints are used.
  * native C++ BPE  — see native/ (drop-in via the same interface).

All are stateless after construction and safe to share across threads.
"""

from __future__ import annotations

import abc
from functools import lru_cache
from typing import Sequence

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_N_SPECIALS = 3


class Tokenizer(abc.ABC):
    """Interface the runtime, TokenManager, and consensus layers depend on."""

    pad_id: int = PAD_ID
    bos_id: int = BOS_ID
    eos_id: int = EOS_ID

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int]) -> str: ...

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    def count(self, text: str) -> int:
        return len(self.encode(text))

    # -- chat templating ----------------------------------------------------
    # The reference sends provider-formatted message arrays over HTTPS; here
    # we render them to a prompt string ourselves. One neutral template for
    # every family keeps prompt-parity tests model-independent.

    def render_chat(self, messages: Sequence[dict]) -> str:
        parts = []
        for m in messages:
            role = m.get("role", "user")
            content = m.get("content", "")
            if not isinstance(content, str):
                content = _stringify_content(content)
            parts.append(f"<|{role}|>\n{content}\n")
        parts.append("<|assistant|>\n")
        return "".join(parts)

    def encode_chat(self, messages: Sequence[dict]) -> list[int]:
        return self.encode(self.render_chat(messages), add_bos=True)


# Single multimodal-content stringifier for the whole stack: chat rendering,
# backend token counting, and TokenManager budgeting must all flatten content
# identically or their counts drift apart.
from quoracle_tpu.utils.normalize import stringify_content as _stringify_content


class ByteTokenizer(Tokenizer):
    """Byte-level reversible tokenizer: id = byte + 3 specials offset."""

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = [b + _N_SPECIALS for b in text.encode("utf-8")]
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # Ids beyond the byte range can appear when a model's vocab is larger
        # than the tokenizer's (tiny random-weight test models); skip them.
        data = bytes(i - _N_SPECIALS for i in ids
                     if _N_SPECIALS <= i < 256 + _N_SPECIALS)
        return data.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return 256 + _N_SPECIALS


class HFTokenizer(Tokenizer):
    """Binding over a HuggingFace ``tokenizers`` file (tokenizer.json)."""

    def __init__(self, path: str, bos_id: int = BOS_ID, eos_id: int = EOS_ID):
        from tokenizers import Tokenizer as _HF
        self._tok = _HF.from_file(path)
        self.bos_id = bos_id
        self.eos_id = eos_id

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


@lru_cache(maxsize=None)
def get_tokenizer(model_name: str, tokenizer_path: str | None = None) -> Tokenizer:
    """Tokenizer for a catalog model. Tiny/bench models use bytes; real
    checkpoints pass an explicit tokenizer.json path.

    bos/eos ids come from the model's catalog entry so the tokenizer and the
    engine's stop condition always agree (the engine stops on
    ``ModelConfig.eos_token_id``)."""
    from quoracle_tpu.models.config import get_model_config
    try:
        cfg = get_model_config(model_name)
        bos, eos, vocab = cfg.bos_token_id, cfg.eos_token_id, cfg.vocab_size
    except KeyError:
        bos, eos, vocab = BOS_ID, EOS_ID, 32768
    if tokenizer_path:
        return HFTokenizer(tokenizer_path, bos_id=bos, eos_id=eos)
    tok: Tokenizer
    try:
        # Learned byte-level BPE sized to the model's vocab (tiny test
        # models get the byte-only prefix). Both the C++ and the Python
        # implementation read the same committed merges artifact.
        from quoracle_tpu.native.tokenizer import NativeBPETokenizer
        import os
        from quoracle_tpu.native.tokenizer import MERGES_PATH
        if os.path.isfile(MERGES_PATH):
            tok = NativeBPETokenizer.for_vocab(vocab)
        else:
            tok = ByteTokenizer()
    except ImportError:
        tok = ByteTokenizer()
    tok.bos_id, tok.eos_id = bos, eos
    return tok
