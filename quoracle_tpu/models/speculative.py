"""Speculative decoding: a small draft model proposes K tokens, the
target model verifies them in ONE chunk forward.

No reference counterpart (the reference never executes attention,
SURVEY §2.8) — this is a TPU-first throughput feature aimed squarely at
the measured bottleneck: BASELINE.md's decode roofline shows batch-1
decode streams the member's full bf16 weights from HBM per token (~47%
of v5e peak bandwidth, compute nearly idle). Verifying K draft tokens in
one target pass reads the weights ONCE for K positions — the accepted-
token rate converts memory-bound decode steps into one compute-denser
chunk, exactly the regime the MXU wants.

Algorithm (leapfrog variant, no bonus token — keeps draft and target
caches in lockstep):

  invariant   ctx = prompt + emitted; BOTH caches hold KV for ctx[:-1];
              ``pending`` = ctx[-1], not yet forwarded by either model.
  propose     draft runs a K-step scan from ``pending``: d_1..d_K with
              per-step draft probs q_i  (draft cache advances K steps,
              through d_{K-1}).
  verify      target runs ONE chunk [pending, d_1..d_{K-1}] → logits
              p_1..p_K (p_i is the target distribution that d_i was
              proposed against; target cache advances the same K steps).
  accept      greedy rows: d_i accepted while d_i == argmax(p_i).
              sampled rows: d_i accepted with prob min(1, p_i[d_i] /
              q_i[d_i]); on rejection the correction token is drawn from
              the residual max(0, p_i - q_i) renormalized — the
              standard rejection-sampling construction, which preserves
              the target model's output distribution exactly
              (PAPERS.md speculative-decoding literature; re-derived
              here, no code reused).
  commit      j accepted → emit d_1..d_j (+ the correction token when
              j < K); roll BOTH caches back to len(ctx')-1 by shrinking
              ``lens`` (KV past lens is masked by attention, later
              writes overwrite it in place); pending' = d_K on full
              accept else the correction token.

Greedy (temperature 0) output is bit-identical to vanilla decode: every
accepted d_i equals argmax(p_i) and every correction IS argmax(p_i).
tests/test_speculative.py asserts equality against GenerateEngine.

Grammar-constrained speculation is supported (``constrain_json`` /
``action_enum``): the draft proposes under the SAME token-DFA mask the
engine decodes with (models/constrained.py) — the proposal distribution
is the masked one, so acceptance math stays exact — and the verify pass
masks p_i with the state in effect at that position (host table walk).
This is what makes speculation applicable to the production consensus
workload, which always decodes constrained action JSON.

v1 scope: batch 1, dense cache (no sessions/pages), text-only, full
attention (no sliding window). The draft and target MUST share one
tokenizer/vocab — verified at construction.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from quoracle_tpu.analysis.lockdep import named_lock
from quoracle_tpu.infra import costobs
from quoracle_tpu.infra.flightrec import FLIGHT
from quoracle_tpu.infra.telemetry import (
    SPEC_ACCEPTANCE, SPEC_ACCEPTED, SPEC_DRAFTED, SPEC_ENGAGED,
    SPEC_FALLBACK_TOTAL, SPEC_K, SPEC_ROUNDS, SPEC_TOKENS_PER_ROUND,
)
from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.generate import (
    grammar_mask, prefill, prefill_chunk,
)
from quoracle_tpu.models.sampling import sample_tokens
from quoracle_tpu.training.capture import CAPTURE, spec_example
from quoracle_tpu.models.transformer import (
    KVCache, forward_hidden, init_cache, project_logits,
)


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _row_keys(rows) -> list:
    """Chip-economics attribution keys (ISSUE 17) for scheduler
    _Row-likes — integer QoS priorities render as class names so the
    ledger shares the budget plane's vocabulary."""
    from quoracle_tpu.serving.qos import class_name
    return [(str(getattr(r, "tenant", None) or "-"),
             class_name(getattr(r, "priority", 1)),
             str(getattr(r, "task_id", None) or "-"),
             str(getattr(r, "decide", None) or "-")) for r in rows]


@dataclasses.dataclass
class SpecResult:
    token_ids: list
    text: str
    n_prompt_tokens: int
    n_gen_tokens: int
    latency_s: float
    finish_reason: str
    rounds: int                  # speculative rounds executed
    drafted: int                 # draft tokens proposed in total
    accepted: int                # draft tokens accepted in total
    n_cached_tokens: int = 0     # session-resident prefix reused

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / max(1, self.drafted)

    @property
    def tokens_per_round(self) -> float:
        return self.n_gen_tokens / max(1, self.rounds)


# Bench/baton-path decoder: compiles once at _build per (which,
# cache_len); the production continuous path ledgers through the owning
# engine's CompileRegistry instead (BatchedSpeculator + verify_chunk).
# qlint: allow[jit-unregistered] batch-1 decoder; engines own the ledger
class SpeculativeDecoder:
    """Draft/verify decoder over two models sharing one tokenizer.

    ``target_cfg``/``draft_cfg`` + params are the same structures
    GenerateEngine serves; K is the draft length per round. Construct
    once per (target, draft) pair — the three jits (two prefills, the
    draft scan, the verify chunk) compile per cache-length bucket and
    are reused across calls.
    """

    def __init__(self, target_cfg: ModelConfig, target_params: dict,
                 draft_cfg: ModelConfig, draft_params: dict,
                 tokenizer, *, k: int = 6, max_seq: int = 2048,
                 seed: int = 0, cache_dtype=None):
        assert target_cfg.vocab_size == draft_cfg.vocab_size, \
            "draft and target must share one tokenizer/vocab"
        assert target_cfg.sliding_window is None \
            and draft_cfg.sliding_window is None, \
            "speculative v1 requires full attention (no sliding window)"
        self.tc, self.tp = target_cfg, target_params
        self.dc, self.dp = draft_cfg, draft_params
        self.tokenizer = tokenizer
        self.k = int(k)
        self.max_seq = max_seq
        # match each model's params dtype like GenerateEngine does — a
        # bf16 cache under fp32 params trips lax.scatter's dtype check in
        # the KV write
        self.t_cache_dtype = (cache_dtype if cache_dtype is not None
                              else jax.tree.leaves(target_params)[0].dtype)
        self.d_cache_dtype = (cache_dtype if cache_dtype is not None
                              else jax.tree.leaves(draft_params)[0].dtype)
        self._rng = jax.random.PRNGKey(seed)
        # NOT thread-safe: sessions/caches/rng mutate per call. Callers
        # that share a decoder serialize through this lock (TPUBackend
        # try-acquires it and falls back to batched vanilla on contention)
        self.lock = named_lock("spec.decoder")
        self._build()

    # ------------------------------------------------------------------

    def _build(self) -> None:
        K = self.k

        @functools.partial(jax.jit, static_argnames=("cache_len", "which"))
        def _prefill(params, tokens, lens, cache_len: int, which: str):
            cfg = self.tc if which == "t" else self.dc
            dt = self.t_cache_dtype if which == "t" else self.d_cache_dtype
            cache = init_cache(cfg, 1, cache_len, dtype=dt)
            return prefill(params, cfg, tokens, lens, cache)

        @functools.partial(jax.jit, static_argnames=("which",))
        def _extend(params, cache: KVCache, tokens, chunk_lens,
                    which: str):
            """Session resume: forward a right-padded suffix chunk on top
            of the resident prefix (prefill_chunk at prefix = cache.lens)
            — the speculative counterpart of the engine's token-splice."""
            cfg = self.tc if which == "t" else self.dc
            _, cache = prefill_chunk(params, cfg, tokens, cache.lens,
                                     chunk_lens, cache)
            return cache

        eos_id = self.tc.eos_token_id
        # generate.grammar_mask IS the engine's mask — one implementation,
        # zero drift (the bit-exactness guarantee depends on it)
        _mask = functools.partial(grammar_mask, eos_id=eos_id)

        @functools.partial(jax.jit,
                           static_argnames=("constrained", "greedy"))
        def _draft_scan(params, cache: KVCache, pending, rng, temperature,
                        top_p, json_table, jstate0,
                        constrained: bool = False, greedy: bool = False):
            """K autoregressive draft steps from ``pending``.

            Returns (d_tokens [K], q_probs [K, V], cache'): step i
            forwards the previous token (pending for i=0), samples d_i
            from the draft distribution q_i — grammar-masked when
            ``constrained`` (the proposal distribution IS the masked one,
            so acceptance math stays exact). The cache advances K
            positions — through d_{K-1} — matching the target's verify
            chunk exactly (module docstring invariant)."""
            cfg = self.dc

            def step(carry, _):
                cache, tok, rng, jstate = carry
                pos = cache.lens[:, None]
                hidden, cache = forward_hidden(
                    params, cfg, tok[:, None], pos, cache,
                    write_offset=cache.lens, kv_lens=cache.lens + 1)
                cache = cache._replace(lens=cache.lens + 1)
                logits = project_logits(params, cfg, hidden)[:, 0, :]
                logits = logits.astype(jnp.float32)
                if constrained:
                    logits = _mask(logits, jstate, json_table)
                rng, ks = jax.random.split(rng)
                nxt = sample_tokens(logits, ks, temperature, top_p)
                if greedy:
                    # acceptance needs no proposal distribution: the host
                    # compares token ids — skip the [V] softmax entirely
                    q = jnp.zeros((1, 1), jnp.float32)
                else:
                    q = jax.nn.softmax(
                        logits / jnp.maximum(temperature, 1e-6)[:, None],
                        axis=-1)
                    # greedy rows draft greedily: q as one-hot keeps the
                    # acceptance rule exact (accept iff d_i == argmax p_i)
                    q = jnp.where(
                        (temperature <= 0)[:, None],
                        jax.nn.one_hot(nxt, logits.shape[-1]), q)
                if constrained:
                    jstate = jnp.where(
                        jstate >= 0,
                        json_table[jnp.clip(jstate, 0, None),
                                   nxt].astype(jnp.int32), jstate)
                return (cache, nxt, rng, jstate), (nxt[0], q[0])

            (cache, _, rng, _), (toks, qs) = jax.lax.scan(
                step, (cache, pending, rng, jstate0), None, length=K)
            return toks, qs, cache

        @functools.partial(jax.jit,
                           static_argnames=("constrained", "greedy"))
        def _verify_chunk(params, cache: KVCache, chunk, temperature,
                          json_table, jstate0, constrained: bool = False,
                          greedy: bool = False):
            """One target pass over [pending, d_1..d_{K-1}] → p_1..p_K
            (full per-position distributions) with the cache advanced K
            positions. Under constraint the per-position grammar states
            are walked IN-DEVICE from ``jstate0`` over the draft tokens
            (chunk[1:]) — no host sync sits between the draft scan and
            this dispatch — and the mask applied to p_i equals the one
            the vanilla engine would apply at that position."""
            cfg = self.tc
            T = K
            lens0 = cache.lens
            positions = (lens0[:, None]
                         + jnp.arange(T, dtype=jnp.int32)[None, :])
            hidden, cache = forward_hidden(
                params, cfg, chunk[None, :], positions, cache,
                write_offset=lens0, kv_lens=lens0 + T)
            cache = cache._replace(lens=lens0 + T)
            logits = project_logits(params, cfg, hidden)[0].astype(
                jnp.float32)                                     # [K, V]
            if constrained:
                def adv(s, tok):
                    nxt = json_table[jnp.clip(s, 0, None),
                                     tok].astype(jnp.int32)
                    s2 = jnp.where(s >= 0, nxt, s)
                    return s2, s2
                _, rest = jax.lax.scan(adv, jstate0[0], chunk[1:])
                jstates = jnp.concatenate([jstate0, rest])       # [K]
                logits = _mask(logits, jstates, json_table)
            argmax_ids = jnp.argmax(logits, axis=-1)         # [K]
            if greedy:
                # the [K, V] probs would be a dead jit output the compiler
                # must still write to HBM — drop it in the hot greedy path
                probs = jnp.zeros((1, 1), jnp.float32)
            else:
                probs = jax.nn.softmax(
                    logits / jnp.maximum(temperature, 1e-6)[:, None],
                    axis=-1)
                greedy_probs = jax.nn.one_hot(argmax_ids,
                                              logits.shape[-1])
                probs = jnp.where((temperature <= 0)[:, None],
                                  greedy_probs, probs)
            return probs, argmax_ids, cache

        self._prefill = _prefill
        self._extend = _extend
        self._draft_scan = _draft_scan
        self._verify_chunk = _verify_chunk
        self._sessions: dict = {}

    def _grammar(self, action_enum) -> tuple:
        """(numpy table, start_state, device table) per enum, cached. One
        DFA serves both models — they share the tokenizer by contract.
        Key is normalized (sorted, deduped — CharDFA normalizes the enum
        internally, so permutations build byte-identical tables) and the
        cache is BOUNDED: device tables are states × vocab int16, tens of
        MB at large vocabs, and varied capability sets must not
        accumulate until HBM OOM (same rationale as the engine's
        _json_table_device eviction)."""
        if not hasattr(self, "_grammar_cache"):
            self._grammar_cache = {}
        key = tuple(sorted(set(action_enum))) if action_enum else None
        if key not in self._grammar_cache:
            from quoracle_tpu.models.constrained import JsonTokenTable
            tt = JsonTokenTable.for_tokenizer(
                self.tokenizer, self.tc.vocab_size, self.tc.eos_token_id,
                extra_stop_ids=tuple(self.tc.stop_token_ids),
                action_enum=list(action_enum) if action_enum else None)
            for old in list(self._grammar_cache)[:max(
                    0, len(self._grammar_cache) - 3)]:
                del self._grammar_cache[old]     # keep newest 3 + this
            self._grammar_cache[key] = (tt.table, tt.start_state,
                                        jnp.asarray(tt.table))
        return self._grammar_cache[key]

    def next_rng(self) -> jax.Array:
        self._rng, k = jax.random.split(self._rng)
        return k

    # ------------------------------------------------------------------

    def drop_session(self, session_id: str) -> None:
        with self.lock:
            self._sessions.pop(session_id, None)

    def session_tokens(self, session_id: str) -> Optional[list]:
        """The session's resident conversation ids, or None — mirrors
        GenerateEngine.session_tokens EXACTLY so callers splice the next
        round's prompt identically against whichever store holds the
        session. Engine parity detail: on a "length" finish the final
        emitted token was sampled but never forwarded (no KV), and the
        engine's store-back retains only KV-valid ids — so the view
        drops ctx's trailing pending token for length-finished sessions
        (a "stop" finish already popped its unforwarded terminal).
        Splicing from a different id set than the engine would let the
        next prompt's BPE merge differently and silently fork temp-0
        bits between the speculative and vanilla paths."""
        with self.lock:
            s = self._sessions.get(session_id)
            if s is None:
                return None
            ctx = s["ctx"]
            return list(ctx[:-1] if s.get("finish") == "length" else ctx)

    def generate(self, prompt, *, max_new_tokens: int = 128,
                 temperature: float = 0.0, top_p: float = 1.0,
                 constrain_json: bool = False,
                 action_enum=None,
                 session_id: Optional[str] = None,
                 rng: Optional[jax.Array] = None) -> SpecResult:
        t0 = time.monotonic()
        K = self.k
        prompt = list(prompt)
        assert prompt, "empty prompt"
        assert len(prompt) + max_new_tokens < self.max_seq, \
            f"prompt {len(prompt)} + max_new {max_new_tokens} >= " \
            f"max_seq {self.max_seq}"
        assert temperature <= 0 or top_p >= 1.0, \
            ("speculative v1 supports top_p only in greedy mode: the "
             "acceptance test needs q to be the ACTUAL proposal "
             "distribution, and the nucleus mask is not applied to q")
        rng = rng if rng is not None else self.next_rng()
        rng_np = np.random.default_rng(int(jax.random.bits(rng) & 0x7fffffff))
        temp = jnp.asarray([float(temperature)], jnp.float32)
        topp = jnp.asarray([float(top_p)], jnp.float32)
        if constrain_json:
            tbl_np, start_state, tbl_dev = self._grammar(action_enum)
            jstate = start_state
        else:
            tbl_np, jstate = None, -1
            tbl_dev = jnp.zeros((1, self.tc.vocab_size), jnp.int16)

        # --- cache resolution: session resume or fresh prefill ----------
        # Session resume (speculative counterpart of the engine's token
        # splice): caches hold ctx[:-1] of the PRIOR call's prompt +
        # response; a new prompt that cleanly extends ctx forwards only
        # the suffix — a refinement round re-prefills template glue, not
        # the conversation — then decode speculates as usual.
        n_cached = 0
        sess = self._sessions.get(session_id) if session_id else None
        need = len(prompt) + max_new_tokens + K + 1
        if sess is not None:
            ctx = sess["ctx"]
            lcp = 0
            for a, b in zip(ctx, prompt):
                if a != b:
                    break
                lcp += 1
            suffix = prompt[len(ctx) - 1:-1]
            # dynamic_update_slice CLAMPS out-of-range starts — an
            # overrunning chunk would silently shift left over valid
            # prefix KV, so BOTH the decode chunks (need, which includes
            # K+1) and the 64-padded extend chunk must provably fit
            fits = (need <= sess["cache_len"]
                    and (len(ctx) - 1 + _round_up(max(1, len(suffix)), 64)
                         <= sess["cache_len"]))
            if lcp == len(ctx) and len(prompt) >= len(ctx) and fits:
                tcache, dcache = sess["t"], sess["d"]
                n_cached = len(ctx)
                # forward ctx[-1] .. prompt[-2] so caches hold prompt[:-1]
                if suffix:
                    pad = _round_up(len(suffix), 64)
                    sf = np.zeros((1, pad), np.int32)
                    sf[0, :len(suffix)] = suffix
                    cl = jnp.asarray([len(suffix)], jnp.int32)
                    tcache = self._extend(self.tp, tcache,
                                          jnp.asarray(sf), cl, "t")
                    dcache = self._extend(self.dp, dcache,
                                          jnp.asarray(sf), cl, "d")
            else:
                sess = None                      # diverged or outgrown
                self._sessions.pop(session_id, None)
        if sess is None:
            # session caches carry decode slack (K+1) plus the extend
            # pad overhang (63) ABOVE max_seq — see the clamp note above
            cache_len = (_round_up(self.max_seq + K + 64, 128)
                         if session_id else _round_up(need, 128))
            pad = _round_up(len(prompt), 64)
            toks = np.zeros((1, pad), np.int32)
            toks[0, :len(prompt)] = prompt
            lens = jnp.asarray([len(prompt)], jnp.int32)
            # Both caches prefill ctx[:-1] = prompt minus its last token,
            # so the invariant (pending un-forwarded) holds from the
            # start. Prefill with full prompt length then roll lens back
            # one: the last column's KV is simply overwritten by the
            # first chunk.
            _, tcache = self._prefill(self.tp, jnp.asarray(toks), lens,
                                      cache_len, "t")
            _, dcache = self._prefill(self.dp, jnp.asarray(toks), lens,
                                      cache_len, "d")
            tcache = tcache._replace(lens=lens - 1)
            dcache = dcache._replace(lens=lens - 1)
        else:
            cache_len = sess["cache_len"]
        pending = jnp.asarray([prompt[-1]], jnp.int32)

        stops = {self.tc.eos_token_id, *self.tc.stop_token_ids}
        emitted: list[int] = []
        rounds = drafted = accepted_total = 0
        finish = "length"
        def host_advance(s: int, tok: int) -> int:
            if not constrain_json or s < 0:
                return s
            return int(tbl_np[s, tok])

        while len(emitted) < max_new_tokens:
            rounds += 1
            rng, kd = jax.random.split(rng)
            jstate0 = jnp.asarray([jstate], jnp.int32)
            d_toks, q_probs, dcache = self._draft_scan(
                self.dp, dcache, pending, kd, temp, topp,
                tbl_dev, jstate0, constrained=constrain_json,
                greedy=temperature <= 0)
            chunk = jnp.concatenate([pending, d_toks[:-1]])
            # verify dispatches on DEVICE values only (the per-position
            # grammar states walk in-device from jstate0) — no host sync
            # sits between the draft scan and the target chunk
            p_probs, p_am, tcache = self._verify_chunk(
                self.tp, tcache, chunk, jnp.broadcast_to(temp, (K,)),
                tbl_dev, jstate0, constrained=constrain_json,
                greedy=temperature <= 0)
            d = np.asarray(d_toks)
            if temperature <= 0:
                # greedy needs only the [K] argmax ids — accepted drafts
                # equal them and corrections ARE them. The [K, V] prob
                # tensors never materialize host-side (at 128k vocab
                # that's megabytes per round through the dispatch
                # channel).
                pam = np.asarray(p_am)
                q = p = None
            else:
                q = np.asarray(q_probs)
                p = np.asarray(p_probs)
                pam = None
            drafted += K

            j = 0
            correction: Optional[int] = None
            while j < K:
                di = int(d[j])
                if temperature <= 0:
                    ok = di == int(pam[j])
                else:
                    ok = rng_np.random() < min(
                        1.0, float(p[j, di]) / max(float(q[j, di]), 1e-20))
                if not ok:
                    if temperature <= 0:
                        correction = int(pam[j])
                    else:
                        residual = np.maximum(p[j] - q[j], 0.0)
                        tot = residual.sum()
                        correction = (int(np.argmax(p[j])) if tot <= 0
                                      else int(rng_np.choice(
                                          residual.shape[0],
                                          p=residual / tot)))
                    break
                j += 1
            accepted_total += j

            new_tokens = [int(x) for x in d[:j]]
            if correction is not None:
                new_tokens.append(correction)
            # commit: truncate at stop/max_new, roll caches to ctx'[:-1].
            # The budget cut applies FIRST — a stop token that lands just
            # past max_new is cut away and must report "length", exactly
            # as vanilla decode's row_limit would (engine parity).
            cut = len(new_tokens)
            stop_at = None
            for idx, t in enumerate(new_tokens):
                if t in stops:
                    stop_at = idx
                    cut = idx + 1
                    break
            room = max_new_tokens - len(emitted)
            cut = min(cut, room)
            if stop_at is not None and stop_at < cut:
                finish = "stop"
            new_tokens = new_tokens[:cut]
            emitted.extend(new_tokens)
            for t in new_tokens:
                jstate = host_advance(jstate, t)
            if finish == "stop" or len(emitted) >= max_new_tokens:
                break
            # lens' = len(ctx') - 1; ctx' grew by len(new_tokens)
            ctx_len = len(prompt) + len(emitted)
            new_lens = jnp.asarray([ctx_len - 1], jnp.int32)
            tcache = tcache._replace(lens=new_lens)
            dcache = dcache._replace(lens=new_lens)
            pending = jnp.asarray([emitted[-1]], jnp.int32)

        # engine parity: the terminal stop token is popped from the output
        # (generate.py result assembly does the same)
        if emitted and emitted[-1] in stops:
            emitted.pop()
            finish = "stop"
        if session_id and emitted:
            # store at the invariant: caches hold ctx'[:-1]. Committed
            # tokens' KV is valid through ctx'-2 (a trailing correction's
            # position is excluded by the -1; rejected drafts past it are
            # masked and later overwritten in place).
            ctx_out = prompt + emitted
            norm = jnp.asarray([len(ctx_out) - 1], jnp.int32)
            # LRU, not FIFO: pop-then-reinsert moves a re-stored session
            # to the end, so the hot session is never the eviction victim
            self._sessions.pop(session_id, None)
            for old in list(self._sessions)[:max(
                    0, len(self._sessions) - 7)]:
                self._sessions.pop(old)          # bound: newest 7 + this
            self._sessions[session_id] = {
                "t": tcache._replace(lens=norm),
                "d": dcache._replace(lens=norm),
                "ctx": ctx_out, "cache_len": cache_len,
                "finish": finish,
            }
        return SpecResult(
            token_ids=emitted,
            text=self.tokenizer.decode(emitted),
            n_prompt_tokens=len(prompt),
            n_gen_tokens=len(emitted),
            latency_s=time.monotonic() - t0,
            finish_reason=finish,
            rounds=rounds,
            drafted=drafted,
            accepted=accepted_total,
            n_cached_tokens=n_cached,
        )


# ---------------------------------------------------------------------------
# Batched speculation for the CONTINUOUS serving path (ISSUE 6 tentpole)
# ---------------------------------------------------------------------------


class BatchedSpeculator:
    """Draft/verify decoding over the ContinuousBatcher's live slots.

    Where :class:`SpeculativeDecoder` (v1) owns a private batch-1 dense
    cache, this operates entirely on the two engines' PAGED SESSION
    stores — the same KV the vanilla continuous path uses — so rows can
    mix speculative and vanilla ticks freely and nothing is resident
    twice:

      propose   ``draft.generate`` over every eligible slot's context in
                ONE batched call (greedy, grammar-masked — the draft's own
                sessions track ctx, so each round forwards one suffix
                token + K draft steps);
      verify    ``target.verify_chunk`` — ONE teacher-forced chunk
                forward per round across all rows against the target's
                paged session KV, returning per-position grammar-masked
                argmax (greedy rows) and masked softmax probs (sampled
                rows);
      commit    host-side accept/rollback per row. Rollback is FREE: both
                engines resume sessions by longest-common-prefix, so a
                rejected draft's stale KV is simply overwritten by the
                next round's suffix prefill.

    Acceptance math: greedy rows accept d_i iff d_i == argmax(p_i) —
    temp-0 output is bit-identical to vanilla decode. Sampled rows
    (top_p == 1 only) draft GREEDILY, i.e. a deterministic one-hot
    proposal distribution: accept d_i with prob p_i[d_i], else draw the
    correction from p_i with d_i's mass removed, renormalized — the
    standard rejection-sampling construction with q = δ(d_i), which
    preserves the target distribution exactly without shipping draft
    probs to the host.

    ADAPTIVE K (per member): a rolling EWMA of per-round acceptance
    shrinks K toward ``k_min`` when acceptance sags below
    ``shrink_below``, grows it back toward ``k_max`` above
    ``grow_above``, and DISENGAGES to vanilla decode entirely below
    ``accept_floor`` — after ``reprobe_after`` vanilla ticks the member
    re-probes at ``k_min``. All transitions are flight-recorded and the
    current state exports as quoracle_spec_* gauges.

    Not thread-safe for ``run_round`` (the batcher's single worker thread
    owns it); ``stats()``/eligibility reads are lock-guarded snapshots.
    """

    def __init__(self, target_engine, draft_engine, *, k: int = 6,
                 k_min: int = 2, k_max: int = 8,
                 accept_floor: float = 0.35, shrink_below: float = 0.6,
                 grow_above: float = 0.85, ewma_alpha: float = 0.15,
                 reprobe_after: int = 24, seed: int = 0):
        assert target_engine.cfg.vocab_size == draft_engine.cfg.vocab_size, \
            "draft and target must share one tokenizer/vocab"
        assert target_engine.cfg.sliding_window is None \
            and draft_engine.cfg.sliding_window is None, \
            "speculative serving requires full attention (no sliding window)"
        self.target = target_engine
        self.draft = draft_engine
        self.model = target_engine.cfg.name
        self.k_init = max(1, int(k))
        self.k_min = max(1, min(int(k_min), self.k_init))
        self.k_max = max(self.k_init, int(k_max))
        self.accept_floor = float(accept_floor)
        self.shrink_below = float(shrink_below)
        self.grow_above = float(grow_above)
        self.ewma_alpha = float(ewma_alpha)
        self.reprobe_after = int(reprobe_after)
        self._rng_np = np.random.default_rng(seed)
        self._lock = named_lock("spec.adaptive")
        self._k = self.k_init
        self._engaged = True
        self._ewma: Optional[float] = None
        self._vanilla_ticks = 0            # ticks since disengage
        self._rounds_since_probe = 0       # evidence behind the EWMA
        self._stops = {target_engine.cfg.eos_token_id,
                       *target_engine.cfg.stop_token_ids}
        # cumulative counters (stats() snapshot)
        self.rounds = 0
        self.drafted = 0
        self.accepted = 0
        self.emitted = 0
        self.disengages = 0
        self.reprobes = 0
        self.fallbacks: dict = {}
        self._tables: dict = {}            # enum key -> (np table, start)
        SPEC_K.set(self._k, model=self.model)
        SPEC_ENGAGED.set(1.0, model=self.model)

    # -- eligibility ----------------------------------------------------

    @property
    def k(self) -> int:
        return self._k

    @property
    def engaged(self) -> bool:
        return self._engaged

    def ineligible_reason(self, ctx_len: int, temperature: float,
                          top_p: float) -> Optional[str]:
        """None when a row with this shape may speculate this tick;
        otherwise the fallback reason (exported per-tick by the
        scheduler via note_fallback)."""
        if not self._engaged:
            return "disengaged"
        if temperature > 0 and top_p < 1.0:
            # the acceptance test needs the ACTUAL proposal/target
            # distributions; the nucleus mask is not applied to either
            return "sampling"
        if (ctx_len + self._k + 1 >= self.target.max_seq
                or ctx_len + self._k + 1 >= self.draft.max_seq):
            # overflow-near-window: the verify prompt (ctx + K - 1) and
            # the draft's decode slack must both fit — rows this close to
            # the window decode vanilla and retire at the edge
            return "window"
        return None

    def note_fallback(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + n
        SPEC_FALLBACK_TOTAL.inc(n, model=self.model, reason=reason)

    def tick_vanilla(self) -> None:
        """Count one disengaged tick; re-probe after ``reprobe_after``."""
        with self._lock:
            if self._engaged:
                return
            self._vanilla_ticks += 1
            if self._vanilla_ticks < self.reprobe_after:
                return
            self._engaged = True
            self._k = self.k_min
            self._ewma = None              # fresh measurement window
            self._rounds_since_probe = 0
            self.reprobes += 1
        SPEC_ENGAGED.set(1.0, model=self.model)
        SPEC_K.set(self._k, model=self.model)
        FLIGHT.record("spec_reprobe", model=self.model, k=self.k_min)

    def drop_session(self, session_id: str) -> None:
        """Release the DRAFT engine's session for a retired row (the
        target session is dropped by the scheduler/engine as usual)."""
        self.draft.drop_session(session_id)

    def swap_draft(self, new_engine):
        """Hot-swap the draft engine (ISSUE 19 promotion path) and
        return the incumbent for instant rollback.

        Safe mid-serving because draft KV is DERIVED state: the new
        engine simply has no sessions yet, so each row's next round
        cold-prefills its context into the new draft — exactly the
        longest-common-prefix resume path a rejected chunk already
        takes. Adaptive state resets to a fresh measurement window
        (k_init, no EWMA) so the incumbent's acceptance history cannot
        disengage — or shield — the candidate."""
        assert new_engine.cfg.vocab_size == self.target.cfg.vocab_size, \
            "draft and target must share one tokenizer/vocab"
        assert new_engine.cfg.sliding_window is None, \
            "speculative serving requires full attention"
        with self._lock:
            old = self.draft
            self.draft = new_engine
            self._k = self.k_init
            self._engaged = True
            self._ewma = None
            self._vanilla_ticks = 0
            self._rounds_since_probe = 0
            self._tables = {}
        SPEC_K.set(self._k, model=self.model)
        SPEC_ENGAGED.set(1.0, model=self.model)
        return old

    # -- the round ------------------------------------------------------

    def _host_table(self, action_enum) -> tuple:
        """(np transition table, start_state) for host-side grammar
        walks, sourced from the TARGET engine's own table cache so the
        mask/table can never drift from what the device applied."""
        key = tuple(sorted(set(action_enum))) if action_enum else None
        hit = self._tables.get(key)
        if hit is None:
            self.target._json_table_device((key,))     # ensure built
            tt = self.target._json_cache[("one", key)]
            for old in list(self._tables)[:max(0, len(self._tables) - 7)]:
                del self._tables[old]                   # keep newest 7 +1
            hit = self._tables[key] = (tt.table, tt.start_state)
        return hit

    def run_round(self, rows) -> dict:
        """One draft/verify round over ``rows`` (scheduler _Row-likes:
        .prompt/.emitted/.temperature/.top_p/.max_new/.session_id/
        .constrain/.action_enum/.json_state/.spec_* fields). Mutates each
        row's emitted/json_state/spec counters in place and returns
        {id(row): "stop" | None} — "stop" rows hit a stop token and must
        retire. Raises on engine failure (the scheduler falls back to
        vanilla for the tick)."""
        K = self._k
        eos = self.draft.cfg.eos_token_id
        ctxs = [list(r.prompt) + list(r.emitted) for r in rows]
        k_req = [max(1, min(K, r.max_new - len(r.emitted))) for r in rows]
        # chip-economics attribution (ISSUE 17): the scheduler's active
        # set shrinks between rounds, so keys are re-declared per engine
        # call, not per tick — one declaration covers exactly one call
        costobs.set_row_keys(_row_keys(rows))
        drafts = self.draft.generate(
            ctxs, temperature=0.0, top_p=1.0, max_new_tokens=k_req,
            session_ids=[r.session_id for r in rows],
            constrain_json=[bool(r.constrain) for r in rows],
            action_enums=[r.action_enum for r in rows],
            initial_json_state=[r.json_state for r in rows])
        proposals = []
        for r, g, kq in zip(rows, drafts, k_req):
            r.chip_ms = getattr(r, "chip_ms", 0.0) + g.chip_ms
            p = list(g.token_ids)
            if g.finish_reason == "stop" and len(p) < kq:
                # the engine pops the terminal stop id; re-propose A stop
                # (eos) — if the target wants a different stop id the
                # verify correction supplies it
                p.append(eos)
            proposals.append(p or [eos])
        need_probs = any(r.temperature > 0 for r in rows)
        costobs.set_row_keys(_row_keys(rows))
        vres = self.target.verify_chunk(
            [c + p[:-1] for c, p in zip(ctxs, proposals)],
            [r.session_id for r in rows],
            [len(p) for p in proposals],
            temperature=[r.temperature for r in rows],
            constrain_json=[bool(r.constrain) for r in rows],
            action_enums=[r.action_enum for r in rows],
            initial_json_state=[r.json_state for r in rows],
            need_probs=need_probs)

        finishes: dict = {}
        drafted = accepted = committed_total = 0
        # serving flywheel intake (ISSUE 19): when the capture plane is
        # live, copy each row's (ctx, proposal, verdicts, correction)
        # AFTER the commit math below — pure reads of values the round
        # computed anyway, so temp-0 bits are identical on or off
        cap_rows: Optional[list] = [] if CAPTURE.active else None
        for r, ctx, props, v in zip(rows, ctxs, proposals, vres):
            ids, probs = v["ids"], v["probs"]
            r.chip_ms = getattr(r, "chip_ms", 0.0) + v.get("chip_ms", 0.0)
            if r.n_cached_first is None:
                r.n_cached_first = v["n_cached"]
            j = 0
            correction: Optional[int] = None
            greedy = r.temperature <= 0
            for t, d in enumerate(props):
                if greedy:
                    ok = d == ids[t]
                else:
                    # one-hot proposal: accept with prob p_t[d]
                    ok = self._rng_np.random() < float(probs[t, d])
                if not ok:
                    if greedy:
                        correction = int(ids[t])
                    else:
                        resid = np.asarray(probs[t], np.float64).copy()
                        resid[d] = 0.0
                        z = resid.sum()
                        correction = (int(ids[t]) if z <= 0 else
                                      int(self._rng_np.choice(
                                          resid.shape[0], p=resid / z)))
                    break
                j += 1
            drafted += len(props)
            accepted += j
            new_tokens = props[:j]
            if correction is not None:
                new_tokens = new_tokens + [correction]
            # stop/budget cut — v1 commit semantics: the budget cut
            # applies FIRST, so a stop landing past max_new reports
            # "length" exactly as vanilla row_limit would
            cut = len(new_tokens)
            stop_at = None
            for idx, t in enumerate(new_tokens):
                if t in self._stops:
                    stop_at = idx
                    cut = idx + 1
                    break
            room = r.max_new - len(r.emitted)
            cut = min(cut, room)
            finish = None
            if stop_at is not None and stop_at < cut:
                finish = "stop"
            out_tokens = new_tokens[:cut]
            if finish == "stop":
                out_tokens = out_tokens[:-1]   # engine parity: stop popped
            r.emitted.extend(out_tokens)
            committed_total += len(out_tokens)
            r.spec_rounds += 1
            r.spec_drafted += len(props)
            r.spec_accepted += j
            if r.constrain and out_tokens:
                table, start = self._host_table(r.action_enum)
                s = r.json_state if (r.json_state is not None
                                     and r.json_state >= 0) else start
                for t in out_tokens:
                    if s >= 0:
                        s = int(table[s, t])
                r.json_state = s
            finishes[id(r)] = finish
            if cap_rows is not None:
                cap_rows.append(spec_example(
                    ctx, props, [int(x) for x in ids[:len(props)]],
                    j, correction, r.temperature, r.constrain,
                    r.action_enum))

        with self._lock:
            self.rounds += 1
            self.drafted += drafted
            self.accepted += accepted
            self.emitted += committed_total
            rate = accepted / max(1, drafted)
            self._ewma = (rate if self._ewma is None else
                          self.ewma_alpha * rate
                          + (1 - self.ewma_alpha) * self._ewma)
            self._rounds_since_probe += 1
            changed = self._adapt_locked()
        SPEC_ROUNDS.inc(model=self.model)
        SPEC_DRAFTED.inc(drafted, model=self.model)
        SPEC_ACCEPTED.inc(accepted, model=self.model)
        SPEC_ACCEPTANCE.observe(rate, model=self.model)
        SPEC_TOKENS_PER_ROUND.observe(committed_total / max(1, len(rows)),
                                      model=self.model)
        if changed:
            SPEC_K.set(self._k, model=self.model)
            SPEC_ENGAGED.set(1.0 if self._engaged else 0.0,
                             model=self.model)
        if cap_rows:
            # outside every lock; the plane absorbs all failures
            CAPTURE.observe_spec_round(self.model, self.draft.cfg.name,
                                       cap_rows)
        return finishes

    def _adapt_locked(self) -> bool:
        """Adaptive-K state machine (caller holds the lock). Returns True
        when K or engagement changed."""
        ewma = self._ewma
        if ewma is None:
            return False
        if ewma < self.accept_floor and self._rounds_since_probe >= 3:
            # acceptance collapse — speculation now COSTS latency (every
            # round pays draft + verify for ~1 token). Disengage; the
            # scheduler's vanilla ticks count toward the re-probe.
            self._engaged = False
            self._vanilla_ticks = 0
            self._ewma = None
            self.disengages += 1
            k_was, self._k = self._k, self.k_init
            FLIGHT.record("spec_disengage", model=self.model,
                          ewma=round(ewma, 3), k=k_was)
            return True
        if ewma < self.shrink_below and self._k > self.k_min:
            self._k -= 1
            return True
        if ewma > self.grow_above and self._k < self.k_max:
            self._k += 1
            return True
        return False

    # -- observability --------------------------------------------------

    def stats(self) -> dict:
        """Point-in-time snapshot for /api/models + the scorecards."""
        with self._lock:
            return {
                "mode": "continuous",
                "draft": self.draft.cfg.name,
                "engaged": self._engaged,
                "k": self._k,
                "k_init": self.k_init,
                "acceptance_ewma": (round(self._ewma, 4)
                                    if self._ewma is not None else None),
                "rounds": self.rounds,
                "drafted_tokens": self.drafted,
                "accepted_tokens": self.accepted,
                "emitted_tokens": self.emitted,
                "acceptance_rate": (round(self.accepted
                                          / max(1, self.drafted), 4)
                                    if self.drafted else None),
                "tokens_per_round": (round(self.emitted
                                           / max(1, self.rounds), 2)
                                     if self.rounds else None),
                "disengages": self.disengages,
                "reprobes": self.reprobes,
                "fallbacks": dict(self.fallbacks),
            }
