"""Radix prefix cache: ref-counted, copy-on-write KV page sharing across
sessions (vLLM automatic-prefix-caching / SGLang RadixAttention analog,
re-derived for the paged SessionStore in models/generate.py).

Every consensus round fans the same built prompt out to K rows, and every
child agent inherits most of its parent's system/task preamble — so the
same page-aligned token blocks get prefilled over and over. This module
maps token prefixes to the pages that already hold their KV:

  * a RADIX TREE over PAGE-ALIGNED token blocks: each node is exactly one
    page of the device pool, its edge labeled with that page's ``page``
    token ids; a root-to-node path spells a cached token prefix whose KV
    is resident in the path's pages;
  * the tree holds its OWN REFERENCE on every node's page (the store's
    refcount dict), so cached prefixes survive the death of the session
    that prefilled them — the old donor-scan sharing only worked while
    the donor stayed resident;
  * LRU EVICTION strips unreferenced leaves (pages whose ONLY remaining
    reference is the tree's) when the pool runs dry — shared live pages
    are never evicted, and eviction is leaf-first so an evicted node can
    never orphan cached descendants;
  * COPY-ON-WRITE is enforced at the write site (generate._run_paged):
    a session about to rewrite a shared page beyond its identical-prefix
    region — including the partially-filled boundary page it is
    extending — swaps in a fresh page and leaves the shared copy (and
    therefore every tree/adopter reader) untouched. The engine reports
    those swaps here (``note_cow``) so the counter sits with the rest of
    the cache telemetry.

Invariants (asserted by tests/test_prefix_cache.py):
  I1  a page is freed only when its refcount reaches zero — never while a
      session, an in-flight batch, or the tree still references it;
  I2  tree page content is immutable: writers either rewrite a shared page
      byte-identically (the gather scatter inside the identical-prefix
      region) or COW-swap it — a cached block's KV never changes under a
      reader;
  I3  sessions hold contiguous root-path references, so iterative
      unreferenced-LEAF eviction reaches exactly the reclaimable nodes.

Locking: all mutating/inspecting methods assume the owning SessionStore's
RLock is held (the store re-enters it freely); the store's public wrappers
(`match_prefix`, `insert_prefix`, `alloc`) take it.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional, Sequence


class _Node:
    """One cached page: edge label ``block`` (page-length token tuple,
    relative to the parent path), pool page id, LRU stamp."""

    __slots__ = ("block", "page", "children", "parent", "last_used")

    def __init__(self, block: tuple, page: int, parent: "Optional[_Node]"):
        self.block = block
        self.page = page
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.last_used = time.monotonic()


class RadixPrefixCache:
    """Radix tree over page-aligned KV blocks of one SessionStore's pool."""

    def __init__(self, store):
        self.store = store
        self.page = store.page
        self._root = _Node((), 0, None)      # sentinel; page 0 is scratch
        self._pages: dict[int, _Node] = {}   # page id -> its node
        # counters (monotonic; exposed via stats() -> web API + bench)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0
        self.cow_copies = 0

    # -- lookup ------------------------------------------------------------

    def _walk(self, tokens: Sequence[int], max_reuse: int) -> list[_Node]:
        """The node path for the longest cached page-aligned prefix of
        ``tokens``, bounded by ``max_reuse`` (callers pass len-1 so >= 1
        suffix token always re-runs to produce last-position logits)."""
        page = self.page
        node = self._root
        path: list[_Node] = []
        n_blocks = min(len(tokens), max_reuse) // page
        for j in range(n_blocks):
            block = tuple(tokens[j * page:(j + 1) * page])
            child = node.children.get(block)
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def match(self, tokens: Sequence[int],
              max_reuse: int) -> tuple[list[int], int]:
        """Longest cached page-aligned prefix: (pages, n_tokens). Bumps the
        path's LRU stamps and the hit/miss counters — call once per real
        lookup (the wave planner probes via match_len instead)."""
        path = self._walk(tokens, max_reuse)
        now = time.monotonic()
        for node in path:
            node.last_used = now
        matched = len(path) * self.page
        if path:
            self.hits += 1
            self.hit_tokens += matched
        else:
            self.misses += 1
            self.miss_tokens += len(tokens)
        return [n.page for n in path], matched

    def match_len(self, tokens: Sequence[int], max_reuse: int) -> int:
        """Counter-free probe (intra-batch wave planning)."""
        return len(self._walk(tokens, max_reuse)) * self.page

    # -- insert ------------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Record a prefilled prefix: every FULL page of ``tokens`` whose
        block is not yet cached gets a node holding ``pages[j]`` and a tree
        reference on it. Blocks already cached keep their existing node
        (dedupe — the caller's duplicate page stays the session's own).
        Returns the number of new nodes."""
        page = self.page
        node = self._root
        added = 0
        for j in range(len(tokens) // page):
            pg = pages[j] if j < len(pages) else None
            if pg is None:
                break
            block = tuple(tokens[j * page:(j + 1) * page])
            child = node.children.get(block)
            if child is None:
                if pg in self._pages or pg == 0:
                    break      # page already cached under another path
                child = _Node(block, pg, node)
                node.children[block] = child
                self._pages[pg] = child
                # the tree's own reference: absent refcount key == 1
                self.store._refs[pg] = self.store._refs.get(pg, 1) + 1
                added += 1
            child.last_used = time.monotonic()
            node = child
        self.inserted_pages += added
        return added

    # -- eviction ----------------------------------------------------------

    def _evictable_leaf(self) -> Optional[_Node]:
        """LRU leaf whose page's ONLY remaining reference is the tree's.
        Refcount semantics (store._refs, absent key == 1): the count is the
        number of current holders — the allocating session's base ref, one
        per adopter acquire, one for the tree. A session dropping its pages
        decrements normally, so a page cached here but referenced by nobody
        else sits at exactly 1."""
        best: Optional[_Node] = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is self._root or node.children:
                continue
            if self.store._refs.get(node.page, 1) != 1:
                continue       # a session/adopter still reads it
            if best is None or node.last_used < best.last_used:
                best = node
        return best

    def _remove(self, node: _Node) -> None:
        del node.parent.children[node.block]
        self._pages.pop(node.page, None)

    def _node_tokens(self, node: _Node) -> list:
        """The full token prefix a node's page caches (root-path blocks
        concatenated) — the tier's content-addressed key."""
        blocks = []
        while node is not self._root:
            blocks.append(node.block)
            node = node.parent
        out: list = []
        for b in reversed(blocks):
            out.extend(b)
        return out

    def evict(self, n: int) -> int:
        """Free up to ``n`` pages by stripping unreferenced LRU leaves.
        Returns pages actually freed to the store's free list. With a
        tier attached (serving/kvtier.py), a stripped leaf's block is
        CAPTURED host-side first — eviction demotes instead of
        destroying, and a later lookup pages the block back in."""
        freed = 0
        while freed < n:
            leaf = self._evictable_leaf()
            if leaf is None:
                break
            if self.store.tier is not None:
                self.store.tier.capture_leaf(self._node_tokens(leaf),
                                             leaf.page)
            self._remove(leaf)
            self.store._release([leaf.page])   # last ref -> free list
            freed += 1
            self.evicted_pages += 1
        return freed

    def clear(self) -> int:
        """Drop every node, releasing the tree's references (pages still
        held by sessions survive with refcount decremented)."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.store._release([node.page])
            dropped += 1
        self._root.children.clear()
        self._pages.clear()
        return dropped

    # -- alloc accounting --------------------------------------------------

    def holds(self, page: int) -> bool:
        return page in self._pages

    def evictable_after(self, released: Counter) -> int:
        """How many tree pages would FREE if ``released`` (page -> count of
        references victim sessions would give up) were applied and the tree
        then stripped leaves bottom-up. Exact simulation for
        SessionStore.alloc's attainability check: a node frees iff its
        whole subtree frees and no reference beyond the tree's survives."""
        def strippable(node: _Node) -> tuple[bool, int]:
            count = 0
            all_ok = True
            for child in node.children.values():
                ok, c = strippable(child)
                count += c
                all_ok = all_ok and ok
            if node is self._root:
                return True, count
            remaining = self.store._refs.get(node.page, 1) \
                - released.get(node.page, 0)
            ok = all_ok and remaining <= 1     # only the tree's ref left
            return ok, count + (1 if ok else 0)

        return strippable(self._root)[1]

    # -- telemetry ---------------------------------------------------------

    def note_cow(self, n: int = 1) -> None:
        """The engine swapped ``n`` shared pages for fresh copies before a
        divergent write (generate._run_paged shared_beyond/boundary swap)."""
        self.cow_copies += n

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "miss_tokens": self.miss_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
            "cow_copies": self.cow_copies,
            "cached_pages": len(self._pages),
        }

    def occupancy(self) -> dict:
        """Live occupancy — the point-in-time complement to the monotonic
        stats() counters (ISSUE 3 satellite): resident pages (every tree
        node), REFERENCED pages (refcount > 1 — a session or in-flight
        adopter reads them beyond the tree's own reference, so eviction
        cannot touch them), and evictable LEAF pages (refcount exactly 1
        and no children — what one evict() pass could reclaim right now).
        Assumes the owning SessionStore's lock is held, like every other
        inspecting method here."""
        referenced = evictable = 0
        for pg, node in self._pages.items():
            if self.store._refs.get(pg, 1) > 1:
                referenced += 1
            elif not node.children:
                evictable += 1
        return {
            "resident_pages": len(self._pages),
            "referenced_pages": referenced,
            "evictable_leaf_pages": evictable,
        }

    def __len__(self) -> int:
        return len(self._pages)
