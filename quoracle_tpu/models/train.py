"""Training step (fine-tuning) for catalog models.

The reference has no training at all (every model is a hosted API); an
in-tree pool makes fine-tuning a new first-class capability — e.g. adapting a
pool member on accumulated ACE lessons. Also the substrate for the driver's
multichip dry-run: one jitted step over the dp×tp mesh with the same param
shardings the serving path uses (parallel/mesh.py), so XLA lays grads and
optimizer state out exactly like the weights (psum over dp for grads rides
ICI).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.transformer import KVCache, forward, init_cache


class TrainState(NamedTuple):
    params: dict
    opt_state: optax.OptState
    step: jax.Array


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, weight_decay=weight_decay)


def loss_fn(params: dict, cfg: ModelConfig, tokens: jax.Array,
            loss_mask: jax.Array) -> jax.Array:
    """Next-token cross-entropy over [B, T] token batches.

    Runs the same forward as serving (cache write is a no-op cost at T=S);
    one code path to maintain and the dry-run exercises the real model.
    """
    B, T = tokens.shape
    # cache dtype follows the params (bf16 serving-shaped runs, fp32
    # CPU fine-tuning) — a mixed-dtype cache scatter is a trace error
    cache = init_cache(cfg, B, T,
                       dtype=jax.tree.leaves(params)[0].dtype)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    logits, _ = forward(params, cfg, tokens, positions, cache,
                        write_offset=jnp.zeros((B,), jnp.int32),
                        kv_lens=jnp.full((B,), T, jnp.int32))
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def train_step(state: TrainState, cfg: ModelConfig, optimizer,
               tokens: jax.Array, loss_mask: jax.Array) -> tuple[TrainState, jax.Array]:
    loss, grads = jax.value_and_grad(loss_fn)(state.params, cfg, tokens, loss_mask)
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def save_train_state(path: str, state: TrainState) -> None:
    """Durable TrainState checkpoint (orbax): params + optimizer state +
    step, restorable across processes/hosts. Complements the agent-state
    persistence layer (persistence/) — that checkpoints the ORCHESTRATION
    (conversations, tasks, costs); this checkpoints the fine-tuning
    substrate's weights, a capability the reference cannot have (its
    models are hosted APIs, SURVEY §2.3)."""
    import os

    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ckptr:
        # force=True: periodic saves to a stable path (ckpt/latest every N
        # steps) must overwrite, not crash on the second call
        ckptr.save(os.path.abspath(path), state, force=True)


def load_train_state(path: str, template: TrainState) -> TrainState:
    """Restore a TrainState saved by save_train_state. ``template`` is a
    same-shaped state (e.g. freshly initialized) that tells orbax the tree
    structure, dtypes, AND shardings — restoring onto a multihost mesh
    lays the weights out exactly as the template's arrays are."""
    import os

    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path), template)
    if isinstance(restored, TrainState):
        return restored
    # template-less/dict restore shape ({'params','opt_state','step'}) —
    # keyword construction, never positional star-unpacking of dict KEYS
    return TrainState(**restored)
