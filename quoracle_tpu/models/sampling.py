"""Per-row token sampling.

The consensus pipeline needs a DIFFERENT temperature per pool member per
refinement round (reference lib/quoracle/consensus/temperature.ex:84-98 —
temperature descent), so sampling params are [B] arrays, not scalars: one
batched generate step serves heterogeneous sampling configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jax.Array,       # [B, V] fp32
    rng: jax.Array,
    temperature: jax.Array,  # [B] fp32; <= 0 means greedy for that row
    top_p: jax.Array,        # [B] fp32 in (0, 1]; 1.0 disables
) -> jax.Array:
    """Returns [B] int32 sampled token ids. Fully shape-static."""
    B, V = logits.shape

    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # Nucleus mask: drop tokens beyond the top-p cumulative mass.
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Number of tokens to keep per row (always >= 1).
    keep = jnp.sum(cum - sorted_probs < top_p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(sorted_logits, (keep - 1)[:, None], axis=-1)
    masked = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, masked, axis=-1)
    return jnp.where(temperature <= 0, greedy, sampled).astype(jnp.int32)
