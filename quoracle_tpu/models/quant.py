"""Int8 quantization for the serving plane (ISSUE 13 tentpole).

Two independent byte economies, both opt-in per pool member
(``RuntimeConfig.quantize_weights`` / ``quantize_kv``):

**Weights** — per-channel symmetric int8 applied at engine build
(:func:`quantize_params`): every projection matrix keeps an ``int8``
payload plus one fp32 scale per OUTPUT channel (the contraction axis is
reduced away by the matmul, so a per-output scale commutes with it);
matmuls dequantize on the fly (``dequant_weight`` inside the forward —
XLA fuses the convert-multiply into the matmul prologue). Norm vectors
and QKV biases stay bf16: they are O(dim) and numerically load-bearing.

**KV pages** — the session page pool stores int8 K/V with one fp32
scale per (token, kv-head), laid out PAGE-STRUCTURED as
``[L, n_pages, KV, page]`` so a page's scales are a contiguous block
that travels WITH the page through every tier move (demote, disk
spill, prefix write-through, handoff envelope, prefixd fetch). The
``[KV, page]`` orientation is deliberate: inside the ragged Pallas
kernel a page's scale block broadcasts against score rows as
``[1, page]`` — K's scale multiplies the scores (``q·(k·s) = (q·k)·s``
per key token) and V's scale multiplies the probabilities
(``(p·s)·v = p·(v·s)``), so in-kernel dequant never needs a lane
transpose (ops/paged_attention.py).

Quantization rule (shared by every write site so requantization of an
unchanged page is deterministic): ``scale = amax(|x|, hd) / 127``
(1.0 for an all-zero vector), ``q = clip(round(x / scale), -127, 127)``
— symmetric, zero-point-free, the max element lands exactly on ±127.

The scale overhead is 2·KV·4 bytes per token per layer against
2·KV·hd int8 payload bytes — ~3% at hd=128 — so pool capacity, tier
budgets, spill files, and handoff envelopes all land within a few
percent of exactly half their bf16 size.

No reference counterpart (the reference runs no model math locally,
SURVEY.md §2.8); the format follows standard weight-only / KV-cache
int8 serving practice (PAPERS.md Gemma-on-TPU sizing playbook).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# fp32 scale per (token, kv-head), one for K and one for V
KV_SCALE_BYTES_PER_TOKEN_PER_HEAD = 8

# Weight leaves quantized per-channel (everything the matmuls contract
# over); norms/biases stay bf16.
_LAYER_WEIGHT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w) -> bool:
    """True for a quantized-weight leaf ({"q8" + "scale"/"scale_r"})."""
    return isinstance(w, dict) and "q8" in w


def _quantize_channels(w: np.ndarray | jax.Array, axis: int) -> dict:
    """Symmetric int8 over ``axis`` (the contraction axis); the scale
    keeps the remaining (per-output-channel) shape. The scale's KEY
    names its orientation — ``scale`` reduces axis -2 (stacked layer
    weights / lm_head), ``scale_r`` reduces axis -1 (embed rows) — so
    dequant dispatch is structural, never a shape guess (square
    matrices would make shapes ambiguous), and stays correct after
    ``lax.scan`` strips the leading layer axis."""
    assert axis in (-1, -2)
    x = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / jnp.expand_dims(scale, axis)),
                 -127, 127).astype(jnp.int8)
    key = "scale_r" if axis == -1 else "scale"
    return {"q8": q, key: scale.astype(jnp.float32)}


def quantize_params(params: dict, cfg) -> dict:
    """Per-channel symmetric int8 for the text decoder's projection
    matrices (embed / layer projections / lm_head). Vision towers stay
    bf16 (the ViT is a fraction of decoder bytes and its GELU stack is
    less quantization-tolerant). Returns a NEW pytree; unquantized
    leaves are shared, not copied."""
    out = dict(params)
    # embed [V, D]: per-vocab-row scale serves both the gather (row v
    # dequantizes as q[v]·s[v]) and the tied head (logits_v =
    # (h·q[:,v])·s[v] — the row scale IS the head's output-channel
    # scale).
    out["embed"] = _quantize_channels(params["embed"], axis=-1)
    layers = dict(params["layers"])
    for key in _LAYER_WEIGHT_KEYS:
        # stacked [L, in, out]: contraction over ``in`` → scale [L, out]
        layers[key] = _quantize_channels(params["layers"][key], axis=-2)
    out["layers"] = layers
    if "lm_head" in params:
        out["lm_head"] = _quantize_channels(params["lm_head"], axis=-2)
    return out


def dequant_weight(w, dtype=jnp.bfloat16):
    """One weight leaf back to a dense array for the matmul. Quantized
    leaves expand as q8·scale (f32 multiply, cast to ``dtype`` so the
    matmul runs at the same precision as the unquantized path); plain
    arrays pass through untouched — every forward call site routes
    through here, so the two modes share one code path."""
    if not is_quantized(w):
        return w
    q = w["q8"].astype(jnp.float32)
    if "scale_r" in w:          # per-row (embed): scale over axis -1
        return (q * w["scale_r"][..., None]).astype(dtype)
    # per-output-channel (layer projections / lm_head): scale over the
    # contraction axis -2
    return (q * jnp.expand_dims(w["scale"], -2)).astype(dtype)


def params_nbytes(params: dict) -> int:
    """Device bytes of a (possibly quantized) params pytree."""
    return sum(int(x.size) * jnp.dtype(x.dtype).itemsize
               for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# KV page quantization
# ---------------------------------------------------------------------------


def kv_quant(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Quantize KV entries per (…, kv-head): ``x [..., KV, hd]`` →
    (int8 same shape, fp32 scale ``[..., KV]``). The shared write rule:
    deterministic, zero-safe, max element lands on ±127."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def kv_dequant(q: jax.Array, scale: jax.Array,
               dtype=jnp.float32) -> jax.Array:
    """``q [..., KV, hd]`` int8 + ``scale [..., KV]`` → dense KV."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)


def gather_scales(scales: jax.Array, tables: jax.Array) -> jax.Array:
    """Per-layer scale pool ``[n_pages, KV, page]`` gathered by a page
    table ``[B, maxp]`` → token-major ``[B, maxp·page, KV]`` aligned
    with the gathered KV ``[B, maxp·page, KV, hd]``."""
    B, maxp = tables.shape
    _, KV, page = scales.shape
    s = scales[tables]                         # [B, maxp, KV, page]
    return s.transpose(0, 1, 3, 2).reshape(B, maxp * page, KV)


def kv_token_bytes(n_layers: int, n_kv: int, head_dim: int,
                   pool_itemsize: int, quantized: bool) -> int:
    """Per-token K+V pool bytes (scales included when quantized) — the
    one formula the session budget, pool_sizing, /api/kv compression
    and the resources attribution all share."""
    payload = 2 * n_layers * n_kv * head_dim * pool_itemsize
    if quantized:
        payload += n_layers * n_kv * KV_SCALE_BYTES_PER_TOKEN_PER_HEAD
    return payload


def entry_nbytes(*arrays: Optional[np.ndarray]) -> int:
    """Total bytes of a tier entry's payload arrays (None-tolerant)."""
    return sum(int(a.nbytes) for a in arrays if a is not None)
