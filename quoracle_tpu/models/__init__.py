"""JAX model runtime: the in-tree replacement for the reference's entire model
provider layer (reference lib/quoracle/models/ + lib/quoracle/providers/ —
SURVEY.md §2.3). Where the reference resolves credentials and fans out HTTPS
requests per model, this package loads open-weights models onto the TPU mesh
and serves batched generate/embed steps from HBM-resident KV caches.
"""

from quoracle_tpu.models.config import (  # noqa: F401
    ModelConfig,
    get_model_config,
    list_models,
    register_model,
)
