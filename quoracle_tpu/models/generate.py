"""Batched prefill + decode: the generate step that replaces the reference's
per-model HTTPS fan-out (reference lib/quoracle/models/model_query.ex:88-131,
Task.async per model -> ReqLLM.generate_text). A consensus round here is ONE
batched call per pool member with per-row sampling params.

Functional core (this file) is pure and jit-compiled; the stateful Engine
handles padding, shape-bucketing (to bound recompiles), RNG, and
detokenization. Decode runs a ``lax.while_loop`` with static bounds and
early-exits when every row has emitted EOS — shape-static, data-dependent
only in trip count, exactly what XLA wants.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quoracle_tpu.infra.telemetry import (
    DECODE_MS, DECODE_STEP_MS, JIT_COMPILES, PREFILL_MS,
    PREFILL_TOKENS_PER_S, PREFIX_LOOKUP_MS, TRACER,
)
from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.sampling import sample_tokens
from quoracle_tpu.models.transformer import (
    KVCache, forward_hidden, forward_hidden_ragged, init_cache,
    project_logits,
)

# Finite mask value: a whole-row -inf would NaN the sampling softmax; the
# grammar layer guarantees >= 1 allowed token, this is defense in depth.
NEG_INF_LOGITS = -1e30
REJECT_STATE = -1          # models/constrained.py REJECT


def prefill_chunk(params: dict, cfg: ModelConfig, tokens: jax.Array,
                  prefix_lens: jax.Array, chunk_lens: jax.Array,
                  cache: KVCache,
                  kv_off: Optional[jax.Array] = None,
                  ring: Optional[tuple] = None,
                  input_embeds: Optional[jax.Array] = None,
                  ) -> tuple[jax.Array, KVCache]:
    """Fill the cache from a right-padded token CHUNK starting at per-row
    buffer index ``prefix_lens`` (0 = fresh prefill; >0 = resume on top
    of a KV prefix already in the buffer — the prefix-reuse path). Returns
    (last-token logits [B, V], cache with lens = prefix + chunk).

    ``kv_off`` is buffer index 0's absolute position (nonzero only for
    sliding-window sessions whose leading pages were trimmed): RoPE
    positions and the causal mask use kv_off + buffer index.

    The head projection happens AFTER gathering each row's last hidden state —
    projecting the full [B, T, vocab] tensor first would cost ~4 GB/row fp32
    at llama-3-8b scale for values that are immediately discarded."""
    B, T = tokens.shape
    positions = (prefix_lens[:, None]
                 + jnp.arange(T, dtype=jnp.int32)[None, :])
    if kv_off is not None:
        positions = positions + kv_off.astype(jnp.int32)[:, None]
    total = (prefix_lens + chunk_lens).astype(jnp.int32)
    hidden, cache = forward_hidden(
        params, cfg, tokens, positions, cache,
        write_offset=prefix_lens.astype(jnp.int32),
        kv_lens=total,
        kv_pos_offset=kv_off,
        ring=ring,
        input_embeds=input_embeds,
    )
    last_h = jnp.take_along_axis(
        hidden, (chunk_lens - 1)[:, None, None].astype(jnp.int32), axis=1)
    last = project_logits(params, cfg, last_h)[:, 0, :]
    return last, cache._replace(lens=total)


def prefill(params: dict, cfg: ModelConfig, tokens: jax.Array,
            prompt_lens: jax.Array, cache: KVCache,
            ring: Optional[tuple] = None,
            input_embeds: Optional[jax.Array] = None,
            ) -> tuple[jax.Array, KVCache]:
    """Fresh prefill = prefill_chunk from position 0."""
    B = tokens.shape[0]
    return prefill_chunk(params, cfg, tokens,
                         jnp.zeros((B,), jnp.int32), prompt_lens, cache,
                         ring=ring, input_embeds=input_embeds)


def grammar_mask(logits: jax.Array, jstate: jax.Array,
                 json_table: jax.Array, eos_id: int) -> jax.Array:
    """THE grammar mask — every constrained decode path (gather decode,
    direct paged decode, speculative draft + verify) calls this one
    implementation so they can never drift on dead-end or unconstrained
    handling. logits [B, V], jstate [B]; jstate < 0 = unconstrained row;
    a dead-end state (vocab gap: no token allowed) permits eos so the row
    stops instead of sampling an all -inf distribution."""
    allowed = json_table[jnp.clip(jstate, 0, None)] >= 0       # [B, V]
    none_ok = ~jnp.any(allowed, axis=-1, keepdims=True)
    eos_hot = (jnp.arange(logits.shape[-1]) == eos_id)[None, :]
    allowed = allowed | (none_ok & eos_hot) | (jstate < 0)[:, None]
    return jnp.where(allowed, logits, NEG_INF_LOGITS)


def _sampling_fns(json_table: Optional[jax.Array], eos_id: int,
                  stop_ids: tuple):
    """The stop/grammar closures shared by decode() and decode_paged() —
    one implementation so the gather and direct paged paths can never
    drift apart on stop handling or grammar dead-end recovery (the two
    must stay token-exact; tests/test_paged_kv.py equality test)."""
    stops = jnp.asarray((eos_id,) + tuple(stop_ids), jnp.int32)
    constrained = json_table is not None

    def is_stop(tok):
        return jnp.any(tok[:, None] == stops[None, :], axis=1)

    def mask_logits(logits, jstate):
        if not constrained:
            return logits
        return grammar_mask(logits, jstate, json_table, eos_id)

    def advance(jstate, tok, done):
        if not constrained:
            return jstate
        nxt = json_table[jnp.clip(jstate, 0, None), tok].astype(jnp.int32)
        return jnp.where((jstate >= 0) & ~done, nxt, jstate)

    return is_stop, mask_logits, advance, constrained


def _first_token(fns, first_logits, rng, temperature, top_p, active,
                 row_limit, json_state, max_new: int, pad_id: int):
    """Shared decode bootstrap: sample token 0 from the prefill logits and
    build the initial (tok0, n0, done0, jstate0, out0, rng) carry."""
    is_stop, mask_logits, advance, constrained = fns
    B = first_logits.shape[0]
    jstate0 = json_state if constrained else jnp.zeros((B,), jnp.int32)
    rng, k0 = jax.random.split(rng)
    tok0 = sample_tokens(mask_logits(first_logits, jstate0), k0,
                         temperature, top_p)
    n0 = jnp.where(active, 1, 0).astype(jnp.int32)
    done0 = ~active | is_stop(tok0) | (n0 >= row_limit)
    # advance on tok0 for every active row (eos self-loops in accept states)
    jstate0 = advance(jstate0, tok0, ~active)
    out0 = jnp.full((B, max_new), pad_id, jnp.int32).at[:, 0].set(tok0)
    return tok0, n0, done0, jstate0, out0, rng


def decode(
    params: dict,
    cfg: ModelConfig,
    cache: KVCache,
    first_logits: jax.Array,   # [B, V] logits at the last prompt token
    rng: jax.Array,
    temperature: jax.Array,    # [B]
    top_p: jax.Array,          # [B]
    max_new: int,
    eos_id: int,
    active: jax.Array,         # [B] bool — False for batch-bucket padding rows
    row_limit: jax.Array,      # [B] int32 per-row generation budget (<= max_new)
    pad_id: int = 0,
    stop_ids: tuple = (),      # extra stop ids (llama-3 <|eot_id|> style)
    json_table: Optional[jax.Array] = None,   # [S, V] grammar transitions
    json_state: Optional[jax.Array] = None,   # [B] int32; -1 = unconstrained
    kv_off: Optional[jax.Array] = None,       # [B] int32 abs pos of index 0
) -> tuple[jax.Array, jax.Array, KVCache]:
    """Autoregressive decode.

    Returns (tokens [B, max_new], n_emitted [B], final cache) where
    n_emitted counts real tokens written per row INCLUDING a terminal EOS.
    The count is tracked in the loop carry — output extraction must not scan
    for sentinels, because pad_id can be a legitimate vocab token in real
    checkpoints. The returned cache holds the RESPONSE tokens' KV too
    (``lens[b]`` bounds the valid entries: prompt + every emitted token
    except the last sampled one, which never ran forward) — sessions keep it
    so refinement rounds skip re-prefilling the previous response.

    ``max_new`` is the STATIC loop/buffer bound (shape-bucketed for compile
    caching); ``row_limit`` is the TRACED per-row budget — min(requested
    max_new_tokens, context_window - prompt_len). A row stops at EOS or at
    its limit, so bucketing never costs extra forward steps and no row's
    positions run past the context window. Padding rows (``~active``) start
    done, so the early-exit fires when every REAL row has finished.

    With ``json_table``/``json_state`` set, rows whose state is >= 0 sample
    under the JSON grammar mask (models/constrained.py): each step is one
    row gather (allowed = table[state] >= 0) + where() before sampling, and
    a scalar gather to advance the state — output is valid JSON by
    construction (SURVEY §7 hard part 4).
    """
    fns = _sampling_fns(json_table, eos_id, stop_ids)
    is_stop, mask_logits, advance, _ = fns
    tok0, n0, done0, jstate0, out0, rng = _first_token(
        fns, first_logits, rng, temperature, top_p, active, row_limit,
        json_state, max_new, pad_id)

    def cond(carry):
        i, done, *_ = carry
        return (i < max_new) & ~jnp.all(done)

    def body(carry):
        i, done, cur, out, n_emitted, cache, rng, jstate = carry
        positions = cache.lens[:, None]
        if kv_off is not None:
            positions = positions + kv_off.astype(jnp.int32)[:, None]
        hidden, cache = forward_hidden(
            params, cfg, cur[:, None], positions, cache,
            write_offset=cache.lens, kv_lens=cache.lens + 1,
            kv_pos_offset=kv_off,
        )
        logits = project_logits(params, cfg, hidden)
        rng, k = jax.random.split(rng)
        nxt = sample_tokens(mask_logits(logits[:, 0, :], jstate), k,
                            temperature, top_p)
        nxt = jnp.where(done, pad_id, nxt)
        out = jax.lax.dynamic_update_slice_in_dim(out, nxt[:, None], i, axis=1)
        n_emitted = n_emitted + jnp.where(done, 0, 1).astype(jnp.int32)
        cache = cache._replace(lens=cache.lens + jnp.where(done, 0, 1))
        jstate = advance(jstate, nxt, done)
        done = done | is_stop(nxt) | (n_emitted >= row_limit)
        return (i + 1, done, nxt, out, n_emitted, cache, rng, jstate)

    # Feed the first sampled token through the loop starting at step 1.
    init = (jnp.asarray(1, jnp.int32), done0, tok0, out0, n0, cache, rng,
            jstate0)
    _, done, _, out, n_emitted, cache, _, jstate = \
        jax.lax.while_loop(cond, body, init)
    # jstate returned so chunked continuations (models/scheduler.py) can
    # resume the grammar mid-stream via initial_json_state.
    return out, n_emitted, cache, jstate


def decode_paged(
    params: dict,
    cfg: ModelConfig,
    k_pool: jax.Array,         # [L, n_pages, page, KV, hd] — READ-ONLY
    v_pool: jax.Array,
    tables: jax.Array,         # [B, maxp] int32
    pool_lens: jax.Array,      # [B] int32 valid pool tokens (the prompt)
    kv_off: jax.Array,         # [B] int32 abs position of pool index 0
    first_logits: jax.Array,   # [B, V]
    rng: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    max_new: int,
    eos_id: int,
    active: jax.Array,
    row_limit: jax.Array,
    pad_id: int = 0,
    stop_ids: tuple = (),
    json_table: Optional[jax.Array] = None,
    json_state: Optional[jax.Array] = None,
    tail_dtype=jnp.bfloat16,
    shard: Optional[tuple] = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Autoregressive decode against the PAGED pool: same sampling/grammar
    semantics as decode(), but attention reads the row's pages directly
    (ragged — transformer.forward_hidden_paged) and new tokens' KV land in
    a [L, B, max_new] TAIL buffer instead of a gathered working cache. The
    memory high-water drops from pool + [B, maxp·page] working cache to
    pool + tail, and per-step KV reads are proportional to each row's real
    length (NOTES_r03 gap 2).

    Returns (tokens [B, max_new], n_emitted [B], lens [B], tail_k, tail_v)
    where lens = pool_lens + valid tail entries per row — the caller
    scatters tail[:, :lens-pool_lens] into the row's pages (page bookkeeping
    is host-side, as in the gather path).
    """
    from quoracle_tpu.models.transformer import forward_hidden_paged
    B = first_logits.shape[0]
    L, _, page, KV, HD = k_pool.shape
    fns = _sampling_fns(json_table, eos_id, stop_ids)
    is_stop, mask_logits, advance, _ = fns
    tok0, n0, done0, jstate0, out0, rng = _first_token(
        fns, first_logits, rng, temperature, top_p, active, row_limit,
        json_state, max_new, pad_id)
    tail_k0 = jnp.zeros((L, B, max_new, KV, HD), tail_dtype)
    tail_v0 = jnp.zeros((L, B, max_new, KV, HD), tail_dtype)
    lens0 = pool_lens.astype(jnp.int32)

    def cond(carry):
        i, done, *_ = carry
        return (i < max_new) & ~jnp.all(done)

    def body(carry):
        (i, done, cur, out, n_emitted, lens, tail_k, tail_v, rng,
         jstate) = carry
        positions = (lens + kv_off.astype(jnp.int32))[:, None]
        hidden, tail_k, tail_v = forward_hidden_paged(
            params, cfg, cur[:, None], positions, k_pool, v_pool, tables,
            pool_lens, kv_off, tail_k, tail_v, step=i - 1, shard=shard)
        logits = project_logits(params, cfg, hidden)
        rng, k = jax.random.split(rng)
        nxt = sample_tokens(mask_logits(logits[:, 0, :], jstate), k,
                            temperature, top_p)
        nxt = jnp.where(done, pad_id, nxt)
        out = jax.lax.dynamic_update_slice_in_dim(out, nxt[:, None], i,
                                                  axis=1)
        n_emitted = n_emitted + jnp.where(done, 0, 1).astype(jnp.int32)
        lens = lens + jnp.where(done, 0, 1)
        jstate = advance(jstate, nxt, done)
        done = done | is_stop(nxt) | (n_emitted >= row_limit)
        return (i + 1, done, nxt, out, n_emitted, lens, tail_k, tail_v,
                rng, jstate)

    init = (jnp.asarray(1, jnp.int32), done0, tok0, out0, n0, lens0,
            tail_k0, tail_v0, rng, jstate0)
    (_, done, _, out, n_emitted, lens, tail_k, tail_v, _, jstate) = \
        jax.lax.while_loop(cond, body, init)
    return out, n_emitted, lens, tail_k, tail_v, jstate


def decode_ragged(
    params: dict,
    cfg: ModelConfig,
    k_pool: jax.Array,         # [L, n_pages, page, KV, hd] (donated by jit)
    v_pool: jax.Array,
    tables: jax.Array,         # [R, maxp] int32 dst page table per row
    pool_lens: jax.Array,      # [R] int32 valid pool tokens (prompt+chunk)
    kv_off: jax.Array,         # [R] int32 abs position of pool index 0
    first_logits: jax.Array,   # [R, V]
    rng: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    max_new: int,
    eos_id: int,
    active: jax.Array,
    row_limit: jax.Array,
    pad_id: int = 0,
    stop_ids: tuple = (),
    json_table: Optional[jax.Array] = None,
    json_state: Optional[jax.Array] = None,
    shard: Optional[tuple] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,   # [L, n_pages, KV, page] f32 —
    v_scale: Optional[jax.Array] = None,   # int8 pools (ISSUE 13)
) -> tuple:
    """Autoregressive decode through the UNIFIED ragged kernel (ISSUE 8):
    same sampling/grammar semantics as decode()/decode_paged(), but each
    step's KV scatters STRAIGHT into the row's pages before attention and
    the kernel reads everything — prompt, chunk, and generated tokens —
    off the pages. Neither the [B, maxp·page] working cache nor the
    [L, B, max_new] tail buffer exists; decode HBM high-water is the pool
    itself. Every step is one tq=1-block-per-row launch per layer of the
    same kernel that served the mixed prefill chunk.

    Returns (tokens [R, max_new], n_emitted [R], lens [R], k_pool,
    v_pool, jstate) where lens counts the row's valid pool tokens
    (prompt + chunk + emitted-and-forwarded). With ``k_scale``/
    ``v_scale`` (int8 pools, ISSUE 13) each step's token quantizes on
    write inside the forward and the return grows (…, k_scale, v_scale,
    jstate)."""
    R = first_logits.shape[0]
    L, n_pages, page, KV, HD = k_pool.shape
    n_tok = n_pages * page
    maxp = tables.shape[1]
    quant = k_scale is not None
    fns = _sampling_fns(json_table, eos_id, stop_ids)
    is_stop, mask_logits, advance, _ = fns
    tok0, n0, done0, jstate0, out0, rng = _first_token(
        fns, first_logits, rng, temperature, top_p, active, row_limit,
        json_state, max_new, pad_id)
    lens0 = pool_lens.astype(jnp.int32)

    def cond(carry):
        i, done, *_ = carry
        return (i < max_new) & ~jnp.all(done)

    def body(carry):
        (i, done, cur, out, n_emitted, lens, kp, vp, ks, vs, rng,
         jstate) = carry
        live = (~done).astype(jnp.int32)
        # this step's token writes at buffer slot lens; done rows (and
        # any row at its page-table edge) drop via the OOB sentinel
        pg = jnp.take_along_axis(
            tables, jnp.minimum(lens // page, maxp - 1)[:, None],
            axis=1)[:, 0]
        flat = jnp.where(done | (lens // page >= maxp), n_tok,
                         pg * page + lens % page)
        meta = jnp.stack([
            lens + live,              # kv_len incl. the token just written
            lens - (1 - live),        # qpos0 (done rows: inert block)
            live,                     # nq
        ], axis=1)
        positions = lens + kv_off.astype(jnp.int32)
        if quant:
            hidden, kp, vp, ks, vs = forward_hidden_ragged(
                params, cfg, cur[None], positions[None], kp, vp, tables,
                meta, flat, tq=1, interpret=interpret, shard=shard,
                k_scale=ks, v_scale=vs)
        else:
            hidden, kp, vp = forward_hidden_ragged(
                params, cfg, cur[None], positions[None], kp, vp, tables,
                meta, flat, tq=1, interpret=interpret, shard=shard)
        logits = project_logits(params, cfg, hidden)[0]      # [R, V]
        rng, k = jax.random.split(rng)
        nxt = sample_tokens(mask_logits(logits, jstate), k, temperature,
                            top_p)
        nxt = jnp.where(done, pad_id, nxt)
        out = jax.lax.dynamic_update_slice_in_dim(out, nxt[:, None], i,
                                                  axis=1)
        n_emitted = n_emitted + jnp.where(done, 0, 1).astype(jnp.int32)
        lens = lens + jnp.where(done, 0, 1)
        jstate = advance(jstate, nxt, done)
        done = done | is_stop(nxt) | (n_emitted >= row_limit)
        return (i + 1, done, nxt, out, n_emitted, lens, kp, vp, ks, vs,
                rng, jstate)

    # unquantized loops carry scale placeholders as empty pytrees (None
    # is a valid while_loop carry leaf-less node)
    init = (jnp.asarray(1, jnp.int32), done0, tok0, out0, n0, lens0,
            k_pool, v_pool, k_scale, v_scale, rng, jstate0)
    (_, done, _, out, n_emitted, lens, k_pool, v_pool, k_scale, v_scale,
     _, jstate) = jax.lax.while_loop(cond, body, init)
    if quant:
        return (out, n_emitted, lens, k_pool, v_pool, k_scale, v_scale,
                jstate)
    return out, n_emitted, lens, k_pool, v_pool, jstate


def _round_up(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n


# Unified-kernel flat-layout constants (ISSUE 8): rows' query segments are
# padded to RAGGED_TQ-token blocks (the f32 sublane tile) and the flat
# token budget rounds to RAGGED_TOKEN_BUCKETS — the ONLY shape the unified
# programs key on, so steady state compiles one (chunk, decode) pair per
# token-budget bucket instead of prefill×decode per batch bucket.
RAGGED_TQ = 8
RAGGED_TOKEN_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
                        8192, 16384, 32768)


class ContextOverflowError(ValueError):
    """Prompt does not fit the model's context window. The condensation layer
    catches this and retries after condensing (reference semantics:
    per_model_query.ex:93-120 retry-on-context-overflow)."""


@dataclasses.dataclass
class GenResult:
    token_ids: list[int]
    text: str
    n_prompt_tokens: int
    n_gen_tokens: int
    latency_s: float
    finish_reason: str  # "stop" | "length"
    n_cached_tokens: int = 0   # prompt prefix served from a resident KV session
    json_state: int = -1  # final grammar state (-1 = unconstrained); feed
                          # back as initial_json_state to resume a
                          # constrained stream mid-JSON (chunked
                          # continuation, models/scheduler.py)
    # Speculative serving attribution (models/speculative.py
    # BatchedSpeculator → models/scheduler.py): how much of this result
    # was produced by draft/verify rounds instead of vanilla decode
    # steps. Zero on the plain paths.
    spec_rounds: int = 0
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0
    # Chip-economics attribution (ISSUE 17, infra/costobs.py): this
    # row's share of the measured device wall for the jitted steps it
    # rode, split by real tokens. 0.0 with accounting off or on paths
    # that drive their own jits (v1 batch-1 speculative decoder).
    chip_ms: float = 0.0


PAGE = 128   # tokens per KV page


@dataclasses.dataclass
class _Session:
    """Resident KV state for one conversation (agent × model).

    ``tokens`` is the full conversation's token ids (host ints, cheap);
    their K/V live in fixed-size PAGES of the engine's device-resident
    pool — ``pages[j]`` holds buffer positions [j·PAGE, (j+1)·PAGE) of the
    working cache, which map to absolute positions offset by ``start_pos``
    (nonzero after sliding-window trimming drops leading pages). The next
    round's prompt reuses the longest common prefix — refinement rounds
    extend the prior prompt+response, so the whole previous conversation
    (response KV included) resumes for free; after condensation the prefix
    shrinks to the still-shared system prompt (reference analog: cached
    system prompt, consensus_handler.ex:126-152).
    """
    tokens: list[int]
    pages: list[int]
    start_pos: int = 0
    last_used: float = 0.0
    # synthetic donor-prefix marker (cross-session prefix sharing): the
    # pages belong to ANOTHER session; _run_paged refcount-acquires them
    # before using them as this row's dst prefix
    shared_prefix: bool = False

    @property
    def resident_len(self) -> int:
        return len(self.tokens) - self.start_pos


class SessionStore:
    """Paged session cache (VERDICT r2 item 4): sessions are PAGE LISTS
    into one pool; resume moves no KV data host-side — the jitted step
    gathers pages in-device from a [B, maxp] int32 table, and the decode
    step scatters prompt+response KV back to the pages in place. Page 0 is
    scratch (rows without a session write there). LRU sessions evict when
    the free list runs dry. Thread-safe; the ENGINE additionally serializes
    paged steps (the pool buffers are donated through them)."""

    def __init__(self, max_tokens: int = 262_144, page: int = PAGE):
        from quoracle_tpu.analysis.lockdep import named_lock
        self.page = page
        self.n_pages = max(3, -(-max_tokens // page) + 1)   # +1 scratch
        self.max_tokens = (self.n_pages - 1) * page
        self.lock = named_lock("session.store", rlock=True)
        self._sessions: dict[str, _Session] = {}
        self._free: list[int] = list(range(self.n_pages - 1, 0, -1))
        # Page refcounts (cross-session PREFIX SHARING): a page referenced
        # by several sessions frees only when the last reference releases.
        # Absent key = 1 (every allocated page starts singly-owned).
        self._refs: dict[int, int] = {}
        # Radix prefix cache (models/prefix_cache.py): page-aligned token
        # blocks -> pool pages, holding its own reference on each, so
        # cached prefixes outlive the session that prefilled them. The
        # engine feeds it at store-back and consults it for new sessions.
        from quoracle_tpu.models.prefix_cache import RadixPrefixCache
        self.prefix_cache = RadixPrefixCache(self)
        # device pool arrays live on the engine (self.k/self.v set there);
        # the store only manages ids. Quantized-KV engines (ISSUE 13)
        # additionally hold the per-(token, kv-head) fp32 scale pools
        # ([L, n_pages, KV, page]) beside the int8 payload pools.
        self.k: Optional[jax.Array] = None
        self.v: Optional[jax.Array] = None
        self.k_scale: Optional[jax.Array] = None
        self.v_scale: Optional[jax.Array] = None
        # Tiered KV (ISSUE 7, serving/kvtier.py): when attached, alloc's
        # eviction ladder DEMOTES victims to the host tier instead of
        # destroying them, and the engine's session lookup restores
        # hibernated sessions by page-in instead of re-prefill.
        self.tier = None
        self.model = ""          # metric label; engine sets cfg.name

    def get(self, key: str) -> Optional[_Session]:
        with self.lock:
            s = self._sessions.get(key)
            if s is not None:
                s.last_used = time.monotonic()
            return s

    def alloc(self, n: int, protect: tuple = (),
              evict: bool = True) -> Optional[list[int]]:
        """Take n pages from the free list, evicting LRU sessions (never
        the ``protect`` keys — the batch's own sessions) as needed.
        Returns None — WITHOUT evicting anything — when the request cannot
        be satisfied even by evicting every unprotected session.

        ``evict=False`` takes only from the free list: TEMP allocations
        (direct-decode scratch for sessionless rows) must never destroy
        other agents' resident sessions — or thrash the prefix cache — for
        pages that die at call end; the caller falls back to the gather
        decode instead.

        Eviction order: RADIX-CACHE LEAVES first (a cached-but-unreferenced
        prefix is recomputable; a resident session is another agent's live
        state), then LRU sessions. Attainability is counted exactly per
        page refcount — a page shared with a protected session, an
        in-flight adopter, or a cache node that cannot strip does NOT free
        when its victim releases it, so it must not be counted (the old
        len(pages) sum overcounted shared pages)."""
        with self.lock:
            if not evict:
                if n > len(self._free):
                    return None
                return [self._free.pop() for _ in range(n)]
            victims = [k for k in self._sessions if k not in protect]
            if n > self._attainable(victims):
                return None
            while len(self._free) < n:
                if self.prefix_cache.evict(n - len(self._free)):
                    continue
                if not victims:
                    break        # _attainable guarantees this can't happen
                lru = min(victims, key=lambda k: self._sessions[k].last_used)
                victims.remove(lru)
                sess = self._sessions.pop(lru)
                if self.tier is not None:
                    # eviction is demotion, not destruction (ISSUE 7):
                    # one device_get copies the victim host-side; the
                    # release below drops only the victim's own refs, so
                    # shared/COW pages other holders read stay resident
                    self.tier.demote_session(lru, sess)
                self._release(sess.pages)
            if len(self._free) < n:
                # defensive: accounting drift — _attainable promised pages
                # the ladder could not deliver. Formerly a silent None;
                # now counted and flight-recorded (ISSUE 7 satellite) so
                # a refcount bug surfaces as telemetry, not as mystery
                # re-prefills.
                from quoracle_tpu.infra.flightrec import FLIGHT
                from quoracle_tpu.infra.telemetry import KV_ALLOC_DRIFT_TOTAL
                KV_ALLOC_DRIFT_TOTAL.inc(model=self.model)
                FLIGHT.record("kv_alloc_drift", model=self.model,
                              requested=n, free=len(self._free),
                              sessions=len(self._sessions))
                return None
            return [self._free.pop() for _ in range(n)]

    def _attainable(self, victims: list) -> int:
        """Exact count of pages reachable by evicting ``victims`` and then
        stripping freeable prefix-cache leaves: free list + cache pages
        whose every non-tree reference a victim would release + victim
        pages (outside the cache) all of whose references victims hold."""
        import collections
        released: collections.Counter = collections.Counter()
        for k in victims:
            for p in self._sessions[k].pages:
                if p:
                    released[p] += 1
        n_tree = self.prefix_cache.evictable_after(released)
        extra = sum(1 for p, c in released.items()
                    if not self.prefix_cache.holds(p)
                    and c >= self._refs.get(p, 1))
        return len(self._free) + n_tree + extra

    def _release(self, pages: list[int]) -> None:
        for p in pages:
            if p == 0:
                continue
            c = self._refs.get(p, 1) - 1
            if c <= 0:
                self._refs.pop(p, None)
                self._free.append(p)
            else:
                self._refs[p] = c

    def release(self, pages: list[int]) -> None:
        with self.lock:
            self._release(pages)

    def acquire(self, pages: list[int]) -> None:
        """Add a reference to already-allocated pages (prefix sharing:
        an adopter holds the donor's prefix pages alive past the donor's
        own drop/eviction)."""
        with self.lock:
            for p in pages:
                if p != 0:
                    self._refs[p] = self._refs.get(p, 1) + 1

    def match_prefix(self, tokens: Sequence[int],
                     max_reuse: int) -> Optional["_Session"]:
        """Cross-session prefix sharing (SURVEY §7 hard part 2's "system
        prompt cache", the vLLM automatic-prefix-caching analog), served
        by the RADIX PREFIX CACHE: the longest PAGE-ALIGNED cached token
        prefix of ``tokens`` — agents of one config share their system
        prompt verbatim, so a freshly spawned agent's first prefill can
        adopt those pages read-only instead of recomputing them, and the
        tree's own page references mean the prefix stays adoptable after
        the session that prefilled it dies. Alignment is a correctness
        requirement: the boundary page may be partially filled by the
        donor, and the adopter's own suffix must never write into a
        shared page. Returns a synthetic marker session (cached prefix
        tokens + page ids, shared_prefix=True) or None."""
        with self.lock:
            if self.tier is not None:
                # tiered extension (ISSUE 7): blocks stripped to the host
                # tier — or persisted to disk by a previous process —
                # page back in and re-enter the tree before the match, so
                # a restart-warm prefix is indistinguishable from a
                # resident one
                self.tier.extend_prefix(tokens, max_reuse)
            pages, matched = self.prefix_cache.match(tokens, max_reuse)
            if matched < self.page:
                return None
            return _Session(tokens=list(tokens[:matched]),
                            pages=pages, start_pos=0, shared_prefix=True)

    def insert_prefix(self, tokens: Sequence[int],
                      pages: Sequence[int]) -> int:
        """Feed a freshly stored session's full pages into the radix
        cache (the engine calls this at store-back for full-attention,
        non-VLM sessions with start_pos == 0). With a disk-backed tier
        attached, each full block also writes through to the checksummed
        prefix store (content-addressed — re-inserts cost one stat), so
        a restarted process warm-starts from these prefixes."""
        with self.lock:
            added = self.prefix_cache.insert(tokens, pages)
            # durable targets: the local disk store and/or the fleet
            # prefix service (ISSUE 12) — persist_block fans out to both
            if (self.tier is not None
                    and (self.tier.disk is not None
                         or self.tier.prefixd is not None)):
                for j in range(len(tokens) // self.page):
                    if j < len(pages) and pages[j]:
                        self.tier.persist_block(
                            [int(t) for t in tokens[:(j + 1) * self.page]],
                            pages[j])
            return added

    def put(self, key: str, sess: _Session) -> None:
        """Replace a session, releasing any of the old session's pages the
        new one no longer references."""
        sess.last_used = time.monotonic()
        with self.lock:
            old = self._sessions.get(key)
            if old is not None and old is not sess:
                self._release([p for p in old.pages if p not in sess.pages])
            self._sessions[key] = sess
            if self.tier is not None:
                self.tier.discard_session(key)   # host copy now stale

    def put_raw(self, key: str, sess: _Session) -> None:
        """Replace WITHOUT page bookkeeping — the caller owns the page
        lifecycle (the engine's paged step releases explicitly)."""
        sess.last_used = time.monotonic()
        with self.lock:
            self._sessions[key] = sess
            if self.tier is not None:
                self.tier.discard_session(key)   # host copy now stale

    def register_restored(self, key: str, tokens: list, pages: list[int],
                          start_pos: int) -> "_Session":
        """Build + register a session the tier just paged back in
        (serving/kvtier.py restore_session — the tier stays ignorant of
        the _Session type, preserving the serving → infra dependency
        direction). Caller holds the lock and owns the pages."""
        sess = _Session(tokens=tokens, pages=pages, start_pos=start_pos)
        self.put_raw(key, sess)
        return sess

    def drop(self, key: str) -> None:
        with self.lock:
            s = self._sessions.pop(key, None)
            if s is not None:
                self._release(s.pages)
            if self.tier is not None:
                # a dropped conversation must not resurrect from the
                # host tier under a reused id
                self.tier.discard_session(key)

    def free_pages(self) -> int:
        with self.lock:
            return len(self._free)

    def __len__(self) -> int:
        with self.lock:
            return len(self._sessions)


class CompileRegistry:
    """Per-engine record of every dispatched shape bucket (ISSUE 3):
    replaces the single first-shape ``_seen_shapes`` heuristic with an
    accountable ledger — each (shape-bucket) key remembers its first-call
    wall time (compile-dominated unless the persistent XLA cache held the
    executable) and how many later calls HIT it, and a sliding miss
    window trips a RECOMPILE-STORM gauge when more than ``threshold``
    new shapes compile inside ``window_s`` seconds. A storm is the
    classic capacity incident of bucketed serving (a caller bypassing
    the shape buckets turns every round into a 15-40 s compile) and is
    now attributable from telemetry instead of reproduced.

    Per ENGINE, not process-wide: each engine's jit wrappers own their
    compile caches, so a second engine for the same model genuinely
    recompiles — one shared ledger would miscount that as a hit. The
    process-wide aggregate lives in the METRICS counters the methods
    feed (quoracle_compile_cache_{hits,misses}_total)."""

    def __init__(self, model: str, window_s: float = 120.0,
                 threshold: int = 4):
        from quoracle_tpu.analysis.lockdep import named_lock
        self.model = model
        self.window_s = window_s
        self.threshold = threshold
        self._lock = named_lock("cache.compile")
        self._shapes: dict[tuple, dict] = {}
        self._miss_times: list[float] = []
        self.hits = 0
        self.misses = 0
        self.storm = False
        self.storms_total = 0

    def record(self, shape: tuple, wall_ms: float) -> bool:
        """Record one dispatch; returns True on a MISS (first sight of
        this shape bucket — the call paid the compile)."""
        from quoracle_tpu.infra.telemetry import COMPILE_HITS, COMPILE_MISSES
        # Chaos seam (ISSUE 11): "poison" salts the ledger key so every
        # dispatch books as a fresh miss — a ledger-level recompile
        # storm (the gauge/alerting path end-to-end) with zero actual
        # XLA compiles and zero effect on served bits.
        from quoracle_tpu.chaos.faults import CHAOS
        d = CHAOS.fire("compile.key", model=self.model)
        if d is not None and d.kind == "poison":
            shape = tuple(shape) + ("chaos-poison", d.n)
        now = time.monotonic()
        with self._lock:
            entry = self._shapes.get(shape)
            if entry is None:
                self._shapes[shape] = {
                    "shape": shape, "compile_ms": round(wall_ms, 1),
                    "ts": time.time(), "hits": 0,
                }
                self.misses += 1
                self._miss_times.append(now)
                miss = True
            else:
                entry["hits"] += 1
                self.hits += 1
                miss = False
            self._refresh_locked(now)
        (COMPILE_MISSES if miss else COMPILE_HITS).inc(model=self.model)
        return miss

    def _refresh_locked(self, now: float) -> None:
        from quoracle_tpu.infra.telemetry import (
            COMPILE_MISSES_IN_WINDOW, COMPILE_STORM,
        )
        self._miss_times = [t for t in self._miss_times
                            if now - t <= self.window_s]
        n = len(self._miss_times)
        storm = n >= self.threshold
        COMPILE_MISSES_IN_WINDOW.set(n, model=self.model)
        COMPILE_STORM.set(1.0 if storm else 0.0, model=self.model)
        if storm and not self.storm:
            self.storms_total += 1
            from quoracle_tpu.infra.flightrec import FLIGHT
            FLIGHT.record("compile_storm", model=self.model,
                          misses_in_window=n, window_s=self.window_s)
        self.storm = storm

    def refresh(self) -> None:
        """Re-evaluate the storm window against the clock (collector
        hook: a storm must clear at the next scrape even with no new
        dispatches aging the window)."""
        with self._lock:
            self._refresh_locked(time.monotonic())

    def snapshot(self, max_shapes: int = 32) -> dict:
        """JSON view for /api/resources: totals, hit rate, storm state,
        and the most expensive shape entries."""
        with self._lock:
            shapes = sorted(self._shapes.values(),
                            key=lambda e: -e["compile_ms"])[:max_shapes]
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "n_shapes": len(self._shapes),
                "storm": self.storm,
                "storms_total": self.storms_total,
                "misses_in_window": len(self._miss_times),
                "window_s": self.window_s,
                "threshold": self.threshold,
                "shapes": [{**e, "shape": "x".join(map(str, e["shape"]))}
                           for e in shapes],
            }


def _lcp(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


def splice_session_prompt(tokenizer, sess_tokens: Sequence[int],
                          plain_ids: Sequence[int]) -> Optional[list[int]]:
    """Token-level session splice: rebuild a prompt so it shares the longest
    possible TOKEN prefix with ``sess_tokens`` (the session's actual ids —
    original prompt + the ids the model itself sampled).

    Refinement rounds append the assistant's raw text to the conversation
    and re-render the chat template (consensus/engine.py:161); re-ENCODING
    that text rarely reproduces the ids the model SAMPLED, so a plain token
    LCP stops at the previous round's prompt and the retained response KV
    (already resident, generate.py decode) never matches. Comparing decoded
    TEXT instead — and keeping the session's own ids for the shared region —
    resumes the whole previous conversation from resident KV; only the
    genuinely new suffix (template glue + the refinement message) re-encodes.

    Returns the spliced ids, or None when the plain encoding already matches
    the session at least as far (nothing to gain).
    """
    plain_reuse = _lcp(sess_tokens, plain_ids)
    canonical = tokenizer.decode_raw(plain_ids)
    if not canonical:
        return None
    # Fast path: clean extension — the refinement-round shape.
    if canonical.startswith(tokenizer.decode_raw(sess_tokens)):
        k = len(sess_tokens)
    else:
        # Largest k with decode(sess[:k]) a prefix of the new text (lo always
        # satisfies it). The predicate is NOT strictly monotone: a k ending
        # mid-UTF-8 decodes with trailing U+FFFD and fails even when a
        # LONGER prefix decodes cleanly — and such pockets CHAIN when
        # byte-fallback tokens straddle char boundaries (emoji runs). So:
        # bisect, then scan past the settle point while the mismatch is
        # confined to the trailing replacement-char run (still mid-char);
        # any clean success restarts the bisection from there. A mismatch
        # before the trailing U+FFFDs is genuine divergence (condensation
        # rewrote history) and ends the scan. A probe budget bounds the
        # worst-case decode work on the serving hot path.
        def _pred(j: int) -> bool:
            return canonical.startswith(tokenizer.decode_raw(sess_tokens[:j]))

        lo, hi = 0, len(sess_tokens)
        misses = 64
        while True:
            while lo < hi:
                mid = (lo + hi + 1) // 2
                if _pred(mid):
                    lo = mid
                else:
                    hi = mid - 1
            escaped = False
            j = lo + 1
            while j <= len(sess_tokens) and misses > 0:
                s = tokenizer.decode_raw(sess_tokens[:j])
                if canonical.startswith(s):
                    lo, hi, escaped = j, len(sess_tokens), True
                    break
                misses -= 1
                if not canonical.startswith(s.rstrip("�")):
                    break       # diverges before the partial-char tail
                j += 1
            if not escaped:
                break
        k = lo
    # ≥1 suffix token must run through prefill to produce last-position
    # logits; and the splice must beat the plain prefix to be worth
    # diverging from the canonical tokenization.
    while k > plain_reuse:
        suffix = tokenizer.encode(
            canonical[len(tokenizer.decode_raw(sess_tokens[:k])):])
        if suffix:
            return list(sess_tokens[:k]) + suffix
        k -= 1
    return None


class GenerateEngine:
    """Stateful serving wrapper around the functional core for ONE model.

    Holds params (device-resident), compiles (prefill+decode) per shape
    bucket, and exposes a list-in/list-out generate(). The pool runtime
    (models/runtime.py) owns one Engine per pool member.

    With ``mesh`` set, the engine serves SHARDED: params placed per
    parallel/mesh.param_specs (Megatron-style tp), the KV cache constrained
    to cache_spec, and inputs laid out on the dp axis — GSPMD inserts the
    psums, which ride ICI (SURVEY.md §2.9 tp-sharded serving). A pool on a
    multi-chip slice gives each member its own sub-mesh
    (parallel.mesh.pool_submeshes) and the host scheduler overlaps members
    (models/runtime.py). mesh=None is the single-chip degenerate case.

    generate() is thread-safe: the host-side RNG draw is locked; everything
    else is functional.
    """

    BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)

    def __init__(self, cfg: ModelConfig, params: dict, tokenizer,
                 max_seq: Optional[int] = None, seed: int = 0,
                 prompt_buckets: Sequence[int] = (128, 256, 512, 1024, 2048, 4096, 8192),
                 mesh=None, session_max_bytes: int = 2 << 30,
                 sp_window: Optional[int] = None,
                 quantize_weights: bool = False,
                 quantize_kv: bool = False):
        import threading

        from quoracle_tpu.analysis.lockdep import named_lock
        self.cfg = cfg
        self.mesh = mesh
        self.last_prefill_tokens = 0   # diagnostics: suffix actually computed
        # Int8 quantized serving (ISSUE 13, models/quant.py): weights
        # quantize per-channel at build; the KV pool stores int8 pages
        # with per-(token, kv-head) scales beside them. Single-device
        # engines only for now — shard_params has no placement rule for
        # {q8, scale} leaves, and the flat ragged layout is the
        # quantized serving path (it can't ride a dp axis anyway).
        self.quantize_weights = bool(quantize_weights)
        self.quantize_kv = bool(quantize_kv)
        if (self.quantize_weights or self.quantize_kv) \
                and mesh is not None:
            raise ValueError(
                f"engine {cfg.name}: int8 quantized serving "
                f"(--quantize-weights/--quantize-kv) serves on "
                f"single-device engines; drop the mesh or the flags")
        # Params dtype drives the dense working-cache dtype; capture it
        # BEFORE weight quantization turns leaves int8.
        self._raw_param_dtype = jax.tree.leaves(params)[0].dtype
        self._raw_param_bytes = sum(
            int(x.size) * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(params))
        if self.quantize_weights:
            from quoracle_tpu.models.quant import quantize_params
            params = quantize_params(params, cfg)
        if mesh is not None:
            from quoracle_tpu.parallel.mesh import shard_params
            params = shard_params(params, mesh, cfg)
        self.params = params
        self.tokenizer = tokenizer
        self.max_seq = max_seq or cfg.context_window
        # Sequence-parallel serving (mesh with an sp axis): prompts longer
        # than one chip's window (``sp_window``, default max_seq / sp) take
        # the ring-attention prefill path; shorter prompts stay on the
        # dense path (SURVEY §5 long-context).
        sp_size = int(mesh.shape.get("sp", 1)) if mesh is not None else 1
        self.sp_window = (sp_window if sp_window is not None
                          else (self.max_seq // sp_size if sp_size > 1
                                else None))
        self.prompt_buckets = tuple(b for b in prompt_buckets if b <= self.max_seq)
        self._rng = jax.random.PRNGKey(seed)
        self._rng_lock = named_lock("engine.rng")
        # KV cache dtype follows the params (bf16 serving, fp32 parity tests)
        # — mixing dtypes would fail the in-place cache scatter. With
        # quantized KV the POOL dtype is int8 (scales beside the pages);
        # dense working caches stay at the params dtype.
        self.cache_dtype = self._raw_param_dtype
        self.pool_dtype = jnp.int8 if self.quantize_kv else self.cache_dtype
        # Session budget in BYTES, converted to tokens for the store: per
        # cached token K+V cost 2 · L · n_kv · hd · itemsize — at 8B scale
        # that's ~128 KiB/token, so a token-denominated default would permit
        # tens of GiB of HBM before "bounding" anything. Also capped at 32
        # full context windows so tiny-KV test models don't allocate a
        # giant pool from the byte budget alone. Int8 pools count their
        # per-(token, head) scales, so resident_kv_tokens lands at ~2x
        # the bf16 figure at the same byte budget (ISSUE 13).
        from quoracle_tpu.models.quant import kv_token_bytes
        token_bytes = kv_token_bytes(
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim,
            jnp.dtype(self.pool_dtype).itemsize, self.quantize_kv)
        self.sessions = SessionStore(
            max_tokens=max(PAGE, min(session_max_bytes // token_bytes,
                                     32 * self.max_seq)))
        self.sessions.model = cfg.name     # metric label (alloc drift,
                                           # tier counters)
        # The paged steps donate the pool buffers; calls that touch the pool
        # must serialize (concurrent members use separate engines).
        self._paged_lock = named_lock("engine.paged")
        # Cross-session prefix sharing (SessionStore.match_prefix, backed
        # by the radix prefix cache in models/prefix_cache.py): ON by
        # default for full-attention models; the windowed check lives at
        # the adoption site. Tests flip it off to compare. The flag gates
        # both cache lookups and store-back inserts.
        self.prefix_sharing = True
        # Grammar-table cache has its OWN lock so sessionless calls (image
        # rows, models/runtime.py) can run concurrently with the continuous
        # batcher's sessioned chunks without serializing on _paged_lock —
        # the cache dict (build/evict) is their only shared mutable state.
        # Order: _paged_lock → _grammar_lock (sessioned path), never
        # reversed.
        self._grammar_lock = named_lock("cache.grammar")
        # Resident-size thresholds (max prompt tokens in the batch) for the
        # DIRECT (ragged-kernel) paged decode and paged PREFILL. These are
        # MEASURED gates, not constants: where the kernels win depends on
        # the deployment's launch cost (remote-dispatch relay ~2.7 ms vs
        # local-dispatch ~µs — BASELINE.md "Long-context regime"), so
        # tools/calibrate_paged.py measures the gather/direct crossover on
        # the current host and the engine loads it
        # (utils/calibration.py; env QUORACLE_PAGED_CALIB). With no
        # calibration file both paths stay off — a documented absence of
        # data. Beyond latency the direct paths cap peak HBM (no
        # [B, maxp·page] working cache), so memory-pressured deployments
        # may calibrate them on below the latency crossover.
        from quoracle_tpu.utils.calibration import (
            load_paged_gates, resolve_unified_gate,
        )
        gates = load_paged_gates()
        self.paged_gates = gates
        self.direct_decode_min_tokens = gates.decode_min_resident
        self.direct_prefill_min_tokens = gates.prefill_min_resident
        self.direct_prefill_max_chunk = gates.prefill_max_chunk
        # UNIFIED ragged kernel (ISSUE 8): ONE launch per layer for the
        # whole mixed tick — prefill suffixes, continuations, decode and
        # verify rows in one token-major grid, KV written straight to
        # pages. Unlike the direct paths this is ON by default on TPU
        # (gather becomes the measured fallback): the calibration file
        # can raise the threshold or disable it, absent key = auto
        # (0 on TPU, off elsewhere — CPU serving sticks with the fused
        # gather programs; tests force the unified path explicitly).
        self.unified_min_tokens = resolve_unified_gate(gates)
        if self.quantize_kv:
            # Quantized KV serves through the unified ragged path (the
            # kernel dequantizes in its streaming loop; the gather refs
            # are the CPU twin) — force it on regardless of platform
            # calibration; the gather programs stay the structural
            # fallback (pool exhaustion, partial boundary swaps) with
            # dequant-on-gather / requant-on-scatter.
            self.unified_min_tokens = 0
            from quoracle_tpu.infra.telemetry import (
                QUANT_KV_BYTES_PER_TOKEN,
            )
            QUANT_KV_BYTES_PER_TOKEN.set(float(token_bytes),
                                         model=cfg.name)
        if self.quantize_weights:
            from quoracle_tpu.models.quant import params_nbytes
            from quoracle_tpu.infra.telemetry import (
                QUANT_BYTES_SAVED_TOTAL,
            )
            QUANT_BYTES_SAVED_TOTAL.inc(
                max(0, self._raw_param_bytes - params_nbytes(self.params)),
                model=cfg.name, tier="weights")
        # Padding-waste accounting (ISSUE 8 satellite): per generate call
        # (one continuous-batcher tick), how many chunk-token slots the
        # device actually processed vs the tick's real tokens. Ragged
        # ticks reclaim the difference; /api/resources serves the totals.
        self.pad_real_tokens = 0
        self.pad_padded_tokens = 0
        self.pad_ticks = 0
        # Per-call hand-off from _run_unified to _record_telemetry /
        # _note_padding. THREAD-LOCAL: sessionless calls (image rows) run
        # concurrently with the batcher's sessioned chunks and must not
        # steal a unified tick's shape key or padded-token count.
        self._pending = threading.local()
        # Per-call phase diagnostics (read by the bench + dashboards):
        # wall seconds of the last prefill / decode device phases.
        self.last_prefill_s = 0.0
        self.last_decode_s = 0.0
        # Replica-tier role restriction (ISSUE 10, serving/cluster.py):
        # None = unrestricted (the monolithic default). "prefill" caps
        # every generate at ONE new token — a prefill-tier engine exists
        # to build KV and emit the first token; a longer decode on it is
        # a routing bug the guard turns into a loud error instead of a
        # silent MFU regression. "decode" is descriptive metadata only
        # (decode engines still prefill continuation suffixes).
        self.role: Optional[str] = None
        # Compile ledger (ISSUE 3): every dispatched shape bucket with
        # wall time + hit/miss counts, plus the recompile-storm window —
        # /api/resources serves its snapshot per engine.
        self.compiles = CompileRegistry(cfg.name)
        self._build_step()

    def _build_step(self):
        """Two jits per call instead of one fused step: PREFILL fills the
        cache from the prompt chunk, DECODE runs the sampling loop. The
        boundary costs one dispatch (~µs) and buys an honest per-phase
        latency split (prefill is compute-bound on the MXU, decode is
        HBM-bandwidth-bound — a single fused number hides which one
        regressed; SURVEY §5 tracing asks for the split)."""
        cfg = self.cfg
        mesh = self.mesh
        if mesh is not None:
            from jax.sharding import NamedSharding
            from quoracle_tpu.parallel.mesh import cache_spec
            kv_sharding = NamedSharding(mesh, cache_spec(cfg, mesh))

        def _constrain(cache: KVCache) -> KVCache:
            if mesh is None:
                return cache
            # Pin the cache layout (kv heads on tp, batch on dp) so the
            # decode loop carries a stable sharding instead of whatever
            # GSPMD back-propagates from the first write.
            return cache._replace(
                k=jax.lax.with_sharding_constraint(cache.k, kv_sharding),
                v=jax.lax.with_sharding_constraint(cache.v, kv_sharding))

        @functools.partial(jax.jit, static_argnames=("cache_len",))
        def step_prefill(params, tokens, prompt_lens, cache_len: int):
            B = tokens.shape[0]
            cache = _constrain(init_cache(cfg, B, cache_len,
                                          dtype=self.cache_dtype))
            return prefill(params, cfg, tokens, prompt_lens, cache)

        if mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
            ring_args = (mesh, "sp",
                         "dp" if int(mesh.shape.get("dp", 1)) > 1 else None,
                         "tp" if int(mesh.shape.get("tp", 1)) > 1 else None)

            @functools.partial(jax.jit, static_argnames=("cache_len",))
            def step_prefill_ring(params, tokens, prompt_lens,
                                  cache_len: int):
                # Long-prompt path: the prompt exceeds one chip's window,
                # so prefill attention runs sequence-parallel over the sp
                # ring; the cache stays S-sharded (cache_spec) so the full
                # KV never materializes on one chip.
                B = tokens.shape[0]
                cache = _constrain(init_cache(cfg, B, cache_len,
                                              dtype=self.cache_dtype))
                return prefill(params, cfg, tokens, prompt_lens, cache,
                               ring=ring_args)

            self._step_prefill_ring = step_prefill_ring
        else:
            self._step_prefill_ring = None

        if cfg.vision is not None:
            from quoracle_tpu.models.vision import (
                splice_image_embeds, vision_encode,
            )

            @functools.partial(jax.jit, static_argnames=("cache_len",))
            def step_prefill_vlm(params, tokens, prompt_lens, pixels,
                                 cache_len: int):
                # VLM prefill: the ViT tower runs inside the same jit as
                # the decoder prefill — projected patches replace the
                # image-placeholder tokens' embeddings (LLaVA-style soft
                # prompt; models/vision.py).
                B = tokens.shape[0]
                cache = _constrain(init_cache(cfg, B, cache_len,
                                              dtype=self.cache_dtype))
                img = vision_encode(params["vision"], cfg.vision, pixels)
                embeds = params["embed"][tokens]
                if cfg.scale_embeddings:
                    # text embeds scale BEFORE the splice: projected image
                    # features enter at the projector's own scale (standard
                    # VLM semantics — an sqrt(dim) blow-up on soft tokens
                    # would swamp every gemma-family prompt)
                    embeds = (embeds.astype(jnp.float32)
                              * (cfg.dim ** 0.5)).astype(embeds.dtype)
                embeds = splice_image_embeds(embeds, tokens, img,
                                             cfg.image_token_id)
                return prefill(params, cfg, tokens, prompt_lens, cache,
                               input_embeds=embeds)

            self._step_prefill_vlm = step_prefill_vlm
        else:
            self._step_prefill_vlm = None

        @functools.partial(jax.jit, static_argnames=("max_new",),
                           donate_argnums=(1, 2))   # cache updates in place
        def step_decode(params, k_buf, v_buf, lens, last_logits, rng,
                        temperature, top_p, active, row_limit,
                        json_table, json_state, max_new: int):
            cache = _constrain(KVCache(k=k_buf, v=v_buf, lens=lens))
            return decode(params, cfg, cache, last_logits, rng,
                          temperature, top_p, max_new, cfg.eos_token_id,
                          active=active, row_limit=row_limit,
                          pad_id=self.tokenizer.pad_id,
                          stop_ids=cfg.stop_token_ids,
                          json_table=json_table, json_state=json_state)

        KV, HD, L = cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
        page = self.sessions.page
        # Int8 KV pools (ISSUE 13): the gather programs dequantize page
        # reads into the dense working cache and requantize on scatter;
        # the unified ragged path writes int8+scale directly inside its
        # forward. ``quant`` is a trace-time constant, so the two modes
        # compile disjoint programs off one code path.
        quant = self.quantize_kv
        work_dtype = self.cache_dtype

        def _gather_work(k_pool, v_pool, k_scale, v_scale, src_pages):
            """Resident pages → dense working cache [L, B, maxp·page,
            KV, HD] (int8 pools dequantize per (token, kv-head) on the
            gather)."""
            B, maxp = src_pages.shape
            kw = k_pool[:, src_pages].reshape(L, B, maxp * page, KV, HD)
            vw = v_pool[:, src_pages].reshape(L, B, maxp * page, KV, HD)
            if not quant:
                return kw, vw
            ks = k_scale[:, src_pages].transpose(0, 1, 2, 4, 3) \
                .reshape(L, B, maxp * page, KV)
            vs = v_scale[:, src_pages].transpose(0, 1, 2, 4, 3) \
                .reshape(L, B, maxp * page, KV)
            kw = (kw.astype(jnp.float32) * ks[..., None]).astype(work_dtype)
            vw = (vw.astype(jnp.float32) * vs[..., None]).astype(work_dtype)
            return kw, vw

        def _quant_scatter(k_pool, v_pool, k_scale, v_scale, k_work,
                           v_work, dst_pages):
            """Working cache → dst pages, requantizing per (token,
            kv-head) with the shared write rule (models/quant.kv_quant);
            scales land page-structured beside the pages."""
            from quoracle_tpu.models.quant import kv_quant
            B, maxp = dst_pages.shape
            kp = k_work.reshape(L, B, maxp, page, KV, HD)
            vp = v_work.reshape(L, B, maxp, page, KV, HD)
            kq, ks = kv_quant(kp)          # ks: [L, B, maxp, page, KV]
            vq, vs = kv_quant(vp)
            k_pool = k_pool.at[:, dst_pages].set(kq, mode="drop")
            v_pool = v_pool.at[:, dst_pages].set(vq, mode="drop")
            k_scale = k_scale.at[:, dst_pages].set(
                ks.transpose(0, 1, 2, 4, 3), mode="drop")
            v_scale = v_scale.at[:, dst_pages].set(
                vs.transpose(0, 1, 2, 4, 3), mode="drop")
            return k_pool, v_pool, k_scale, v_scale
        # tp-sharded ragged kernels: each tp shard runs the single-device
        # kernel on its local heads under shard_map (heads independent, no
        # collective) — mesh engines keep the direct paths instead of
        # silently falling back to gather (VERDICT r4 item 3). Gated on
        # whole GQA groups per shard; _run_paged checks the same.
        paged_shard = None
        if (mesh is not None and int(mesh.shape.get("tp", 1)) > 1
                and cfg.n_heads % int(mesh.shape["tp"]) == 0
                and cfg.n_kv_heads % int(mesh.shape["tp"]) == 0):
            paged_shard = (mesh, "tp",
                           "dp" if int(mesh.shape.get("dp", 1)) > 1
                           else None)
        self._paged_shard = paged_shard
        # Unified ragged kernel sharding: token-major flat layout can't
        # ride a dp axis (rows interleave in one token axis), so the
        # unified path runs on single-device engines and tp-only meshes
        # (heads independent under shard_map); other meshes fall back.
        ragged_shard = None
        if (paged_shard is not None
                and int(mesh.shape.get("sp", 1)) == 1
                and int(mesh.shape.get("dp", 1)) == 1):
            ragged_shard = (mesh, "tp")
        self._ragged_shard = ragged_shard
        self._ragged_ok = mesh is None or ragged_shard is not None

        @functools.partial(jax.jit, static_argnames=())
        def step_paged_prefill(params, k_pool, v_pool, k_scale, v_scale,
                               src_pages, tokens, prefix_lens,
                               chunk_lens, kv_off):
            # Resume from the page pool: ONE in-device gather materializes
            # each row's resident prefix into the working cache (HBM→HBM at
            # full bandwidth; zero host-side data movement — the host only
            # uploaded the [B, maxp] int32 page table), then only the
            # suffix chunk runs through the stack. Int8 pools dequantize
            # on the gather (scales are None otherwise).
            B, maxp = src_pages.shape
            kw, vw = _gather_work(k_pool, v_pool, k_scale, v_scale,
                                  src_pages)
            cache = _constrain(KVCache(k=kw, v=vw,
                                       lens=jnp.zeros((B,), jnp.int32)))
            return prefill_chunk(params, cfg, tokens, prefix_lens,
                                 chunk_lens, cache, kv_off=kv_off)

        if cfg.vision is not None:
            @functools.partial(jax.jit, static_argnames=())
            def step_paged_prefill_vlm(params, k_pool, v_pool, k_scale,
                                       v_scale, src_pages,
                                       tokens, prefix_lens, chunk_lens,
                                       kv_off, pixels):
                # VLM chunk through the PAGED machinery (image-keyed
                # sessions): the ViT tower runs inside the jit and its
                # projected patches replace the chunk's placeholder ids —
                # resumed rounds take the TEXT paged prefill instead (their
                # suffix carries no placeholders), so the tower only ever
                # runs when an image is genuinely new.
                from quoracle_tpu.models.vision import (
                    splice_image_embeds, vision_encode,
                )
                B, maxp = src_pages.shape
                kw, vw = _gather_work(k_pool, v_pool, k_scale, v_scale,
                                      src_pages)
                cache = _constrain(KVCache(k=kw, v=vw,
                                           lens=jnp.zeros((B,), jnp.int32)))
                img = vision_encode(params["vision"], cfg.vision, pixels)
                embeds = params["embed"][tokens]
                if cfg.scale_embeddings:
                    embeds = (embeds.astype(jnp.float32)
                              * (cfg.dim ** 0.5)).astype(embeds.dtype)
                embeds = splice_image_embeds(embeds, tokens, img,
                                             cfg.image_token_id)
                return prefill_chunk(params, cfg, tokens, prefix_lens,
                                     chunk_lens, cache, kv_off=kv_off,
                                     input_embeds=embeds)
            self._step_paged_prefill_vlm = step_paged_prefill_vlm
        else:
            self._step_paged_prefill_vlm = None

        @functools.partial(jax.jit, static_argnames=("max_new",),
                           donate_argnums=(1, 2, 5, 6))
        def step_paged_decode(params, k_pool, v_pool, k_scale, v_scale,
                              k_work, v_work, lens,
                              dst_pages, kv_off, last_logits, rng,
                              temperature, top_p, active, row_limit,
                              json_table, json_state, max_new: int):
            cache = _constrain(KVCache(k=k_work, v=v_work, lens=lens))
            out, n_emitted, cache, jstate = decode(
                params, cfg, cache, last_logits, rng, temperature, top_p,
                max_new, cfg.eos_token_id, active=active,
                row_limit=row_limit, pad_id=self.tokenizer.pad_id,
                stop_ids=cfg.stop_token_ids, json_table=json_table,
                json_state=json_state, kv_off=kv_off)
            # Scatter prompt + response KV back into the pool pages in
            # place (pool donated → aliased update). Rows without a session
            # point every dst slot at scratch page 0. Int8 pools
            # requantize on the scatter (scales beside the pages).
            B, maxp = dst_pages.shape
            if quant:
                k_pool, v_pool, k_scale, v_scale = _quant_scatter(
                    k_pool, v_pool, k_scale, v_scale, cache.k, cache.v,
                    dst_pages)
            else:
                kp = cache.k.reshape(L, B, maxp, page, KV, HD)
                vp = cache.v.reshape(L, B, maxp, page, KV, HD)
                k_pool = k_pool.at[:, dst_pages].set(kp, mode="drop")
                v_pool = v_pool.at[:, dst_pages].set(vp, mode="drop")
            # cache.k/v returned (and discarded by the host) so the donated
            # work buffers alias an output — the decode loop then runs
            # truly in place instead of copying the working cache.
            return out, n_emitted, cache.lens, k_pool, v_pool, k_scale, \
                v_scale, cache.k, cache.v, jstate

        @functools.partial(jax.jit, static_argnames=("kmax", "need_probs"))
        def step_paged_verify(params, k_pool, v_pool, k_scale, v_scale,
                              src_pages, tokens,
                              prefix_lens, chunk_lens, kv_off, k_arr,
                              temperature, json_table, json_state,
                              kmax: int, need_probs: bool):
            # Speculative VERIFY (models/speculative.py BatchedSpeculator):
            # teacher-forced chunk forward over [pending, d_1..d_{K-1}]
            # against each row's resident paged prefix, projecting logits
            # at the last k_arr positions of every row's chunk — the
            # positions whose argmax decides draft acceptance. Same gather
            # as step_paged_prefill; the caller scatters the chunk KV back
            # to pages (step_scatter_prompt), so a committed prefix is
            # resident for the next round and rejected draft KV is just
            # dead weight the next chunk's prefill overwrites (the LCP
            # session resume IS the rollback).
            B, maxp = src_pages.shape
            kw, vw = _gather_work(k_pool, v_pool, k_scale, v_scale,
                                  src_pages)
            cache = _constrain(KVCache(k=kw, v=vw,
                                       lens=jnp.zeros((B,), jnp.int32)))
            T = tokens.shape[1]
            positions = (prefix_lens[:, None]
                         + jnp.arange(T, dtype=jnp.int32)[None, :])
            positions = positions + kv_off.astype(jnp.int32)[:, None]
            total = (prefix_lens + chunk_lens).astype(jnp.int32)
            hidden, cache = forward_hidden(
                params, cfg, tokens, positions, cache,
                write_offset=prefix_lens.astype(jnp.int32), kv_lens=total,
                kv_pos_offset=kv_off)
            cache = cache._replace(lens=total)
            # verify window = each row's last k_arr chunk positions
            widx = jnp.clip(
                chunk_lens[:, None] - k_arr[:, None]
                + jnp.arange(kmax, dtype=jnp.int32)[None, :], 0, T - 1)
            wh = jnp.take_along_axis(hidden, widx[:, :, None], axis=1)
            logits = project_logits(params, cfg, wh).astype(jnp.float32)
            if json_table is not None:
                # per-position grammar states walk IN-DEVICE from the
                # state after ctx (json_state) over the window's draft
                # tokens — the mask applied at position t equals the one
                # vanilla decode would apply there (bit-exactness).
                wtok = jnp.take_along_axis(tokens, widx, axis=1)

                def adv(s, tok):
                    nxt = json_table[jnp.clip(s, 0, None),
                                     tok].astype(jnp.int32)
                    s2 = jnp.where(s >= 0, nxt, s)
                    return s2, s2

                _, rest = jax.lax.scan(adv, json_state, wtok[:, 1:].T)
                states = jnp.concatenate(
                    [json_state[None, :], rest], axis=0).T    # [B, kmax]
                V = logits.shape[-1]
                logits = grammar_mask(
                    logits.reshape(B * kmax, V), states.reshape(-1),
                    json_table, cfg.eos_token_id).reshape(B, kmax, V)
            ids = jnp.argmax(logits, axis=-1)                 # [B, kmax]
            if need_probs:
                probs = jax.nn.softmax(
                    logits / jnp.maximum(temperature, 1e-6)[:, None, None],
                    axis=-1)
                # greedy rows in a mixed batch: one-hot keeps the host
                # acceptance rule exact (accept iff d_i == argmax p_i)
                probs = jnp.where(
                    (temperature <= 0)[:, None, None],
                    jax.nn.one_hot(ids, logits.shape[-1]), probs)
            else:
                # dead [B, kmax, V] outputs still cost HBM writes — drop
                # them in the hot greedy path (same as the v1 decoder)
                probs = jnp.zeros((1, 1, 1), jnp.float32)
            return ids, probs, cache

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step_paged_prefill_direct(params, k_pool, v_pool, src_tables,
                                      tokens, prefix_lens, chunk_lens,
                                      kv_off, flat_dst):
            # DIRECT paged prefill: the suffix chunk attends to the
            # resident prefix straight off its pages (one kernel launch
            # per layer per chunk) and its KV scatters into the dst pages
            # in place — the [B, maxp·page] working cache never
            # materializes (VERDICT r4 item 2). Pools donated: the
            # scatter aliases them.
            from quoracle_tpu.models.transformer import (
                forward_hidden_paged_prefill,
            )
            B, T = tokens.shape
            positions = ((prefix_lens + kv_off).astype(jnp.int32)[:, None]
                         + jnp.arange(T, dtype=jnp.int32)[None, :])
            hidden, k_pool, v_pool = forward_hidden_paged_prefill(
                params, cfg, tokens, positions, k_pool, v_pool,
                src_tables, prefix_lens, chunk_lens, flat_dst,
                shard=paged_shard)
            last_h = jnp.take_along_axis(
                hidden, (chunk_lens - 1)[:, None, None].astype(jnp.int32),
                axis=1)
            last = project_logits(params, cfg, last_h)[:, 0, :]
            return last, k_pool, v_pool

        @functools.partial(jax.jit, donate_argnums=(0, 1, 4, 5))
        def step_scatter_prompt(k_pool, v_pool, k_scale, v_scale, k_work,
                                v_work, dst_pages):
            # Working cache (prefix gather + suffix prefill) → dst pages,
            # BEFORE decode: the direct-decode path then reads pages only.
            # k_work/v_work are donated so the working cache's HBM frees
            # here (the memory win of the direct path) — XLA warns the
            # donation isn't aliasable into an output; that's the point,
            # it's a free, not an alias. Int8 pools requantize on the
            # scatter (scales beside the pages).
            if quant:
                return _quant_scatter(k_pool, v_pool, k_scale, v_scale,
                                      k_work, v_work, dst_pages)
            B, maxp = dst_pages.shape
            kp = k_work.reshape(L, B, maxp, page, KV, HD)
            vp = v_work.reshape(L, B, maxp, page, KV, HD)
            k_pool = k_pool.at[:, dst_pages].set(kp, mode="drop")
            v_pool = v_pool.at[:, dst_pages].set(vp, mode="drop")
            return k_pool, v_pool, k_scale, v_scale

        @functools.partial(jax.jit, static_argnames=("max_new",))
        def step_paged_decode_direct(params, k_pool, v_pool, tables,
                                     pool_lens, kv_off, last_logits, rng,
                                     temperature, top_p, active, row_limit,
                                     json_table, json_state, max_new: int):
            # Pools are READ-ONLY here (not donated): attention streams
            # pages via ops/paged_attention.py; new KV accumulates in the
            # tail buffer, scattered into pages by step_scatter_tail.
            return decode_paged(
                params, cfg, k_pool, v_pool, tables, pool_lens, kv_off,
                last_logits, rng, temperature, top_p, max_new,
                cfg.eos_token_id, active=active, row_limit=row_limit,
                pad_id=self.tokenizer.pad_id, stop_ids=cfg.stop_token_ids,
                json_table=json_table, json_state=json_state,
                tail_dtype=self.cache_dtype, shard=paged_shard)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step_scatter_tail(k_pool, v_pool, tail_k, tail_v, flat_idx):
            # tail slot t of row b → pool token slot flat_idx[b, t]
            # (host-computed; out-of-range = drop for invalid slots)
            n_tok = k_pool.shape[1] * page
            kf = k_pool.reshape(L, n_tok, KV, HD)
            vf = v_pool.reshape(L, n_tok, KV, HD)
            kf = kf.at[:, flat_idx].set(tail_k, mode="drop")
            vf = vf.at[:, flat_idx].set(tail_v, mode="drop")
            return (kf.reshape(k_pool.shape), vf.reshape(v_pool.shape))

        def _fwd_ragged(params, k_pool, v_pool, k_scale, v_scale,
                        tokens_flat, positions_flat, block_tables,
                        block_meta, flat_dst, tq):
            """The one ragged forward call both unified steps share:
            int8 pools thread their scale pools through (quantize-on-
            write inside the forward, in-kernel dequant on read)."""
            if quant:
                return forward_hidden_ragged(
                    params, cfg, tokens_flat[None], positions_flat[None],
                    k_pool, v_pool, block_tables, block_meta, flat_dst,
                    tq=tq, shard=ragged_shard,
                    k_scale=k_scale, v_scale=v_scale)
            hidden, k_pool, v_pool = forward_hidden_ragged(
                params, cfg, tokens_flat[None], positions_flat[None],
                k_pool, v_pool, block_tables, block_meta, flat_dst,
                tq=tq, shard=ragged_shard)
            return hidden, k_pool, v_pool, k_scale, v_scale

        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("tq",))
        def step_paged_ragged(params, k_pool, v_pool, k_scale, v_scale,
                              tokens_flat,
                              positions_flat, block_tables, block_meta,
                              flat_dst, last_idx, tq: int):
            # UNIFIED mixed chunk forward (ISSUE 8): one ragged launch
            # per layer over the token-major flattened tick — prefill
            # suffixes, 1-token continuations, any mix of lengths — with
            # chunk KV scattered to the rows' pages inside the forward.
            # Shapes key on (flat token budget, page-table width) only:
            # the batch-bucket × prompt-bucket program matrix collapses.
            hidden, k_pool, v_pool, k_scale, v_scale = _fwd_ragged(
                params, k_pool, v_pool, k_scale, v_scale, tokens_flat,
                positions_flat, block_tables, block_meta, flat_dst, tq)
            last_h = hidden[0][last_idx]                  # [R, D]
            last = project_logits(params, cfg, last_h[:, None])[:, 0, :]
            return last, k_pool, v_pool, k_scale, v_scale

        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("tq", "kmax", "need_probs"))
        def step_paged_ragged_verify(params, k_pool, v_pool, k_scale,
                                     v_scale, tokens_flat,
                                     positions_flat, block_tables,
                                     block_meta, flat_dst, widx,
                                     temperature, json_table, json_state,
                                     tq: int, kmax: int, need_probs: bool):
            # Speculative VERIFY through the SAME unified kernel: the
            # teacher-forced chunk rides the ragged forward (KV scattered
            # to pages — committed prefixes resident for the next round,
            # LCP resume is still the rollback) and verdict logits
            # project at the flat indices of each row's last K positions.
            hidden, k_pool, v_pool, k_scale, v_scale = _fwd_ragged(
                params, k_pool, v_pool, k_scale, v_scale, tokens_flat,
                positions_flat, block_tables, block_meta, flat_dst, tq)
            wh = hidden[0][widx]                          # [R, kmax, D]
            logits = project_logits(params, cfg, wh).astype(jnp.float32)
            R = widx.shape[0]
            if json_table is not None:
                # per-position grammar states walk in-device over the
                # window's draft tokens — identical recipe (and therefore
                # identical masks) to step_paged_verify
                wtok = tokens_flat[widx]                  # [R, kmax]

                def adv(s, tok):
                    nxt = json_table[jnp.clip(s, 0, None),
                                     tok].astype(jnp.int32)
                    s2 = jnp.where(s >= 0, nxt, s)
                    return s2, s2

                _, rest = jax.lax.scan(adv, json_state, wtok[:, 1:].T)
                states = jnp.concatenate(
                    [json_state[None, :], rest], axis=0).T
                V = logits.shape[-1]
                logits = grammar_mask(
                    logits.reshape(R * kmax, V), states.reshape(-1),
                    json_table, cfg.eos_token_id).reshape(R, kmax, V)
            ids = jnp.argmax(logits, axis=-1)             # [R, kmax]
            if need_probs:
                probs = jax.nn.softmax(
                    logits / jnp.maximum(temperature,
                                         1e-6)[:, None, None], axis=-1)
                probs = jnp.where(
                    (temperature <= 0)[:, None, None],
                    jax.nn.one_hot(ids, logits.shape[-1]), probs)
            else:
                probs = jnp.zeros((1, 1, 1), jnp.float32)
            return ids, probs, k_pool, v_pool, k_scale, v_scale

        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnames=("max_new",))
        def step_paged_decode_ragged(params, k_pool, v_pool, k_scale,
                                     v_scale, tables,
                                     pool_lens, kv_off, last_logits, rng,
                                     temperature, top_p, active,
                                     row_limit, json_table, json_state,
                                     max_new: int):
            # Decode continuation of the unified tick: KV written straight
            # to pages inside the loop (no tail buffer, no tail scatter);
            # attention is the same ragged kernel at tq=1 (int8 pools
            # quantize each step's token on write).
            res = decode_ragged(
                params, cfg, k_pool, v_pool, tables, pool_lens, kv_off,
                last_logits, rng, temperature, top_p, max_new,
                cfg.eos_token_id, active=active, row_limit=row_limit,
                pad_id=self.tokenizer.pad_id, stop_ids=cfg.stop_token_ids,
                json_table=json_table, json_state=json_state,
                shard=ragged_shard, k_scale=k_scale, v_scale=v_scale)
            if quant:
                return res
            out, n_emitted, lens, k_pool, v_pool, jstate = res
            return (out, n_emitted, lens, k_pool, v_pool, k_scale,
                    v_scale, jstate)

        self._step_paged_ragged = step_paged_ragged
        self._step_paged_ragged_verify = step_paged_ragged_verify
        self._step_paged_decode_ragged = step_paged_decode_ragged

        self._step_prefill = step_prefill
        self._step_decode = step_decode
        self._step_paged_prefill = step_paged_prefill
        self._step_paged_verify = step_paged_verify
        self._step_paged_prefill_direct = step_paged_prefill_direct
        self._step_paged_decode = step_paged_decode
        self._step_scatter_prompt = step_scatter_prompt
        self._step_paged_decode_direct = step_paged_decode_direct
        self._step_scatter_tail = step_scatter_tail

    def next_rng(self) -> jax.Array:
        with self._rng_lock:
            self._rng, k = jax.random.split(self._rng)
            return k

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        temperature: Sequence[float] | float = 1.0,
        top_p: Sequence[float] | float = 1.0,
        max_new_tokens: Sequence[int] | int = 256,
        rng: Optional[jax.Array] = None,
        session_ids: Optional[Sequence[Optional[str]]] = None,
        constrain_json: Optional[Sequence[bool]] = None,
        action_enums: Optional[Sequence[Optional[Sequence[str]]]] = None,
        images: Optional[Sequence] = None,
        initial_json_state: Optional[Sequence[Optional[int]]] = None,
        image_sessions: bool = False,
    ) -> list[GenResult]:
        """``session_ids`` (aligned with prompts; None entries opt out)
        enables KV residency: each row reuses the longest token prefix it
        shares with its session's resident cache and prefills only the
        suffix; the prompt KV is stored back for the next round. Consensus
        refinement rounds extend the previous prompt, so rounds 2+ skip
        re-prefilling the whole conversation (SURVEY §7 hard part 2).

        ``action_enums`` (aligned; only read where constrain_json is True)
        upgrades the JSON grammar to the schema-aware variant: the row's
        top-level ``"action"`` value is constrained to the given names
        (models/constrained.py action_enum).

        ``images`` (aligned; None entries = text-only row) enables the VLM
        path on vision-configured models: each entry is a preprocessed
        [H, W, 3] float array whose projected patches replace the row's
        image-placeholder tokens. By default image rows skip KV sessions
        (identical placeholder ids under different images must not
        prefix-match); ``image_sessions=True`` keeps them — the CALLER
        asserts the hazard is gone by keying each row's session id with an
        image digest (models/runtime.py does), so a resumed prefix always
        encodes the same image and VLM refinement rounds stop re-prefilling
        their whole prompt (VERDICT r3 weak #5)."""
        has_images = images is not None and any(i is not None
                                                for i in images)
        if self.role == "prefill":
            budgets = (max_new_tokens if not isinstance(
                max_new_tokens, int) else [max_new_tokens])
            if any(int(b) > 1 for b in budgets):
                raise ValueError(
                    f"engine {self.cfg.name} is a prefill-tier replica "
                    f"(role='prefill'): it builds KV and emits at most "
                    f"one token per row; route decode to a decode-tier "
                    f"replica (serving/cluster.py)")
        if has_images and self.cfg.vision is None:
            raise ValueError(f"model {self.cfg.name} has no vision tower")
        if has_images and not image_sessions:
            # Image rows opt out of sessions (identical placeholder ids
            # under different images must not prefix-match). Text rows
            # KEEP their resident prefixes: a mixed batch splits into a
            # VLM sub-batch and a (possibly paged) text sub-batch.
            txt_idx = [i for i, im in enumerate(images) if im is None]
            if txt_idx and session_ids is not None and any(
                    session_ids[i] for i in txt_idx):
                img_idx = [i for i, im in enumerate(images)
                           if im is not None]

                def pick(seq, idxs):
                    if seq is None or isinstance(seq, (int, float)):
                        return seq
                    return [seq[i] for i in idxs]

                res_img = self.generate(
                    [prompts[i] for i in img_idx],
                    pick(temperature, img_idx), pick(top_p, img_idx),
                    pick(max_new_tokens, img_idx), None, None,
                    pick(constrain_json, img_idx),
                    pick(action_enums, img_idx),
                    [images[i] for i in img_idx],
                    pick(initial_json_state, img_idx))
                res_txt = self.generate(
                    [prompts[i] for i in txt_idx],
                    pick(temperature, txt_idx), pick(top_p, txt_idx),
                    pick(max_new_tokens, txt_idx), None,
                    pick(session_ids, txt_idx),
                    pick(constrain_json, txt_idx),
                    pick(action_enums, txt_idx), None,
                    pick(initial_json_state, txt_idx))
                merged: list = [None] * len(prompts)
                for j, i in enumerate(img_idx):
                    merged[i] = res_img[j]
                for j, i in enumerate(txt_idx):
                    merged[i] = res_txt[j]
                return merged
            session_ids = None       # image-only (or sessionless) batch
        if session_ids is not None and any(session_ids):
            # Sessioned calls serialize per engine: session lookup, page
            # allocation/eviction, the pool-donating steps, and the store
            # must be one atomic unit, or a concurrent call could evict and
            # recycle pages this batch still references.
            with self._paged_lock:
                later = self._prefix_wave_split(prompts, session_ids)
                if later:
                    return self._generate_waves(
                        later, prompts, temperature, top_p, max_new_tokens,
                        rng, session_ids, constrain_json, action_enums,
                        images, initial_json_state)
                return self._generate_impl(
                    prompts, temperature, top_p, max_new_tokens, rng,
                    session_ids, constrain_json, action_enums, images,
                    initial_json_state)
        return self._generate_impl(prompts, temperature, top_p,
                                   max_new_tokens, rng, session_ids,
                                   constrain_json, action_enums, images,
                                   initial_json_state)

    def _prefix_wave_split(self, prompts, session_ids) -> list[int]:
        """Intra-batch prefix dedup (the consensus fan-out shape: K new
        agent sessions arrive in ONE batch sharing the built system/task
        prompt): rows that would re-prefill a page-aligned prefix another
        row of the SAME batch is about to prefill — and that the radix
        cache does not cover yet — are deferred to a SECOND wave, which
        then adopts the first wave's freshly cached pages. The shared
        prompt prefills once; rows 2..K prefill only their suffix.
        Returns the deferred row indices ([] = single wave)."""
        if (not self.prefix_sharing or session_ids is None
                or self.cfg.sliding_window is not None
                or self.cfg.vision is not None):
            return []
        st = self.sessions
        page = st.page
        first: list[int] = []
        later: list[int] = []
        from collections import Counter
        sid_counts = Counter(s for s in session_ids if s)
        with st.lock:
            seen: set = set()
            for i, sid in enumerate(session_ids):
                if not sid or sid in seen:
                    continue        # sessionless / duplicate-sid rows
                seen.add(sid)
                if sid_counts[sid] > 1:
                    # duplicated sid in one batch: deferring the first
                    # occurrence would hand the session to the duplicate —
                    # keep the existing first-occurrence-owns semantics
                    continue
                if st._sessions.get(sid) is not None:
                    continue        # resident: resumes off its own pages
                cap = len(prompts[i]) - 1
                best = 0
                for j in first:
                    l = min(_lcp(prompts[j], prompts[i]), cap)
                    best = max(best, (l // page) * page)
                # defer only when waiting gains >= 1 full page over what
                # the cache would already serve this row today
                if (best >= page and
                        st.prefix_cache.match_len(prompts[i], cap)
                        < best):
                    later.append(i)
                else:
                    first.append(i)
        return later

    def _generate_waves(self, later, prompts, temperature, top_p,
                        max_new_tokens, rng, session_ids, constrain_json,
                        action_enums, images, initial_json_state):
        """Two-wave sessioned generate (caller holds _paged_lock): wave 1
        prefills the batch's unique prefixes and stores them (radix-cache
        inserts included), wave 2 runs the deferred duplicate-prefix rows,
        which now adopt those pages and prefill only their suffixes.
        Phase/telemetry fields accumulate across both waves."""
        n = len(prompts)
        later_set = set(later)
        first_idx = [i for i in range(n) if i not in later_set]

        def pick(seq, idxs):
            if seq is None or isinstance(seq, (int, float)):
                return seq
            return [seq[i] for i in idxs]

        rng1 = rng2 = None
        if rng is not None:
            rng1, rng2 = jax.random.split(rng)

        def run(idxs, wave_rng):
            return self._generate_impl(
                [prompts[i] for i in idxs], pick(temperature, idxs),
                pick(top_p, idxs), pick(max_new_tokens, idxs), wave_rng,
                pick(session_ids, idxs), pick(constrain_json, idxs),
                pick(action_enums, idxs),
                pick(images, idxs) if images is not None else None,
                pick(initial_json_state, idxs))

        res1 = run(first_idx, rng1)
        w1 = (self.last_prefill_tokens, self.last_prefill_s,
              self.last_decode_s)
        res2 = run(later, rng2)
        self.last_prefill_tokens += w1[0]
        self.last_prefill_s += w1[1]
        self.last_decode_s += w1[2]
        merged: list = [None] * n
        for j, i in enumerate(first_idx):
            merged[i] = res1[j]
        for j, i in enumerate(later):
            merged[i] = res2[j]
        return merged

    def kv_signature(self) -> str:
        """The engine's exact KV geometry + dtype as a string: the disk
        prefix store's directory key AND the cross-replica handoff
        compatibility check (serving/handoff.py) — two engines may only
        exchange KV bytes when their signatures match exactly."""
        cfg = self.cfg
        # Quantized KV is part of the signature (ISSUE 13): a
        # quantized↔unquantized peer pair must reject handoff BEFORE any
        # bytes move (and never share a disk-store directory) — the
        # degrade is a cold re-prefill, exactly the version-skew path.
        # Unquantized engines keep the historic signature unchanged.
        return (f"{cfg.name.replace('/', '_')}-L{cfg.n_layers}"
                f"x{cfg.n_kv_heads}x{cfg.head_dim}-p{self.sessions.page}"
                f"-{jnp.dtype(self.pool_dtype).name}"
                + ("-q8kv" if self.quantize_kv else ""))

    def attach_tier(self, host_mb: int = 256,
                    disk_dir: Optional[str] = None,
                    disk_gb: float = 8.0):
        """Enable tiered KV (ISSUE 7, serving/kvtier.py): HBM eviction
        demotes to a ``host_mb``-bounded host page store, touches restore
        by page-in, and (with ``disk_dir``) prefix-cache blocks persist
        to a checksummed disk store — ``disk_gb``-bounded, oldest-LRU
        entries pruned — that warm-starts the next process. The disk
        signature binds entries to this engine's exact KV geometry and
        dtype, so mismatched processes can never exchange bytes.
        Returns the TierManager (also at ``sessions.tier``)."""
        from quoracle_tpu.serving.kvtier import TierManager
        cfg = self.cfg
        tier = TierManager(self.sessions, model=cfg.name,
                           host_mb=host_mb, disk_dir=disk_dir,
                           paged_lock=self._paged_lock,
                           signature=self.kv_signature(),
                           disk_gb=disk_gb)
        self.sessions.tier = tier
        return tier

    def prefetch_session(self, session_id: str) -> bool:
        """Warm a hibernated session before its owner needs it (the
        scheduler/agent-tick prefetch hook, ISSUE 7): restore it by
        page-in if it sits in the host tier. TRY-acquires the paged lock
        — a busy engine skips the warm-up rather than blocking the
        caller; the sessioned generate path restores synchronously
        anyway, so prefetch is purely an overlap optimization."""
        tier = self.sessions.tier
        if tier is None or not tier.has_session(session_id):
            return False
        if self.sessions.get(session_id) is not None:
            return False                  # already resident
        if not self._paged_lock.acquire(blocking=False):
            return False
        try:
            self._ensure_pool()
            return tier.restore_session(session_id) is not None
        finally:
            self._paged_lock.release()

    def drop_session(self, session_id: str) -> None:
        """Release a session's pages — including any image-digest-qualified
        variants ("<sid>|img:<sha>", models/runtime.py VLM sessions).
        Serialized with sessioned generate calls so an in-flight batch
        never loses pages it references."""
        with self._paged_lock:
            self.sessions.drop(session_id)
            prefix = session_id + "|img:"
            for key in [k for k in self.sessions._sessions
                        if k.startswith(prefix)]:
                self.sessions.drop(key)
            tier = self.sessions.tier
            if tier is not None:
                # digest-keyed variants may live ONLY in the host tier
                # (hibernated) — discard those too, or a dead agent's
                # image sessions linger until host-LRU
                for key in [k for k in tier.host.sessions
                            if k.startswith(prefix)]:
                    tier.discard_session(key)

    def session_tokens(self, session_id: str) -> Optional[list[int]]:
        """The session's resident conversation ids (host ints, prompt +
        retained response), or None. Callers use these to SPLICE the next
        round's prompt (splice_session_prompt) so its token prefix matches
        the resident KV exactly. Snapshot copy: generate replaces the
        _Session object wholesale, never mutates tokens in place.
        Hibernated sessions answer from the host tier — the splice works
        against the hibernated ids and the generate then restores the
        pages (tokens are host ints in either tier)."""
        s = self.sessions.get(session_id)
        if s is not None:
            return list(s.tokens)
        tier = self.sessions.tier
        if tier is not None:
            return tier.peek_tokens(session_id)
        return None

    def verify_chunk(self, prompts, session_ids, verify_k, *,
                     temperature=0.0, constrain_json=None,
                     action_enums=None, initial_json_state=None,
                     need_probs: bool = False) -> list[dict]:
        """Speculative VERIFY against the paged session KV (the target
        side of models/speculative.py BatchedSpeculator): each row i's
        prompt is ctx_i + proposals_i[:-1] and ``verify_k[i]`` =
        len(proposals_i); ONE teacher-forced chunk forward resumes the
        row's session (LCP prefix reuse, exactly like generate) and
        returns the target's verdict at the K_i positions that predict
        proposals_i — ``ids`` (grammar-masked argmax per position) plus
        ``probs`` ([K_i, V] masked softmax) when ``need_probs``. The
        chunk KV is stored back to the session's pages, so the session
        afterwards holds the full prompt; rejected draft KV past the
        committed prefix is overwritten by the next round's suffix
        prefill (LCP resume IS the rollback — no explicit cache surgery).

        ``initial_json_state`` is the row's grammar state after ctx_i
        (the scheduler's relative-state convention). Every row must be
        sessioned; speculative serving never runs on sliding-window or
        vision engines (the BatchedSpeculator enforces eligibility)."""
        assert session_ids is not None and all(session_ids), \
            "verify_chunk requires a session per row"
        assert len(verify_k) == len(prompts)
        assert all(1 <= int(k) <= len(p)
                   for k, p in zip(verify_k, prompts))
        with self._paged_lock:
            return self._generate_impl(
                prompts, temperature, 1.0, 1, None, session_ids,
                constrain_json, action_enums, None, initial_json_state,
                verify=([int(k) for k in verify_k], bool(need_probs)))

    def _generate_impl(self, prompts, temperature=1.0, top_p=1.0,
                       max_new_tokens=256, rng=None, session_ids=None,
                       constrain_json=None, action_enums=None,
                       images=None,
                       initial_json_state=None, verify=None):
        t0 = time.monotonic()
        n = len(prompts)
        if n == 0:
            return []
        vk = verify[0] if verify is not None else None
        temps = [temperature] * n if isinstance(temperature, (int, float)) else list(temperature)
        tops = [top_p] * n if isinstance(top_p, (int, float)) else list(top_p)
        # Per-row decode budgets: consensus rows grouped into one batch keep
        # their own caps (traced row limits; the static bound is the max).
        if isinstance(max_new_tokens, int):
            row_budgets = [max_new_tokens] * n
        else:
            row_budgets = [int(m) for m in max_new_tokens]
            assert len(row_budgets) == n

        max_prompt = max(len(p) for p in prompts)
        if max_prompt >= self.max_seq:
            # The context layer (condensation) is responsible for fitting
            # prompts; a prompt at/over the window is a caller bug, parallel
            # to the reference's context-overflow error path
            # (per_model_query.ex:93-120) — loud, never silent garbage.
            raise ContextOverflowError(
                f"prompt of {max_prompt} tokens >= max_seq {self.max_seq} "
                f"for model {self.cfg.name}")

        # Session prefix lookup: how much of each prompt is already
        # resident in the page pool. ``reuse_abs`` counts ABSOLUTE tokens
        # reused; the row's buffer-index prefix is reuse_abs - start_pos
        # (sliding-window sessions trim leading pages, offsetting the
        # buffer). A session id appearing twice in one batch would collide
        # on its pages — later duplicates run sessionless.
        # Long-prompt sequence-parallel path: prompts beyond one chip's
        # window ring-prefill over sp. Sessions don't compose with the
        # S-sharded ring layout yet — such rows run a full fresh prefill.
        use_ring = (self._step_prefill_ring is not None
                    and self.sp_window is not None
                    and max_prompt > self.sp_window)

        sess_rows: list[Optional[_Session]] = [None] * n
        reuse_abs = [0] * n
        kv_off_host = [0] * n
        store_sids: list[Optional[str]] = [None] * n
        paged = False
        if session_ids is not None and not use_ring:
            seen: set[str] = set()
            for i, sid in enumerate(session_ids):
                if not sid or sid in seen:
                    continue
                seen.add(sid)
                store_sids[i] = sid
                paged = True
                s = self.sessions.get(sid)
                if s is None and self.sessions.tier is not None \
                        and self.sessions.tier.has_session(sid):
                    # hibernated session: restore by page-in instead of
                    # re-prefill (ISSUE 7; the caller holds _paged_lock,
                    # so the pool scatter cannot race a paged step). A
                    # restore failure of ANY kind degrades to re-prefill
                    # — the tier is never a correctness dependency.
                    try:
                        self._ensure_pool()
                        s = self.sessions.tier.restore_session(sid)
                    except Exception:     # noqa: BLE001 — fall back
                        import logging
                        logging.getLogger(__name__).exception(
                            "kv restore failed for %s; re-prefilling",
                            sid)
                        s = None
                if s is None:
                    # Cross-session prefix sharing: a NEW session whose
                    # prompt starts with a RADIX-CACHED page-aligned
                    # prefix (same system prompt across the tree's
                    # agents; models/prefix_cache.py) adopts those pages
                    # read-only — _run_paged refcount-acquires them and
                    # uses them as this row's dst prefix, so only the
                    # suffix prefills.
                    if (self.prefix_sharing
                            and self.cfg.sliding_window is None
                            # VLM engines: identical placeholder token
                            # ids can front DIFFERENT images — adopting
                            # another session's prefix KV would condition
                            # on the wrong image (the digest-keyed
                            # session safeguard, models/runtime.py)
                            and self.cfg.vision is None):
                        t_pl = time.monotonic()
                        # verify mode: the last K_i positions are the
                        # verify window and must run through the chunk
                        # forward — never be served from reused KV
                        cap = (len(prompts[i]) - 1 if vk is None
                               else len(prompts[i]) - vk[i])
                        if self.sessions.tier is not None:
                            # tiered lookup may page disk/host blocks
                            # into the pool — it must exist first
                            self._ensure_pool()
                        d = (self.sessions.match_prefix(prompts[i], cap)
                             if cap > 0 else None)
                        PREFIX_LOOKUP_MS.observe(
                            (time.monotonic() - t_pl) * 1000,
                            model=self.cfg.name)
                        if d is not None:
                            sess_rows[i] = d
                            reuse_abs[i] = len(d.tokens)
                            kv_off_host[i] = 0
                    continue
                # ≥1 suffix token must run to produce last-position logits
                # (verify mode: the whole K_i window must run — see above)
                p = min(_lcp(s.tokens, prompts[i]),
                        len(prompts[i]) - 1 if vk is None
                        else len(prompts[i]) - vk[i])
                if self.cfg.sliding_window is not None and p < len(s.tokens):
                    # Windowed models resume only on clean extension: after
                    # a divergence the resident window [start_pos, p) would
                    # leave a hole below the new tokens' attention windows.
                    continue
                if p > s.start_pos:
                    sess_rows[i] = s
                    reuse_abs[i] = p
                    kv_off_host[i] = s.start_pos

        prefixes = [r - o for r, o in zip(reuse_abs, kv_off_host)]  # buffer
        suffixes = [list(p[r:]) for p, r in zip(prompts, reuse_abs)]
        max_chunk = max(len(s) for s in suffixes)
        # verify chunks are K-token windows (steady state K ≤ 8, plus the
        # occasional full re-prefill after eviction) — padding them to the
        # 128-floor prompt buckets would forward 16-20x the needed
        # positions per round. The verify jit is its own program, so the
        # extra small buckets cost no compile churn on the main prefill.
        T = _round_up(max_chunk,
                      tuple(sorted({8, 16, 32, 64,
                                    *self.prompt_buckets}))
                      if vk is not None else self.prompt_buckets)
        if use_ring:
            sp = int(self.mesh.shape["sp"])
            T = ((T + sp - 1) // sp) * sp   # ring shards the chunk evenly
        B = _round_up(n, self.BATCH_BUCKETS)
        if self.mesh is not None:
            # batch rows ride the dp axis — pad the bucket to a multiple
            dp = int(self.mesh.shape.get("dp", 1))
            B = ((B + dp - 1) // dp) * dp
        # Bucket the decode bound too: consensus computes a DYNAMIC max_tokens
        # per round (reference per_model_query.ex:136-145), which would
        # otherwise trigger one XLA compile per unique value. Per-row TRACED
        # limits stop each row at its own budget, so bucketing costs nothing.
        max_new = _round_up(min(max(row_budgets), self.max_seq - 1),
                            (64, 128, 256, 512, 1024, 2048, 4096))
        # The padded chunk is written at write_offset=prefix_i, so the
        # buffer must cover max(prefix) + T (the full padded extent, NOT
        # just max prompt length): dynamic_update_slice CLAMPS start
        # indices, and an under-sized buffer would silently scribble the
        # pad region over valid prefix KV.
        cache_len = _round_up(max(prefixes) + T,
                              self.prompt_buckets) + max_new
        page = self.sessions.page
        maxp = -(-cache_len // page)      # pages per row (paged path)
        if paged:
            cache_len = maxp * page

        tokens = np.full((B, T), self.tokenizer.pad_id, np.int32)
        pre_arr = np.zeros((B,), np.int32)
        off_arr = np.zeros((B,), np.int32)
        chunk_arr = np.ones((B,), np.int32)  # padded rows: 1 (harmless)
        limits = np.ones((B,), np.int32)
        for i, s in enumerate(suffixes):
            tokens[i, :len(s)] = s
            pre_arr[i] = prefixes[i]
            off_arr[i] = kv_off_host[i]
            chunk_arr[i] = max(1, len(s))
            total = max(1, len(prompts[i]))
            limits[i] = max(1, min(row_budgets[i], self.max_seq - total))
        temp_arr = np.zeros((B,), np.float32)
        temp_arr[:n] = temps
        top_arr = np.ones((B,), np.float32)
        top_arr[:n] = tops
        active = np.zeros((B,), bool)
        active[:n] = True

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            row = NamedSharding(self.mesh, P("dp"))
            mat = NamedSharding(self.mesh, P("dp", None))
            put = lambda a, s: jax.device_put(a, s)
        else:
            row = mat = None
            put = lambda a, s: jnp.asarray(a)
        rng_key = rng if rng is not None else self.next_rng()
        samp = (put(temp_arr, row), put(top_arr, row),
                put(active, row), put(limits, row))

        # JSON grammar constraint: rows flagged True start in their
        # grammar's start state; -1 rows sample unconstrained. Rows may
        # carry different action enums — distinct grammars stack into one
        # table with offset state ids.
        grammar_bases = None
        if constrain_json is not None and any(constrain_json):
            enums = [None] * n
            if action_enums is not None:
                enums = [tuple(sorted(set(e))) if e else None
                         for e in action_enums]
            distinct = sorted({e for e, f in zip(enums, constrain_json)
                               if f},
                              key=lambda e: (e is not None, e or ()))
            table, offsets, bases = self._json_table_device(tuple(distinct))
            grammar_bases = [bases.get(e, 0) for e in enums]
            jstate = np.full((B,), -1, np.int32)
            for i, flag in enumerate(constrain_json):
                if flag:
                    # resume a mid-stream grammar state (chunked
                    # continuation): states travel RELATIVE to their
                    # grammar's block base, so they survive different
                    # table stackings across calls
                    init_js = (initial_json_state[i]
                               if initial_json_state is not None else None)
                    if init_js is not None and init_js >= 0:
                        jstate[i] = grammar_bases[i] + init_js
                    else:
                        jstate[i] = offsets[enums[i]]
            json_args = (table, put(jstate, row))
            jstate_np = jstate
        else:
            json_args = (None, None)
            jstate_np = None

        vrun = None
        if verify is not None:
            # verify is paged by construction (every row sessioned) and
            # never rides the sp ring (BatchedSpeculator eligibility)
            assert paged and not use_ring, \
                "verify_chunk requires the paged session path"
            k_arr = np.ones((B,), np.int32)
            k_arr[:n] = vk
            vrun = (k_arr, _round_up(max(vk), (4, 8, 16)), verify[1])
        if paged:
            out, n_emitted, jstate_f, t_prefill, now, vout = \
                self._run_paged(
                    prompts, suffixes, sess_rows, reuse_abs, kv_off_host,
                    store_sids, B, maxp, tokens, pre_arr, off_arr,
                    chunk_arr, limits, rng_key, samp, json_args, max_new,
                    put, mat, row, t0, verify=vrun,
                    samp_np=(temp_arr, top_arr, active, limits),
                    jstate_np=jstate_np)
        else:
            if images is not None and any(i is not None for i in images):
                vc = self.cfg.vision
                pixels = np.zeros((B, vc.image_size, vc.image_size, 3),
                                  np.float32)
                for i, img in enumerate(images):
                    if img is not None:
                        pixels[i] = np.asarray(img, np.float32)
                last_logits, cache = self._step_prefill_vlm(
                    self.params, put(tokens, mat), put(chunk_arr, row),
                    jnp.asarray(pixels), cache_len=cache_len)
            else:
                step_pre = (self._step_prefill_ring if use_ring
                            else self._step_prefill)
                last_logits, cache = step_pre(
                    self.params, put(tokens, mat), put(chunk_arr, row),
                    cache_len=cache_len)
            jax.block_until_ready(last_logits)  # phase fence: prefill done
            t_prefill = time.monotonic()
            out, n_emitted, _, jstate_f = self._step_decode(
                self.params, cache.k, cache.v, cache.lens, last_logits,
                rng_key, *samp, *json_args, max_new=max_new)
            out = np.asarray(out)
            n_emitted = np.asarray(n_emitted)
            jstate_f = np.asarray(jstate_f)
            now = time.monotonic()
        self.last_prefill_tokens = sum(len(s) for s in suffixes)
        self.last_prefill_s = t_prefill - t0
        self.last_decode_s = now - t_prefill
        latency = now - t0
        # Padding-waste telemetry (ISSUE 8 satellite): chunk-token slots
        # the device processed this tick vs the tick's real tokens. The
        # unified path overrides the [B, T] rectangle with its flat token
        # budget (_run_unified sets the thread-local).
        padded_toks = getattr(self._pending, "padded_tokens", None)
        self._pending.padded_tokens = None
        self._note_padding(sum(max(1, len(s)) for s in suffixes),
                           B * T if padded_toks is None else padded_toks)
        # Chip-economics charge (ISSUE 17): split each phase's measured
        # wall across the live rows by real tokens; padding waste lands
        # on the overhead pseudo-tenant. Read-only — consumes the row
        # keys the batcher declared on this thread, touches no RNG or
        # device state.
        from quoracle_tpu.infra import costobs
        chip_ms_rows = costobs.charge_step(
            self, n=n,
            prefill_weights=([max(1, len(s)) for s in suffixes[:n]]
                             if vrun is None else [int(k) for k in vk]),
            decode_weights=[int(n_emitted[i]) for i in range(n)],
            padded_prefill=(B * T if padded_toks is None
                            else padded_toks),
            padded_decode=(B * vrun[1] if vrun is not None
                           else B * max_new),
            cache_len=cache_len, verify=vrun is not None,
            prefill_bucket=vrun[1] if vrun is not None else T,
            decode_bucket=max_new)
        # Liveness heartbeat (ISSUE 18): tokens the device actually
        # produced this call — a frozen counter under live rows is the
        # stall detector's engine-level signal.
        from quoracle_tpu.infra import introspect
        introspect.beat(f"engine.tokens:{self.cfg.name}",
                        sum(int(n_emitted[i]) for i in range(n)))
        self._record_telemetry(n, B, T, cache_len,
                               vrun[1] if vrun is not None else max_new,
                               "verify" if vrun is not None else paged,
                               n_emitted, latency)

        if verify is not None:
            vids, vprobs = vout
            return [{
                # window position t predicts proposals[t]; valid verdicts
                # are the first K_i entries (kmax padding is garbage)
                "ids": [int(x) for x in vids[i, :vk[i]]],
                "probs": (np.asarray(vprobs[i, :vk[i]], np.float32)
                          if vprobs is not None else None),
                "n_cached": reuse_abs[i],
                "chip_ms": chip_ms_rows[i],
            } for i in range(n)]

        results = []
        for i in range(n):
            # Extract by emitted COUNT, not by sentinel scan: pad_id may be a
            # real vocab token in HF checkpoints.
            k = min(int(n_emitted[i]), row_budgets[i])
            ids = [int(t) for t in out[i, :k]]
            finish = "length"
            stop_set = {self.cfg.eos_token_id, *self.cfg.stop_token_ids}
            if ids and ids[-1] in stop_set:
                ids.pop()
                finish = "stop"
            results.append(GenResult(
                token_ids=ids,
                text=self.tokenizer.decode(ids),
                n_prompt_tokens=len(prompts[i]),
                n_gen_tokens=len(ids),
                latency_s=latency,
                finish_reason=finish,
                n_cached_tokens=reuse_abs[i],
                json_state=(int(jstate_f[i]) - grammar_bases[i]
                            if constrain_json is not None
                            and constrain_json[i] else -1),
                chip_ms=chip_ms_rows[i],
            ))
        return results

    def _record_telemetry(self, n: int, B: int, T: int, cache_len: int,
                          max_new: int, paged: bool, n_emitted,
                          latency: float) -> None:
        """Per-call histogram observations + first-shape (JIT compile)
        events for this generate (infra/telemetry.py): device phase
        latencies, per-wave prefill token throughput, per-emitted-token
        decode time. Pure observation — no RNG, no device work — so
        temp-0 outputs are bit-identical with telemetry sinks on or off.
        A shape key unseen by this engine marks the call as a first-call
        compile (the wall time is compile-dominated unless the persistent
        XLA cache already held the executable)."""
        name = self.cfg.name
        PREFILL_MS.observe(self.last_prefill_s * 1000, model=name)
        DECODE_MS.observe(self.last_decode_s * 1000, model=name)
        if self.last_prefill_s > 0 and self.last_prefill_tokens:
            PREFILL_TOKENS_PER_S.observe(
                self.last_prefill_tokens / self.last_prefill_s, model=name)
        steps = max((int(n_emitted[i]) for i in range(n)), default=0)
        if steps > 0 and self.last_decode_s > 0:
            DECODE_STEP_MS.observe(self.last_decode_s * 1000 / steps,
                                   model=name)
        # The unified ragged path keys its programs on (flat token budget,
        # page-table width, decode bound) — _run_paged stashes that exact
        # key so CompileRegistry ledgers the REAL program identity (and
        # the tier-1 collapse assertion can count it), not the meaningless
        # [B, T] rectangle the flat layout never compiles.
        shape = getattr(self._pending, "shape_key", None)
        self._pending.shape_key = None
        if shape is None:
            shape = (B, T, cache_len, max_new, paged)
        if self.compiles.record(shape, latency * 1000):
            JIT_COMPILES.inc(model=name)
            if self.quantize_kv:
                # the dequant path's program identity (ISSUE 13): a
                # storm here is the quantized twin of a compile storm
                from quoracle_tpu.infra.telemetry import (
                    QUANT_DEQUANT_COMPILES_TOTAL,
                )
                QUANT_DEQUANT_COMPILES_TOTAL.inc(model=name)
            TRACER.emit(
                "generate.first_shape_compile", latency * 1000,
                model=name, phase="compile",
                shape=f"B{B}xT{T}xC{cache_len}xN{max_new}"
                      + ("p" if paged else ""))

    def _note_padding(self, real: int, padded: int) -> None:
        """Account one tick's chunk-token padding waste (ISSUE 8
        satellite): ``real`` tokens the caller actually submitted vs
        ``padded`` device slots the chosen path processed ([B·T] for the
        bucketed paths, the flat token budget for the unified kernel).
        Counters feed Prometheus; the cumulative totals ride
        /api/resources via padding_stats()."""
        from quoracle_tpu.infra.telemetry import (
            SCHED_PAD_WASTE_RATIO, SCHED_PADDED_TOKENS_TOTAL,
            SCHED_REAL_TOKENS_TOTAL,
        )
        name = self.cfg.name
        self.pad_real_tokens += int(real)
        self.pad_padded_tokens += int(padded)
        self.pad_ticks += 1
        SCHED_REAL_TOKENS_TOTAL.inc(int(real), model=name)
        SCHED_PADDED_TOKENS_TOTAL.inc(int(padded), model=name)
        SCHED_PAD_WASTE_RATIO.set(
            (padded - real) / padded if padded else 0.0, model=name)

    def padding_stats(self) -> dict:
        """Cumulative padding-waste view for /api/resources: what
        raggedness reclaims, quantified per engine."""
        padded = self.pad_padded_tokens
        return {
            "ticks": self.pad_ticks,
            "real_tokens": self.pad_real_tokens,
            "padded_tokens": padded,
            "waste_ratio": (round(1 - self.pad_real_tokens / padded, 4)
                            if padded else None),
        }

    def kv_token_pool_bytes(self) -> int:
        """Pool bytes per resident KV token (int8 payload + scales when
        quantized; plain cache bytes otherwise) — the shared byte rate
        for resources attribution, /api/kv compression and planning."""
        from quoracle_tpu.models.quant import kv_token_bytes
        return kv_token_bytes(
            self.cfg.n_layers, self.cfg.n_kv_heads, self.cfg.head_dim,
            jnp.dtype(self.pool_dtype).itemsize, self.quantize_kv)

    def quant_stats(self) -> dict:
        """The member's quantization posture for /api/kv and bench
        config 19: mode flags, the per-token KV byte rate vs the bf16
        rate, and the resulting compression ratio."""
        bf16_rate = (2 * self.cfg.n_layers * self.cfg.n_kv_heads
                     * self.cfg.head_dim
                     * jnp.dtype(self.cache_dtype).itemsize)
        rate = self.kv_token_pool_bytes()
        return {
            "quantize_weights": self.quantize_weights,
            "quantize_kv": self.quantize_kv,
            "kv_bytes_per_token": rate,
            "kv_bytes_per_token_bf16": bf16_rate,
            "kv_compression": round(bf16_rate / rate, 3) if rate else None,
            "resident_kv_tokens": self.sessions.max_tokens,
        }

    def _ensure_pool(self) -> None:
        """Allocate the device page pool on first sessioned call (engines
        that never see sessions never pay for it). Quantized-KV engines
        allocate int8 pools plus the page-structured fp32 scale pools
        ([L, n_pages, KV, page] — a page's scales are one contiguous
        block that tier moves carry beside the page)."""
        st = self.sessions
        if st.k is not None:
            return
        shape = (self.cfg.n_layers, st.n_pages, st.page,
                 self.cfg.n_kv_heads, self.cfg.head_dim)
        k = jnp.zeros(shape, self.pool_dtype)
        v = jnp.zeros(shape, self.pool_dtype)
        if self.quantize_kv:
            sshape = (self.cfg.n_layers, st.n_pages,
                      self.cfg.n_kv_heads, st.page)
            st.k_scale = jnp.ones(sshape, jnp.float32)
            st.v_scale = jnp.ones(sshape, jnp.float32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            tp = int(self.mesh.shape.get("tp", 1))
            kv_axis = "tp" if self.cfg.n_kv_heads % tp == 0 else None
            sh = NamedSharding(self.mesh, P(None, None, None, kv_axis, None))
            k, v = jax.device_put(k, sh), jax.device_put(v, sh)
        st.k, st.v = k, v

    def _run_paged(self, prompts, suffixes, sess_rows, reuse_abs,
                   kv_off_host, store_sids, B, maxp, tokens, pre_arr,
                   off_arr, chunk_arr, limits, rng_key, samp, json_args,
                   max_new, put, mat, row, t0, verify=None, samp_np=None,
                   jstate_np=None):
        """The paged-session call: gather resident pages in-device, prefill
        the suffix, decode, scatter prompt+response KV back to pages, then
        update session page lists host-side (ints only — no KV bytes move
        through the host). The CALLER holds self._paged_lock for the whole
        sessioned generate — lookup, allocation, the pool-donating steps,
        and the store are one atomic unit."""
        n = len(prompts)
        st = self.sessions
        page = st.page
        self._ensure_pool()
        src = np.zeros((B, maxp), np.int32)
        dst = np.zeros((B, maxp), np.int32)
        dst_lists: list[Optional[list[int]]] = [None] * n
        temp_lists: list[Optional[list[int]]] = [None] * n
        spills: list[list[int]] = [[] for _ in range(n)]
        protect = tuple(s for s in store_sids if s)
        # DIRECT paged decode (ops/paged_attention.py) vs gather decode.
        # The ragged kernel costs one pallas launch per LAYER per token, so
        # wherever launch overhead exceeds the gather path's padded KV
        # reads the fused gather decode is faster (measured: 656 → 1640 ms
        # per bench config-1 round at ~1k tokens; still 2.3× slower at 16k
        # resident, batch 1 — tools/bench_longctx.py). The kernel's wins
        # are peak-HBM (no [B, maxp·page] working cache) and very large
        # ragged batches; the gate compares the batch's max RESIDENT
        # (prompt) tokens against direct_decode_min_tokens (measured gate,
        # utils/calibration.py — see __init__). tp meshes run the kernel
        # per-shard via shard_map (_paged_shard, whole GQA groups per
        # shard required); other meshes (sp rings, non-divisible heads)
        # gather. _force_gather_decode is the equality-test seam
        # (tests/test_paged_kv.py).
        mesh_ok = (self.mesh is None
                   or (self._paged_shard is not None
                       and int(self.mesh.shape.get("sp", 1)) == 1))
        use_direct = (mesh_ok
                      and verify is None      # verify is a chunk forward,
                                              # not a decode loop
                      and not getattr(self, "_force_gather_decode", False)
                      # quantized KV serves through the UNIFIED kernel
                      # (in-kernel dequant); the split direct kernels
                      # have no scale stream
                      and not self.quantize_kv
                      and max(len(p) for p in prompts)
                      >= self.direct_decode_min_tokens)
        # UNIFIED ragged kernel (ISSUE 8) — the default serving path on
        # TPU: prefill suffixes, continuations, decode steps and verify
        # windows all dispatch through ONE token-major kernel, KV written
        # straight to pages. Eligibility mirrors the direct paths' page
        # discipline (every prefix-reusing row must read/write its OWN dst
        # pages — there is no gather/scatter to relocate a prefix), plus
        # the flat layout's mesh constraint (dp can't shard interleaved
        # rows). _force_gather_decode is the shared equality/fallback
        # seam; the per-engine threshold comes from the calibration file
        # (utils/calibration.py resolve_unified_gate).
        unified_ok = (self._ragged_ok
                      and not getattr(self, "_force_gather_decode", False)
                      and samp_np is not None
                      and max(len(p) for p in prompts)
                      >= self.unified_min_tokens)
        adopted_release: list[list[int]] = [[] for _ in range(n)]
        partial_swap = [False]      # a swapped boundary page forces the
                                    # gather prefill (see below)
        with st.lock:   # one allocation transaction for the batch
            # Refcount-acquire every adopted donor prefix FIRST: an alloc
            # below may LRU-evict the donor mid-transaction, and the
            # adopted pages must survive until this call's steps have
            # consumed (or stored) them.
            for i in range(n):
                s = sess_rows[i]
                if s is not None and s.shared_prefix:
                    st.acquire(s.pages)
                    adopted_release[i] = list(s.pages)
            for i in range(n):
                s = sess_rows[i]
                if s is not None:
                    # pages beyond this call's table width hold KV past the
                    # reusable prefix — never gathered (prefix <= maxp·page)
                    k = min(len(s.pages), maxp)
                    src[i, :k] = s.pages[:k]
                if store_sids[i] is None:
                    continue
                # dst reuses the STORED session's pages even when the
                # prefix-reuse decision declined them (e.g. windowed
                # divergence): their content is dead either way, and
                # put_raw replacing the session must not leak them.
                stored = st._sessions.get(store_sids[i])
                old = list(stored.pages) if stored is not None else []
                if (stored is None and s is not None and s.shared_prefix):
                    # adopted prefix pages become this row's dst prefix:
                    # the scatter rewrites them with byte-identical values
                    # (the gathered prefix), and the stored session then
                    # OWNS the reference acquired above
                    old = list(s.pages)
                    adopted_release[i] = []
                # resident pages past the table width can't be rewritten
                # this call: release them after the batch runs
                spills[i], old = old[maxp:], old[:maxp]
                # SHARED pages are writable only inside the row's
                # identical-prefix region (the scatter rewrites that part
                # with the gathered, byte-identical values). A shared page
                # past it — a diverged/condensed conversation whose prefix
                # shrank below a page some adopter still reads — would be
                # rewritten with DIFFERENT values (the gather-path scatter
                # writes EVERY dst slot): swap ones this call needs for
                # fresh pages, and drop ones past ``need`` from dst
                # entirely (they would only be garbage-scattered and then
                # released at store-back).
                pre_buf = reuse_abs[i] - kv_off_host[i]
                safe_full = pre_buf // page
                need_tokens = min(
                    pre_buf + len(suffixes[i]) + int(limits[i]),
                    maxp * page)
                need = -(-need_tokens // page)
                tail_shared = [pg for j, pg in enumerate(old)
                               if j >= need and st._refs.get(pg, 1) > 1]
                if tail_shared:
                    old = [pg for j, pg in enumerate(old)
                           if not (j >= need
                                   and st._refs.get(pg, 1) > 1)]
                shared_beyond = [j for j, pg in enumerate(old)
                                 if safe_full <= j < need
                                 and st._refs.get(pg, 1) > 1]
                # Swapping the PARTIALLY-reused boundary page leaves a
                # dst hole the direct-prefill path would never fill (it
                # writes only chunk positions >= pre_buf; the gather
                # scatter covers everything) — force the gather prefill
                # for this batch when that happens.
                if any(j == safe_full and pre_buf % page
                       for j in shared_beyond):
                    partial_swap[0] = True
                if shared_beyond:
                    # copy-on-write: the divergent rewrite lands on fresh
                    # pages; the shared copies (radix cache / adopters)
                    # keep their content (prefix_cache.py invariant I2)
                    st.prefix_cache.note_cow(len(shared_beyond))
                n_extra = max(0, need - len(old)) + len(shared_beyond)
                if n_extra:
                    extra = st.alloc(n_extra, protect=protect)
                    if extra is None:
                        # pool exhausted even after eviction: serve the
                        # row without storing (old session stays valid).
                        # An adopted prefix reverts to read-only use: its
                        # reference releases after the steps run.
                        store_sids[i] = None
                        spills[i] = []
                        if s is not None and s.shared_prefix:
                            adopted_release[i] = list(s.pages)
                        continue
                    for j in shared_beyond:
                        st._release([old[j]])   # our ref; adopters keep
                        old[j] = extra.pop()
                    old = old + extra
                st._release(tail_shared)        # our refs; adopters keep
                dst_lists[i] = old
                dst[i, :len(old)] = old
            if use_direct or unified_ok:
                # The direct AND unified paths read every row's prompt
                # from pages, so rows without a stored session need TEMP
                # pages for this call. Exhaustion falls back to gather.
                for i in range(n):
                    if dst_lists[i] is not None:
                        continue
                    need_tokens = min(len(suffixes[i]) + int(limits[i])
                                      + int(pre_arr[i]), maxp * page)
                    # free-list only: scratch pages that die at call end
                    # must not evict other agents' resident sessions
                    tmp = st.alloc(-(-need_tokens // page),
                                   protect=protect, evict=False)
                    if tmp is None:
                        use_direct = False
                        unified_ok = False
                        break
                    temp_lists[i] = tmp
                    dst[i, :len(tmp)] = tmp
                if not use_direct and not unified_ok:
                    for i, tmp in enumerate(temp_lists):
                        if tmp:
                            st._release(tmp)
                        temp_lists[i] = None

        # DIRECT paged prefill composes with the direct decode only (the
        # gather decode needs the working cache the direct prefill exists
        # to skip): suffix chunks attend to resident pages in place, chunk
        # KV scatters to dst pages, and the decode then reads pages — no
        # [B, maxp·page] materialization anywhere in the call. Gated by
        # the measured calibration (utils/calibration.py) + a chunk-size
        # cap (the intra-chunk piece is dense O(T²)).
        T = tokens.shape[1]
        use_direct_pre = (
            use_direct
            and not getattr(self, "_force_gather_prefill", False)
            and max(len(p) for p in prompts) >= self.direct_prefill_min_tokens
            and T <= self.direct_prefill_max_chunk
            # every prefix-reusing row must write through its OWN session
            # pages (dst prefix == src prefix, so the resident KV is
            # already where the decode will read it). A row whose store
            # was declined (pool exhaustion) reuses a prefix but targets
            # TEMP pages — its prefix would never reach dst; gather
            # handles that batch instead.
            and all(sess_rows[i] is None or dst_lists[i] is not None
                    for i in range(n))
            # a swapped shared BOUNDARY page left a dst hole only the
            # full gather scatter fills (prefix sharing divergence)
            and not partial_swap[0])

        # Final unified-kernel eligibility: every prefix-reusing row must
        # read its prefix from the SAME dst pages the kernel writes (no
        # gather exists to relocate it), and a swapped shared boundary
        # page leaves a hole only the gather scatter fills.
        use_unified = (unified_ok and not partial_swap[0]
                       and all(sess_rows[i] is None
                               or dst_lists[i] is not None
                               for i in range(n)))

        vout = None
        if use_unified:
            (out, n_emitted, final_lens, jstate_f, vout, t_prefill,
             now) = self._run_unified(
                 n, suffixes, dst, pre_arr, off_arr, chunk_arr,
                 samp_np, jstate_np, json_args[0], rng_key, max_new,
                 maxp, verify)
        elif verify is not None:
            # Speculative verify: ONE teacher-forced chunk forward with
            # window logits (no decode loop). The chunk KV scatters back
            # to the rows' own pages so committed tokens are resident for
            # the next round; rejected-draft KV past the commit point is
            # dead weight the next LCP resume overwrites.
            k_arr, kmax, need_probs = verify
            vids, vprobs, cache = self._step_paged_verify(
                self.params, st.k, st.v, st.k_scale, st.v_scale,
                put(src, mat), put(tokens, mat),
                put(pre_arr, row), put(chunk_arr, row), put(off_arr, row),
                put(k_arr, row), samp[0], json_args[0], json_args[1],
                kmax=kmax, need_probs=need_probs)
            jax.block_until_ready(vids)   # phase fence: chunk forward done
            t_prefill = time.monotonic()
            st.k, st.v, st.k_scale, st.v_scale = self._step_scatter_prompt(
                st.k, st.v, st.k_scale, st.v_scale, cache.k, cache.v,
                put(dst, mat))
            cache = None   # k/v donated to the scatter; HBM freed
            vout = (np.asarray(vids),
                    np.asarray(vprobs) if need_probs else None)
            jax.block_until_ready(st.k)
            now = time.monotonic()
            out = np.zeros((B, 0), np.int32)
            n_emitted = np.zeros((B,), np.int32)
            jstate_f = np.full((B,), -1, np.int32)
            final_lens = pre_arr + chunk_arr
        elif use_direct_pre:
            n_tok = st.n_pages * page
            flat = np.full((B, T), n_tok, np.int32)   # OOB sentinel = drop
            for i in range(n):
                n_chunk = min(len(suffixes[i]) or 1,
                              maxp * page - int(pre_arr[i]))
                pos = int(pre_arr[i]) + np.arange(max(0, n_chunk))
                flat[i, :len(pos)] = dst[i, pos // page] * page + pos % page
            last_logits, st.k, st.v = self._step_paged_prefill_direct(
                self.params, st.k, st.v, put(src, mat), put(tokens, mat),
                put(pre_arr, row), put(chunk_arr, row), put(off_arr, row),
                put(flat, mat))
            cache = None
            pool_lens_dev = put(pre_arr + chunk_arr, row)
            jax.block_until_ready(last_logits)  # phase fence: prefill done
            t_prefill = time.monotonic()
        else:
            last_logits, cache = self._step_paged_prefill(
                self.params, st.k, st.v, st.k_scale, st.v_scale,
                put(src, mat), put(tokens, mat),
                put(pre_arr, row), put(chunk_arr, row), put(off_arr, row))
            jax.block_until_ready(last_logits)  # phase fence: prefill done
            t_prefill = time.monotonic()

        if use_unified or verify is not None:
            pass          # handled above (unified runs its own decode)
        elif use_direct:
            # prompt KV → pages (unless the direct prefill already wrote
            # them there), free the working cache, decode straight off the
            # pool (ragged paged attention), then scatter only the
            # generated tail back.
            if not use_direct_pre:
                pool_lens_dev = cache.lens
                st.k, st.v, st.k_scale, st.v_scale = \
                    self._step_scatter_prompt(
                        st.k, st.v, st.k_scale, st.v_scale, cache.k,
                        cache.v, put(dst, mat))
                cache = None  # drop host refs: k/v donated above, HBM freed
            out, n_emitted, final_lens, tail_k, tail_v, jstate_f = \
                self._step_paged_decode_direct(
                    self.params, st.k, st.v, put(dst, mat), pool_lens_dev,
                    put(off_arr, row), last_logits, rng_key, *samp,
                    *json_args, max_new=max_new)
            out = np.asarray(out)
            n_emitted = np.asarray(n_emitted)
            jstate_f = np.asarray(jstate_f)
            lens_host = np.asarray(final_lens)
            pool_lens_host = np.asarray(pool_lens_dev)
            flat = np.full((B, tail_k.shape[2]), st.n_pages * page,
                           np.int32)          # OOB sentinel = dropped
            for i in range(n):
                n_tail = int(lens_host[i]) - int(pool_lens_host[i])
                if n_tail <= 0:
                    continue
                pos = int(pool_lens_host[i]) + np.arange(n_tail)
                pos = pos[pos < maxp * page]
                flat[i, :len(pos)] = dst[i, pos // page] * page + pos % page
            st.k, st.v = self._step_scatter_tail(
                st.k, st.v, tail_k, tail_v, jnp.asarray(flat))
            # the scatter belongs to this call's decode phase: sync before
            # stamping, or its device time leaks into the NEXT call's
            # prefill fence and skews the bench's phase split
            jax.block_until_ready(st.k)
            now = time.monotonic()
        else:
            (out, n_emitted, final_lens, st.k, st.v, st.k_scale,
             st.v_scale, _, _, jstate_f) = \
                self._step_paged_decode(
                    self.params, st.k, st.v, st.k_scale, st.v_scale,
                    cache.k, cache.v, cache.lens,
                    put(dst, mat), put(off_arr, row), last_logits, rng_key,
                    *samp, *json_args, max_new=max_new)
            out = np.asarray(out)
            n_emitted = np.asarray(n_emitted)
            jstate_f = np.asarray(jstate_f)
            now = time.monotonic()

        lens_host = np.asarray(final_lens)
        for i in range(n):
            sid, pages = store_sids[i], dst_lists[i]
            if sid is None or pages is None:
                continue
            valid = int(lens_host[i])            # buffer tokens with KV
            used = max(1, -(-valid // page))
            st.release(spills[i])
            st.release(pages[used:])
            pages = pages[:used]
            start = kv_off_host[i]
            abs_valid = start + valid
            plen = len(prompts[i])
            toks = list(prompts[i]) + [
                int(t) for t in out[i, :abs_valid - plen]]
            W = self.cfg.sliding_window
            if W is not None and valid - W >= page:
                # bound the resident footprint to the attention window
                drop = (valid - W) // page
                st.release(pages[:drop])
                pages = pages[drop:]
                start += drop * page
            # put_raw: page lifecycle handled explicitly above (the old
            # session's pages are all in dst_lists + spills, so the
            # releases above cover exactly the no-longer-referenced ones)
            st.put_raw(sid, _Session(tokens=toks, pages=pages,
                                     start_pos=start))
            # Radix prefix cache insert: every FULL page of the stored
            # conversation (prompt + retained response KV) becomes
            # adoptable by future sessions. Windowed/trimmed sessions are
            # excluded (their pages don't start at position 0) and VLM
            # engines never share (image hazard, see the lookup site).
            # verify-mode store-backs carry unverified DRAFT tokens at the
            # tail — correct to resume from (token-keyed LCP) but not
            # worth polluting the shared prefix cache with
            if (self.prefix_sharing and start == 0
                    and self.cfg.sliding_window is None
                    and self.cfg.vision is None and verify is None):
                st.insert_prefix(toks, pages)
        # temp pages (direct decode for sessionless rows) die with the call
        for tmp in temp_lists:
            if tmp:
                st.release(tmp)
        # adopted-prefix references that no stored session took over
        # (read-only adoption, or a declined store) release now — the
        # steps above have consumed the pages
        for pages in adopted_release:
            if pages:
                st.release(pages)
        return out, n_emitted, jstate_f, t_prefill, now, vout

    def _run_unified(self, n, suffixes, dst, pre_arr, off_arr, chunk_arr,
                     samp_np, jstate_np, json_table, rng_key,
                     max_new, maxp, verify):
        """One UNIFIED ragged tick (ISSUE 8): lay every row's suffix out
        token-major (segments padded to RAGGED_TQ blocks so a block never
        spans rows), run ONE mixed chunk forward through the ragged
        kernel — KV written straight to each row's dst pages — then
        either project verify-window verdicts or continue into the
        ragged decode loop. Device work and compile keys scale with the
        tick's real tokens (the flat budget), never with batch × max:
        program identity is ("ragged", token budget, table width,
        decode bound), which CompileRegistry ledgers for the collapse
        assertion. Returns (out, n_emitted, final_lens, jstate_f, vout,
        t_prefill, now) with all row-indexed arrays sized [NB] whose
        first ``n`` slots are the batch rows in order."""
        st = self.sessions
        page = st.page
        page_cap = maxp * page
        n_tok = st.n_pages * page
        TQ = RAGGED_TQ
        segs, nb_rows = [], []
        for i in range(n):
            s = max(1, min(int(chunk_arr[i]), page_cap - int(pre_arr[i])))
            segs.append(s)
            nb_rows.append(-(-s // TQ))
        raw = sum(b * TQ for b in nb_rows)
        TB = _round_up(raw, RAGGED_TOKEN_BUCKETS)
        if TB == raw and raw > RAGGED_TOKEN_BUCKETS[-1]:
            TB = -(-raw // 4096) * 4096     # beyond the ladder: 4k steps
        NB = TB // TQ                       # blocks; also the row slots
        maxp_p2 = 1 << max(0, maxp - 1).bit_length()   # pow2 table width
        pad_id = self.tokenizer.pad_id
        flat_tok = np.full((TB,), pad_id, np.int32)
        flat_pos = np.zeros((TB,), np.int32)
        flat_dst = np.full((TB,), n_tok, np.int32)     # OOB = drop
        btab = np.zeros((NB, maxp_p2), np.int32)
        bmeta = np.zeros((NB, 3), np.int32)            # kv_len, qpos0, nq
        last_idx = np.zeros((NB,), np.int32)
        r_tables = np.zeros((NB, maxp_p2), np.int32)
        r_pool_lens = np.zeros((NB,), np.int32)
        r_off = np.zeros((NB,), np.int32)
        temp_arr, top_arr, active, limits_np = samp_np
        r_temp = np.zeros((NB,), np.float32)
        r_top = np.ones((NB,), np.float32)
        r_active = np.zeros((NB,), bool)
        r_limits = np.ones((NB,), np.int32)
        r_temp[:n] = temp_arr[:n]
        r_top[:n] = top_arr[:n]
        r_active[:n] = active[:n]
        r_limits[:n] = limits_np[:n]
        js_dev = None
        if json_table is not None:
            r_jstate = np.full((NB,), -1, np.int32)
            r_jstate[:n] = jstate_np[:n]
            js_dev = jnp.asarray(r_jstate)
        if verify is not None:
            k_arr, kmax, need_probs = verify
            widx = np.zeros((NB, kmax), np.int32)
        cur = 0
        for i in range(n):
            s, nb = segs[i], nb_rows[i]
            pre = int(pre_arr[i])
            toks = suffixes[i][:s]
            flat_tok[cur:cur + len(toks)] = toks
            pos = pre + np.arange(s, dtype=np.int32)
            flat_pos[cur:cur + s] = int(off_arr[i]) + pos
            flat_dst[cur:cur + s] = dst[i, pos // page] * page + pos % page
            kv_len = pre + s
            for b in range(nb):
                blk = cur // TQ + b
                btab[blk, :maxp] = dst[i]
                bmeta[blk, 0] = kv_len
                bmeta[blk, 1] = pre + b * TQ
                bmeta[blk, 2] = min(TQ, s - b * TQ)
            last_idx[i] = cur + s - 1
            r_tables[i, :maxp] = dst[i]
            r_pool_lens[i] = kv_len
            r_off[i] = int(off_arr[i])
            if verify is not None:
                widx[i] = cur + np.clip(
                    s - int(k_arr[i]) + np.arange(kmax, dtype=np.int32),
                    0, s - 1)
            cur += nb * TQ
        self._pending.padded_tokens = TB

        if verify is not None:
            self._pending.shape_key = ("ragged_verify", TB, maxp_p2, kmax)
            (vids, vprobs, st.k, st.v, st.k_scale,
             st.v_scale) = self._step_paged_ragged_verify(
                self.params, st.k, st.v, st.k_scale, st.v_scale,
                jnp.asarray(flat_tok),
                jnp.asarray(flat_pos), jnp.asarray(btab),
                jnp.asarray(bmeta), jnp.asarray(flat_dst),
                jnp.asarray(widx), jnp.asarray(r_temp), json_table,
                js_dev, tq=TQ, kmax=kmax, need_probs=need_probs)
            jax.block_until_ready(vids)  # phase fence: chunk forward done
            t_prefill = time.monotonic()
            vout = (np.asarray(vids),
                    np.asarray(vprobs) if need_probs else None)
            jax.block_until_ready(st.k)
            now = time.monotonic()
            out = np.zeros((NB, 0), np.int32)
            n_emitted = np.zeros((NB,), np.int32)
            jstate_f = np.full((NB,), -1, np.int32)
            return (out, n_emitted, r_pool_lens, jstate_f, vout,
                    t_prefill, now)

        self._pending.shape_key = ("ragged", TB, maxp_p2, max_new)
        last_logits, st.k, st.v, st.k_scale, st.v_scale = \
            self._step_paged_ragged(
                self.params, st.k, st.v, st.k_scale, st.v_scale,
                jnp.asarray(flat_tok),
                jnp.asarray(flat_pos), jnp.asarray(btab),
                jnp.asarray(bmeta),
                jnp.asarray(flat_dst), jnp.asarray(last_idx), tq=TQ)
        jax.block_until_ready(last_logits)  # phase fence: prefill done
        t_prefill = time.monotonic()
        (out, n_emitted, final_lens, st.k, st.v, st.k_scale, st.v_scale,
         jstate_f) = \
            self._step_paged_decode_ragged(
                self.params, st.k, st.v, st.k_scale, st.v_scale,
                jnp.asarray(r_tables),
                jnp.asarray(r_pool_lens), jnp.asarray(r_off), last_logits,
                rng_key, jnp.asarray(r_temp), jnp.asarray(r_top),
                jnp.asarray(r_active), jnp.asarray(r_limits), json_table,
                js_dev, max_new=max_new)
        out = np.asarray(out)
        n_emitted = np.asarray(n_emitted)
        jstate_f = np.asarray(jstate_f)
        final_lens = np.asarray(final_lens)
        jax.block_until_ready(st.k)
        now = time.monotonic()
        return out, n_emitted, final_lens, jstate_f, None, t_prefill, now

    def _json_table_device(self, enum_set: tuple):
        """Lazily build + cache grammar tables for this tokenizer (one
        vocab walk per distinct grammar, a few hundred ms; then
        device-resident int16). ``enum_set`` is the tuple of DISTINCT
        action enums present in the batch (None = plain JSON); returns
        (stacked table, {enum: start-state offset into it}). Single-grammar
        batches (the common case) hit a per-enum device cache; mixed
        batches additionally cache the stacked result. Guarded by
        _grammar_lock: sessionless image calls share this cache with the
        batcher thread's sessioned chunks (dict eviction mid-read would
        corrupt)."""
        with self._grammar_lock:
            return self._json_table_device_impl(enum_set)

    def _json_table_device_impl(self, enum_set: tuple):
        from quoracle_tpu.models.constrained import JsonTokenTable
        if not hasattr(self, "_json_cache"):
            self._json_cache: dict = {}

        def _evict(kind: str, keep: int) -> None:
            # Bounded cache: device tables are padded_states × vocab int16
            # (tens-to-hundreds of MB at 128k vocab); agents with varied
            # capability sets must not accumulate tables until HBM OOM.
            # dict preserves insertion order → drop oldest first.
            keys = [k for k in self._json_cache if k[0] == kind]
            for k in keys[:max(0, len(keys) - keep)]:
                del self._json_cache[k]

        def build(enum):
            key = ("one", enum)
            if key not in self._json_cache:
                tt = JsonTokenTable.for_tokenizer(
                    self.tokenizer,
                    # vocab per the MODEL (logit width), padding beyond the
                    # tokenizer's ids stays rejected
                    self.cfg.vocab_size, self.cfg.eos_token_id,
                    extra_stop_ids=tuple(self.cfg.stop_token_ids),
                    action_enum=enum)
                self._json_cache[key] = tt
            return self._json_cache[key]

        if len(enum_set) == 1:
            tt = build(enum_set[0])
            dkey = ("dev", enum_set[0])
            if dkey not in self._json_cache:
                _evict("dev", keep=3)
                _evict("one", keep=7)
                self._json_cache[dkey] = jnp.asarray(tt.table)
            # third element: each grammar's state-block BASE — states
            # relative to it are portable across calls with different
            # stackings (chunked continuation, models/scheduler.py)
            return (self._json_cache[dkey], {enum_set[0]: tt.start_state},
                    {enum_set[0]: 0})
        skey = ("stack", enum_set)
        if skey not in self._json_cache:
            _evict("stack", keep=1)
            _evict("one", keep=7)
            tables, offsets, bases, off = [], {}, {}, 0
            for enum in enum_set:
                tt = build(enum)
                shifted = tt.table.astype(np.int32)
                shifted = np.where(shifted >= 0, shifted + off, REJECT_STATE)
                tables.append(shifted.astype(np.int16))
                offsets[enum] = off + tt.start_state
                bases[enum] = off
                off += tt.table.shape[0]
            assert off < 32767, "stacked grammar state space exceeds int16"
            self._json_cache[skey] = (jnp.asarray(np.concatenate(tables)),
                                      offsets, bases)
        return self._json_cache[skey]
