"""Batched prefill + decode: the generate step that replaces the reference's
per-model HTTPS fan-out (reference lib/quoracle/models/model_query.ex:88-131,
Task.async per model -> ReqLLM.generate_text). A consensus round here is ONE
batched call per pool member with per-row sampling params.

Functional core (this file) is pure and jit-compiled; the stateful Engine
handles padding, shape-bucketing (to bound recompiles), RNG, and
detokenization. Decode runs a ``lax.while_loop`` with static bounds and
early-exits when every row has emitted EOS — shape-static, data-dependent
only in trip count, exactly what XLA wants.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quoracle_tpu.models.config import ModelConfig
from quoracle_tpu.models.sampling import sample_tokens
from quoracle_tpu.models.transformer import (
    KVCache, forward_hidden, init_cache, project_logits,
)


def prefill(params: dict, cfg: ModelConfig, tokens: jax.Array,
            prompt_lens: jax.Array, cache: KVCache) -> tuple[jax.Array, KVCache]:
    """Fill the cache from right-padded prompts. Returns (last-token logits
    [B, V], cache with lens = prompt_lens).

    The head projection happens AFTER gathering each row's last hidden state —
    projecting the full [B, T, vocab] tensor first would cost ~4 GB/row fp32
    at llama-3-8b scale for values that are immediately discarded."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    hidden, cache = forward_hidden(
        params, cfg, tokens, positions, cache,
        write_offset=jnp.zeros((B,), jnp.int32),
        kv_lens=prompt_lens,
    )
    last_h = jnp.take_along_axis(
        hidden, (prompt_lens - 1)[:, None, None].astype(jnp.int32), axis=1)
    last = project_logits(params, cfg, last_h)[:, 0, :]
    return last, cache._replace(lens=prompt_lens.astype(jnp.int32))


def decode(
    params: dict,
    cfg: ModelConfig,
    cache: KVCache,
    first_logits: jax.Array,   # [B, V] logits at the last prompt token
    rng: jax.Array,
    temperature: jax.Array,    # [B]
    top_p: jax.Array,          # [B]
    max_new: int,
    eos_id: int,
    active: jax.Array,         # [B] bool — False for batch-bucket padding rows
    row_limit: jax.Array,      # [B] int32 per-row generation budget (<= max_new)
    pad_id: int = 0,
    stop_ids: tuple = (),      # extra stop ids (llama-3 <|eot_id|> style)
) -> tuple[jax.Array, jax.Array]:
    """Autoregressive decode.

    Returns (tokens [B, max_new], n_emitted [B]) where n_emitted counts real
    tokens written per row INCLUDING a terminal EOS. The count is tracked in
    the loop carry — output extraction must not scan for sentinels, because
    pad_id can be a legitimate vocab token in real checkpoints.

    ``max_new`` is the STATIC loop/buffer bound (shape-bucketed for compile
    caching); ``row_limit`` is the TRACED per-row budget — min(requested
    max_new_tokens, context_window - prompt_len). A row stops at EOS or at
    its limit, so bucketing never costs extra forward steps and no row's
    positions run past the context window. Padding rows (``~active``) start
    done, so the early-exit fires when every REAL row has finished.
    """
    B = first_logits.shape[0]
    stops = jnp.asarray((eos_id,) + tuple(stop_ids), jnp.int32)

    def is_stop(tok):
        return jnp.any(tok[:, None] == stops[None, :], axis=1)

    rng, k0 = jax.random.split(rng)
    tok0 = sample_tokens(first_logits, k0, temperature, top_p)
    n0 = jnp.where(active, 1, 0).astype(jnp.int32)
    done0 = ~active | is_stop(tok0) | (n0 >= row_limit)
    out0 = jnp.full((B, max_new), pad_id, jnp.int32).at[:, 0].set(tok0)

    def cond(carry):
        i, done, *_ = carry
        return (i < max_new) & ~jnp.all(done)

    def body(carry):
        i, done, cur, out, n_emitted, cache, rng = carry
        positions = cache.lens[:, None]
        hidden, cache = forward_hidden(
            params, cfg, cur[:, None], positions, cache,
            write_offset=cache.lens, kv_lens=cache.lens + 1,
        )
        logits = project_logits(params, cfg, hidden)
        rng, k = jax.random.split(rng)
        nxt = sample_tokens(logits[:, 0, :], k, temperature, top_p)
        nxt = jnp.where(done, pad_id, nxt)
        out = jax.lax.dynamic_update_slice_in_dim(out, nxt[:, None], i, axis=1)
        n_emitted = n_emitted + jnp.where(done, 0, 1).astype(jnp.int32)
        cache = cache._replace(lens=cache.lens + jnp.where(done, 0, 1))
        done = done | is_stop(nxt) | (n_emitted >= row_limit)
        return (i + 1, done, nxt, out, n_emitted, cache, rng)

    # Feed the first sampled token through the loop starting at step 1.
    init = (jnp.asarray(1, jnp.int32), done0, tok0, out0, n0, cache, rng)
    _, done, _, out, n_emitted, cache, _ = jax.lax.while_loop(cond, body, init)
    return out, n_emitted


def _round_up(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n


class ContextOverflowError(ValueError):
    """Prompt does not fit the model's context window. The condensation layer
    catches this and retries after condensing (reference semantics:
    per_model_query.ex:93-120 retry-on-context-overflow)."""


@dataclasses.dataclass
class GenResult:
    token_ids: list[int]
    text: str
    n_prompt_tokens: int
    n_gen_tokens: int
    latency_s: float
    finish_reason: str  # "stop" | "length"


class GenerateEngine:
    """Stateful serving wrapper around the functional core for ONE model.

    Holds params (device-resident), compiles (prefill+decode) per shape
    bucket, and exposes a list-in/list-out generate(). The pool runtime
    (models/runtime.py) owns one Engine per pool member.

    With ``mesh`` set, the engine serves SHARDED: params placed per
    parallel/mesh.param_specs (Megatron-style tp), the KV cache constrained
    to cache_spec, and inputs laid out on the dp axis — GSPMD inserts the
    psums, which ride ICI (SURVEY.md §2.9 tp-sharded serving). A pool on a
    multi-chip slice gives each member its own sub-mesh
    (parallel.mesh.pool_submeshes) and the host scheduler overlaps members
    (models/runtime.py). mesh=None is the single-chip degenerate case.

    generate() is thread-safe: the host-side RNG draw is locked; everything
    else is functional.
    """

    BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)

    def __init__(self, cfg: ModelConfig, params: dict, tokenizer,
                 max_seq: Optional[int] = None, seed: int = 0,
                 prompt_buckets: Sequence[int] = (128, 256, 512, 1024, 2048, 4096, 8192),
                 mesh=None):
        import threading
        self.cfg = cfg
        self.mesh = mesh
        if mesh is not None:
            from quoracle_tpu.parallel.mesh import shard_params
            params = shard_params(params, mesh, cfg)
        self.params = params
        self.tokenizer = tokenizer
        self.max_seq = max_seq or cfg.context_window
        self.prompt_buckets = tuple(b for b in prompt_buckets if b <= self.max_seq)
        self._rng = jax.random.PRNGKey(seed)
        self._rng_lock = threading.Lock()
        # KV cache dtype follows the params (bf16 serving, fp32 parity tests)
        # — mixing dtypes would fail the in-place cache scatter.
        self.cache_dtype = jax.tree.leaves(params)[0].dtype
        self._step = self._build_step()

    def _build_step(self):
        cfg = self.cfg
        mesh = self.mesh
        if mesh is not None:
            from jax.sharding import NamedSharding
            from quoracle_tpu.parallel.mesh import cache_spec
            kv_sharding = NamedSharding(mesh, cache_spec(cfg, mesh))

        @functools.partial(jax.jit, static_argnames=("max_new", "cache_len"))
        def step(params, tokens, prompt_lens, rng, temperature, top_p, active,
                 row_limit, max_new: int, cache_len: int):
            B = tokens.shape[0]
            cache = init_cache(cfg, B, cache_len, dtype=self.cache_dtype)
            if mesh is not None:
                # Pin the cache layout (kv heads on tp, batch on dp) so the
                # decode loop carries a stable sharding instead of whatever
                # GSPMD back-propagates from the first write.
                cache = cache._replace(
                    k=jax.lax.with_sharding_constraint(cache.k, kv_sharding),
                    v=jax.lax.with_sharding_constraint(cache.v, kv_sharding))
            last_logits, cache = prefill(params, cfg, tokens, prompt_lens, cache)
            out, n_emitted = decode(params, cfg, cache, last_logits, rng,
                                    temperature, top_p, max_new, cfg.eos_token_id,
                                    active=active, row_limit=row_limit,
                                    pad_id=self.tokenizer.pad_id,
                                    stop_ids=cfg.stop_token_ids)
            return out, n_emitted

        return step

    def next_rng(self) -> jax.Array:
        with self._rng_lock:
            self._rng, k = jax.random.split(self._rng)
            return k

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        temperature: Sequence[float] | float = 1.0,
        top_p: Sequence[float] | float = 1.0,
        max_new_tokens: Sequence[int] | int = 256,
        rng: Optional[jax.Array] = None,
    ) -> list[GenResult]:
        t0 = time.monotonic()
        n = len(prompts)
        if n == 0:
            return []
        temps = [temperature] * n if isinstance(temperature, (int, float)) else list(temperature)
        tops = [top_p] * n if isinstance(top_p, (int, float)) else list(top_p)
        # Per-row decode budgets: consensus rows grouped into one batch keep
        # their own caps (traced row limits; the static bound is the max).
        if isinstance(max_new_tokens, int):
            row_budgets = [max_new_tokens] * n
        else:
            row_budgets = [int(m) for m in max_new_tokens]
            assert len(row_budgets) == n

        max_prompt = max(len(p) for p in prompts)
        if max_prompt >= self.max_seq:
            # The context layer (condensation) is responsible for fitting
            # prompts; a prompt at/over the window is a caller bug, parallel
            # to the reference's context-overflow error path
            # (per_model_query.ex:93-120) — loud, never silent garbage.
            raise ContextOverflowError(
                f"prompt of {max_prompt} tokens >= max_seq {self.max_seq} "
                f"for model {self.cfg.name}")
        T = _round_up(max_prompt, self.prompt_buckets)
        B = _round_up(n, self.BATCH_BUCKETS)
        if self.mesh is not None:
            # batch rows ride the dp axis — pad the bucket to a multiple
            dp = int(self.mesh.shape.get("dp", 1))
            B = ((B + dp - 1) // dp) * dp
        # Bucket the decode bound too: consensus computes a DYNAMIC max_tokens
        # per round (reference per_model_query.ex:136-145), which would
        # otherwise trigger one XLA compile per unique value. Per-row TRACED
        # limits stop each row at its own budget, so bucketing costs nothing.
        max_new = _round_up(min(max(row_budgets), self.max_seq - 1),
                            (64, 128, 256, 512, 1024, 2048, 4096))

        tokens = np.full((B, T), self.tokenizer.pad_id, np.int32)
        lens = np.ones((B,), np.int32)  # padded rows get length 1 (harmless)
        limits = np.ones((B,), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
            lens[i] = max(1, len(p))
            limits[i] = max(1, min(row_budgets[i], self.max_seq - lens[i]))
        temp_arr = np.zeros((B,), np.float32)
        temp_arr[:n] = temps
        top_arr = np.ones((B,), np.float32)
        top_arr[:n] = tops
        active = np.zeros((B,), bool)
        active[:n] = True

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            row = NamedSharding(self.mesh, P("dp"))
            mat = NamedSharding(self.mesh, P("dp", None))
            put = jax.device_put
            args = (put(tokens, mat), put(lens, row))
            samp = (put(temp_arr, row), put(top_arr, row),
                    put(active, row), put(limits, row))
        else:
            args = (jnp.asarray(tokens), jnp.asarray(lens))
            samp = (jnp.asarray(temp_arr), jnp.asarray(top_arr),
                    jnp.asarray(active), jnp.asarray(limits))
        out, n_emitted = self._step(
            self.params, *args,
            rng if rng is not None else self.next_rng(),
            *samp,
            max_new=max_new, cache_len=T + max_new,
        )
        out = np.asarray(out)
        n_emitted = np.asarray(n_emitted)
        latency = time.monotonic() - t0

        results = []
        for i in range(n):
            # Extract by emitted COUNT, not by sentinel scan: pad_id may be a
            # real vocab token in HF checkpoints.
            k = min(int(n_emitted[i]), row_budgets[i])
            ids = [int(t) for t in out[i, :k]]
            finish = "length"
            stop_set = {self.cfg.eos_token_id, *self.cfg.stop_token_ids}
            if ids and ids[-1] in stop_set:
                ids.pop()
                finish = "stop"
            results.append(GenResult(
                token_ids=ids,
                text=self.tokenizer.decode(ids),
                n_prompt_tokens=len(prompts[i]),
                n_gen_tokens=len(ids),
                latency_s=latency,
                finish_reason=finish,
            ))
        return results
