"""Per-action ephemeral router + long-running shell command ownership.

Parity with the reference's Actions.Router (reference
lib/quoracle/actions/router.ex:3-8,42-85): one router per dispatched action,
living only until the action completes. The reference needs a GenServer here
for process isolation and deadlock avoidance (a slow shell command must not
block the agent, and Router.execute must not be called from inside Core —
agent AGENTS.md:237-247); on asyncio the same isolation is one Task per
action, and results return to the Core by posting to its mailbox, never by
calling into it.

Long-running shell commands outlive their action (reference
router.ex:319-351 async mode): each gets its own ShellOwner that holds the
OS process, pumps output into a buffer from the moment of launch, and posts
a completion info message to the Core when the process exits. Later
execute_shell decisions with ``check_id`` resolve to the owner through
``core.shell_routers`` (reference action_executor.ex:121-144 routes check_id
to the same Router). One owner per command — a batch action can hold several
concurrent commands without them clobbering each other.

Secret resolution happens just before execution and output scrubbing just
after (reference router/security.ex; router.ex:324-331), so plaintext secret
values exist only inside the router's execution window. Untrusted-output
actions get NO_EXECUTE wrapping at the Core when the result enters history.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Any, Optional

from quoracle_tpu.actions.executors import ActionError, get_executor
from quoracle_tpu.infra.security import resolve_secrets, scrub_output
from quoracle_tpu.infra.telemetry import ACTION_MS, ACTIONS_TOTAL, TRACER

logger = logging.getLogger(__name__)

# Output cap for shell/file results entering model context (the reference
# truncates via Utils.ResponseTruncator).
MAX_RESULT_CHARS = 100_000


def truncate_output(text: str, limit: int = MAX_RESULT_CHARS) -> str:
    if len(text) <= limit:
        return text
    half = limit // 2
    omitted = len(text) - 2 * half
    return (text[:half] + f"\n…[{omitted} chars truncated]…\n" + text[-half:])


class ActionRouter:
    """Executes exactly one action, then posts the result to the Core's
    mailbox and dies."""

    def __init__(self, core: Any, action_id: str, action: str, params: dict):
        self.core = core
        self.action_id = action_id
        self.action = action
        self.params = params
        self.task: Optional[asyncio.Task] = None

    def dispatch(self) -> None:
        self.task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        core, deps = self.core, self.core.deps
        deps.events.action_started(core.agent_id, self.action_id, self.action,
                                   self.params)
        # Unbound span (telemetry.py): routers interleave on the event
        # loop, so a thread-local current-span binding would leak across
        # tasks — the span links by explicit trace_id (the task) instead.
        span = TRACER.start("action.execute",
                            trace_id=core.config.task_id, parent=None,
                            agent_id=core.agent_id, action=self.action,
                            phase="action")
        t0 = time.monotonic()
        try:
            params, _used = resolve_secrets(
                self.params,
                lambda name: deps.secrets.lookup(
                    name, agent_id=core.agent_id, action=self.action))
            fn = get_executor(self.action)
            result = await fn(core, self, params)
            if "status" not in result:
                result["status"] = "ok"
        except ActionError as e:
            result = {"status": "error", "error": str(e)}
        except asyncio.CancelledError:
            # Core is terminating (reference router.ex:433-446 — routers die
            # with their Core); no result to deliver.
            raise
        except Exception as e:
            logger.exception("action %s (%s) crashed", self.action,
                             self.action_id)
            result = {"status": "error",
                      "error": f"{type(e).__name__}: {e}"}
        result = scrub_output(result, deps.secrets.values())
        span.finish(status=result["status"])
        ACTION_MS.observe((time.monotonic() - t0) * 1000,
                          action=self.action)
        ACTIONS_TOTAL.inc(action=self.action, status=result["status"])
        deps.events.action_completed(core.agent_id, self.action_id,
                                     self.action, result["status"])
        core.post({"type": "action_result", "action_id": self.action_id,
                   "action": self.action, "result": result})

    async def shutdown(self) -> None:
        """Core teardown (reference core.ex:452-462 stops all active Routers
        with :infinity timeout). Live shell commands have their own owners in
        core.shell_routers and are shut down there."""
        if self.task is not None and not self.task.done():
            self.task.cancel()
            try:
                await self.task
            except (asyncio.CancelledError, Exception):
                pass


# ---------------------------------------------------------------------------
# Long-running shell command ownership
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShellCommand:
    """State of one OS command (reference router/shell_command_manager.ex)."""
    command_id: str
    command: str
    proc: Any                         # asyncio.subprocess.Process
    started_at: float
    output: bytearray = dataclasses.field(default_factory=bytearray)
    status: str = "running"           # running | completed | terminated | timeout
    exit_code: Optional[int] = None

    def output_text(self) -> str:
        return self.output.decode("utf-8", errors="replace")


def kill_process_group(proc: Any) -> None:
    """Best-effort synchronous SIGKILL of a command's whole process group
    (commands run with start_new_session=True)."""
    import os
    import signal
    if proc.returncode is not None:
        return
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def close_subprocess_transport(proc: Any) -> None:
    """Release a subprocess transport eagerly. asyncio only closes it via GC
    after both exit and pipe-EOF callbacks run; a loop shutting down right
    after a command finishes would warn about the leak."""
    tr = getattr(proc, "_transport", None)
    if tr is not None:
        tr.close()


async def pump_stream(stream: asyncio.StreamReader, buf: bytearray) -> None:
    """Drain a process stream into a buffer until EOF. Started at launch so
    no output is ever lost to the sync/async handoff."""
    while True:
        chunk = await stream.read(65536)
        if not chunk:
            return
        buf.extend(chunk)


class ShellOwner:
    """Owns one async-mode command: watches it to completion, serves
    check_id polls/terminations, and kills the OS process on teardown."""

    def __init__(self, core: Any, cmd: ShellCommand, pump: asyncio.Task):
        self.core = core
        self.cmd = cmd
        self._pump = pump
        self._watcher: Optional[asyncio.Task] = None

    def adopt(self, timeout: Optional[float]) -> None:
        self.core.shell_routers[self.cmd.command_id] = self
        self._watcher = asyncio.ensure_future(self._watch(timeout))

    async def _watch(self, timeout: Optional[float]) -> None:
        cmd = self.cmd
        try:
            # Wait for process exit by polling returncode (set on SIGCHLD):
            # proc.wait() is gated on pipe EOF, which a daemonized
            # descendant can hold open forever, and the pump has the same
            # failure mode — neither is a reliable exit signal.
            while cmd.proc.returncode is None:
                r = None
                if timeout is not None:
                    r = cmd.started_at + timeout - time.monotonic()
                    if r <= 0:
                        raise asyncio.TimeoutError
                await asyncio.sleep(0.02 if r is None else min(0.02, r))
            cmd.exit_code = cmd.proc.returncode
            # Grace period for the pump to drain what's left in the pipe;
            # for a normal command exit already closed it (instant EOF).
            await self._drain_pump()
            if cmd.status == "running":
                cmd.status = "completed"
        except asyncio.TimeoutError:
            cmd.status = "timeout"
            await self._kill()
            await self._drain_pump()
            cmd.exit_code = cmd.proc.returncode
        except asyncio.CancelledError:
            # Core teardown: kill the OS process before dying (reference
            # router.ex:182-217 terminate kills the port first).
            await self._kill()
            self._pump.cancel()
            raise
        finally:
            self.core.shell_routers.pop(cmd.command_id, None)
            self._close_transport()
        # Completion notification as an info message into the agent loop
        # (reference router.ex:401-407 mark_completed → notify Core). Like
        # every sync result, it is scrubbed before models can see it — the
        # resolved command string and its output may carry secret values.
        self.core.post(scrub_output({
            "type": "shell_completed", "command_id": cmd.command_id,
            "exit_code": cmd.exit_code, "status": cmd.status,
            "output": truncate_output(cmd.output_text()),
            "command": cmd.command,
        }, self.core.deps.secrets.values()))

    async def _drain_pump(self) -> None:
        """After a kill, collect what the pump can still read; give up fast
        if a descendant keeps the pipe open."""
        if self._pump.done():
            return
        try:
            await asyncio.wait_for(asyncio.shield(self._pump), 1.0)
        except (asyncio.TimeoutError, Exception):
            self._pump.cancel()

    async def _kill(self) -> None:
        """Kill the command's whole process group (the shell here does not
        exec its command, so the real work is a grandchild; killing only the
        shell leaves it running and holding the stdout pipe open). Then poll
        returncode rather than awaiting proc.wait(): asyncio gates the exit
        waiter on pipe EOF, which an orphaned descendant can hold open."""
        proc = self.cmd.proc
        if proc.returncode is not None:
            return
        kill_process_group(proc)
        for _ in range(500):                      # ≤5s for SIGCHLD to land
            if proc.returncode is not None:
                return
            await asyncio.sleep(0.01)

    def _close_transport(self) -> None:
        close_subprocess_transport(self.cmd.proc)

    async def terminate_command(self) -> dict:
        """check_id + terminate=true path: kill the running process. The
        watcher is cancelled so no duplicate completion notification posts —
        the caller gets the final state right here."""
        cmd = self.cmd
        cmd.status = "terminated"
        if self._watcher is not None and not self._watcher.done():
            self._watcher.cancel()
            try:
                await self._watcher
            except (asyncio.CancelledError, Exception):
                pass
        await self._kill()
        self.core.shell_routers.pop(cmd.command_id, None)
        self._close_transport()
        return {"status": "ok", "command_id": cmd.command_id,
                "command_status": "terminated",
                "output": truncate_output(cmd.output_text())}

    def poll_command(self) -> dict:
        """check_id polling path: status + output so far."""
        cmd = self.cmd
        return {"status": "ok", "command_id": cmd.command_id,
                "command_status": cmd.status, "exit_code": cmd.exit_code,
                "output": truncate_output(cmd.output_text())}

    async def shutdown(self) -> None:
        if self._watcher is not None and not self._watcher.done():
            self._watcher.cancel()
            try:
                await self._watcher
            except (asyncio.CancelledError, Exception):
                pass
        await self._kill()
        if not self._pump.done():
            self._pump.cancel()
        self._close_transport()
