"""Action executors: the behavior behind each action name.

Each executor is ``async def fn(core, router, params) -> dict``. The schema
layer (actions/schema.py) has already validated params by the time an
executor runs — consensus filters invalid proposals before they can win
(reference consensus.ex:269-293) — so executors only check *runtime*
conditions (child exists, budget available, path allowed…).

Coverage in this module (reference files in parens):
  wait (actions/wait.ex), send_message (send_message.ex), orient (orient.ex),
  todo (todo.ex), file_read / file_write (file_read.ex/file_write.ex),
  execute_shell smart mode (shell.ex:13,24-35,66-114), spawn_child
  (spawn.ex:7-20,109-161,184-227,412-433), dismiss_child (dismiss_child.ex),
  adjust_budget / record_cost (adjust_budget.ex/record_cost.ex),
  generate_secret / search_secrets (generate_secret.ex/search_secrets.ex),
  batch_sync / batch_async (batch_sync.ex/batch_async.ex).
The world-facing network actions (fetch_web, call_api, call_mcp,
answer_engine, generate_images) and the skills actions live in
actions/world.py / the skills subsystem and register themselves here.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid
from decimal import Decimal
from typing import Any, Awaitable, Callable, Optional

import logging

from quoracle_tpu.actions.schema import (
    batchable_async_actions, batchable_sync_actions,
)
from quoracle_tpu.infra.budget import BudgetError

logger = logging.getLogger(__name__)

Executor = Callable[[Any, Any, dict], Awaitable[dict]]

EXECUTORS: dict[str, Executor] = {}


class ActionError(Exception):
    """Executor-level failure that becomes an error result (not a crash)."""


def register(name: str) -> Callable[[Executor], Executor]:
    def deco(fn: Executor) -> Executor:
        EXECUTORS[name] = fn
        return fn
    return deco


def get_executor(name: str) -> Executor:
    fn = EXECUTORS.get(name)
    if fn is None:
        raise ActionError(f"action {name!r} is not available in this runtime")
    return fn


# ---------------------------------------------------------------------------
# Introspection / local state
# ---------------------------------------------------------------------------

@register("wait")
async def wait_action(core, router, params: dict) -> dict:
    """The wait itself is enacted by the Core on the action result
    (reference consensus_handler.ex:264-292 wait-parameter semantics); the
    executor just acknowledges."""
    duration = params.get("duration")
    return {"status": "ok", "waiting": duration if duration else "indefinite",
            "reason": params.get("reason", "")}


@register("orient")
async def orient_action(core, router, params: dict) -> dict:
    """Structured self-reflection: the value is the params themselves landing
    in history (reference actions/orient.ex — 12 reflection fields)."""
    return {"status": "ok", "reflection": dict(params)}


@register("todo")
async def todo_action(core, router, params: dict) -> dict:
    """Replace the TODO list (reference actions/todo.ex — replacement, not
    merge) and broadcast to the UI."""
    items = params["items"]
    core.ctx.todos = list(items)
    core.deps.events.todo_updated(core.agent_id, core.ctx.todos)
    return {"status": "ok", "items": len(core.ctx.todos)}


# ---------------------------------------------------------------------------
# Messaging
# ---------------------------------------------------------------------------

@register("send_message")
async def send_message_action(core, router, params: dict) -> dict:
    """Direct agent messaging: parent / children / announcement / agent id
    (reference actions/send_message.ex; targets at schema.ex:13)."""
    registry = core.deps.registry
    target = params["target"]
    message = {
        "from": core.agent_id,
        "content": params["content"],
        "message_type": params.get("message_type", "info"),
        "ts": time.time(),
    }
    if target == "parent":
        regs = [registry.parent_of(core.agent_id)]
        if regs[0] is None:
            raise ActionError("agent has no parent")
    elif target == "children":
        regs = registry.children_of(core.agent_id)
    elif target == "announcement":
        regs = [r for r in registry.agents_for_task(core.config.task_id)
                if r.agent_id != core.agent_id]
    else:
        reg = registry.lookup(target)
        if reg is None:
            raise ActionError(f"unknown target agent {target!r}")
        regs = [reg]

    delivered = []
    for reg in regs:
        reg.core.post({"type": "agent_message", **message})
        delivered.append(reg.agent_id)
    core.deps.events.task_message(core.config.task_id, {
        **message, "targets": delivered})
    return {"status": "ok", "delivered_to": delivered}


# ---------------------------------------------------------------------------
# Files
# ---------------------------------------------------------------------------

def _check_path(core, path: str, write: bool) -> str:
    """Grove confinement (reference groves/hard_rule_enforcer.ex file
    confinement + path_security.ex): resolve relative to working_dir, then
    check against the agent's node confinement."""
    p = os.path.abspath(os.path.join(core.config.working_dir, path))
    if core.grove is not None:
        err = core.grove.check_file_path(p, write=write,
                                         node=core.config.grove_node)
        if err:
            raise ActionError(err)
    return p


@register("file_read")
async def file_read_action(core, router, params: dict) -> dict:
    from quoracle_tpu.actions.router import truncate_output
    path = _check_path(core, params["path"], write=False)
    offset = int(params.get("offset") or 0)
    limit = params.get("limit")
    try:
        with open(path, "r", errors="replace") as f:
            lines = f.readlines()
    except OSError as e:
        raise ActionError(f"file_read failed: {e}")
    selected = lines[offset: offset + int(limit) if limit else None]
    return {"status": "ok", "path": path,
            "content": truncate_output("".join(selected)),
            "total_lines": len(lines)}


@register("file_write")
async def file_write_action(core, router, params: dict) -> dict:
    path = _check_path(core, params["path"], write=True)
    content = params["content"]
    if core.grove is not None:
        err = core.grove.validate_file_schema(path, content)
        if err:
            raise ActionError(err)
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        mode = "a" if params.get("append") else "w"
        with open(path, mode) as f:
            f.write(content)
    except OSError as e:
        raise ActionError(f"file_write failed: {e}")
    return {"status": "ok", "path": path, "bytes": len(content.encode())}


# ---------------------------------------------------------------------------
# Shell (smart mode)
# ---------------------------------------------------------------------------

@register("execute_shell")
async def execute_shell_action(core, router, params: dict) -> dict:
    """Smart mode (reference actions/shell.ex:13,24-26): sync result if the
    command finishes within the threshold, otherwise async with a command_id
    the agent polls/terminates via check_id (XOR-validated against command).
    Output is pumped into the command's buffer from the moment of launch, so
    nothing emitted before the sync/async handoff is ever lost."""
    from quoracle_tpu.actions.router import (
        ShellCommand, ShellOwner, pump_stream, truncate_output,
    )

    if params.get("check_id"):
        owner = core.shell_routers.get(params["check_id"])
        if owner is None:
            raise ActionError(
                f"no running command {params['check_id']!r} (already "
                f"completed, terminated, or never existed)")
        if params.get("terminate"):
            return await owner.terminate_command()
        return owner.poll_command()

    command = params["command"]
    working_dir = params.get("working_dir") or core.config.working_dir
    if core.grove is not None:
        node = core.config.grove_node
        err = (core.grove.check_shell_command(command, node)
               or core.grove.check_working_dir(working_dir, node))
        if err:
            raise ActionError(err)
    if not os.path.isdir(working_dir):
        raise ActionError(f"working_dir {working_dir!r} does not exist")

    try:
        # Own process group so terminate/timeout can kill the shell AND its
        # descendants (the sh here does not exec; a lone kill of the shell
        # would orphan the real command with the stdout pipe still open).
        proc = await asyncio.create_subprocess_shell(
            command, cwd=working_dir,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            stdin=asyncio.subprocess.DEVNULL,
            start_new_session=True)
    except OSError as e:
        raise ActionError(f"failed to start command: {e}")

    from quoracle_tpu.actions.router import (
        close_subprocess_transport, kill_process_group,
    )
    cmd = ShellCommand(command_id=f"cmd-{uuid.uuid4().hex[:10]}",
                       command=command, proc=proc,
                       started_at=time.monotonic())
    pump = asyncio.ensure_future(pump_stream(proc.stdout, cmd.output))

    threshold = core.deps.shell_sync_threshold_s
    timeout = params.get("timeout")

    def go_async() -> dict:
        ShellOwner(core, cmd, pump).adopt(float(timeout) if timeout else None)
        return {"status": "ok", "async": True, "command_id": cmd.command_id,
                "command_status": "running",
                "note": ("command still running; poll or terminate it with "
                         f"execute_shell check_id={cmd.command_id!r}")}

    try:
        # Poll returncode for the sync window instead of proc.wait():
        # asyncio's exit waiter is gated on pipe EOF, which a backgrounded
        # descendant can hold open long after the process itself exits.
        deadline = time.monotonic() + threshold
        while proc.returncode is None and time.monotonic() < deadline:
            await asyncio.sleep(min(0.005, threshold / 4))
        if proc.returncode is None:
            return go_async()
        try:
            # Process exited within the threshold; the pump ends at pipe
            # EOF — which a backgrounded descendant can hold open, in which
            # case the command is still producing output: treat as async.
            await asyncio.wait_for(asyncio.shield(pump), timeout=threshold)
        except asyncio.TimeoutError:
            return go_async()
    except asyncio.CancelledError:
        # Core teardown cancelled the router mid-launch — this process has
        # no ShellOwner yet, so reap it here or it leaks.
        pump.cancel()
        kill_process_group(proc)
        close_subprocess_transport(proc)
        raise
    cmd.status = "completed"
    cmd.exit_code = proc.returncode
    close_subprocess_transport(proc)
    return {"status": "ok", "sync": True, "exit_code": cmd.exit_code,
            "output": truncate_output(cmd.output_text())}


# ---------------------------------------------------------------------------
# Lifecycle: spawn / dismiss
# ---------------------------------------------------------------------------

SPAWN_MAX_RETRIES = 3        # reference spawn.ex:412-433
SPAWN_RETRY_DELAY_S = 0.2


SPAWN_FIELD_SUMMARIZE_TOKENS = 2000   # per-field threshold (reference
                                      # config_builder pre-summarization)


def _compose_initial_message(params: dict) -> str:
    return "\n\n".join(
        f"[{label}]\n{params[key]}" for label, key in (
            ("TASK", "task_description"),
            ("SUCCESS CRITERIA", "success_criteria"),
            ("IMMEDIATE CONTEXT", "immediate_context"),
            ("APPROACH GUIDANCE", "approach_guidance"),
        ))


async def _summarize_spawn_fields(core, params: dict) -> dict:
    """Pre-summarize OVERSIZED spawn fields through the configured
    summarization model before the child inherits them (reference
    spawn/config_builder.ex maybe_pre_summarize_entry + the
    summarization_model setting): a parent that pastes its whole
    conversation into immediate_context must not start the child at the
    edge of its window. Failures keep the original text — degraded, never
    blocking (the reference's fallback-artifact behavior)."""
    deps = core.deps
    model = None
    if deps.persistence is not None:
        model = deps.persistence.get_setting("summarization_model")
    model = model or core.config.model_pool[0]
    from quoracle_tpu.models.runtime import QueryRequest
    out = dict(params)
    loop = asyncio.get_running_loop()

    async def summarize_one(key: str, text: str) -> None:
        try:
            # count INSIDE the guard: a misconfigured summarization_model
            # (unknown spec) must degrade, not kill the spawn task
            n = deps.token_manager.count(model, text)
            if n <= SPAWN_FIELD_SUMMARIZE_TOKENS:
                return
            # the summarizer's own window bounds one query: clamp the
            # input to its newest tail rather than sending an overflow
            # the degrade-guard would swallow (leaving the child with the
            # full oversized field — the outcome this function prevents)
            cap = max(1024, deps.backend.context_window(model) - 1200)
            while deps.token_manager.count(model, text) > cap \
                    and len(text) > 2000:
                text = "[earlier context truncated]\n" \
                    + text[-(len(text) * 2 // 3):]
            res = (await loop.run_in_executor(
                None, lambda: deps.backend.query([
                    QueryRequest(model, [
                        {"role": "system",
                         "content": "Condense the following context for a "
                                    "sub-agent. Keep every concrete fact, "
                                    "path, and constraint; drop "
                                    "narration."},
                        {"role": "user", "content": text}],
                        temperature=0.2, max_tokens=1024)])))[0]
            if res.ok and res.text.strip():
                out[key] = res.text.strip()
                if res.usage and res.usage.cost:
                    from quoracle_tpu.infra.costs import CostEntry
                    deps.costs.record(CostEntry(
                        agent_id=core.agent_id,
                        task_id=core.config.task_id,
                        amount=Decimal(str(res.usage.cost)),
                        cost_type="model", model_spec=model,
                        input_tokens=res.usage.prompt_tokens,
                        output_tokens=res.usage.completion_tokens,
                        description=f"spawn field summarization: {key}"))
        except Exception:             # noqa: BLE001 — degrade, don't block
            logger.warning("spawn field summarization failed for %s",
                           key, exc_info=True)

    # concurrent: the spawn waits for the SLOWEST oversized field, not
    # the sum (the backend's batcher may even coalesce the queries)
    await asyncio.gather(*(
        summarize_one(key, out[key])
        for key in ("task_description", "success_criteria",
                    "immediate_context", "approach_guidance",
                    "global_context")
        if isinstance(out.get(key), str)))
    return out


@register("spawn_child")
async def spawn_child_action(core, router, params: dict) -> dict:
    """Async spawn (reference spawn.ex:7-20): child_id allocated and budget
    escrowed synchronously, the child itself starts in a background task, and
    the action returns immediately — success/failure arrives later as a
    child_spawned / spawn_failed message to the parent."""
    from quoracle_tpu.agent.state import AgentConfig, new_agent_id

    deps, registry = core.deps, core.deps.registry
    if registry.dismissing(core.agent_id):
        raise ActionError("parent is being dismissed; refusing to spawn")

    child_id = new_agent_id()
    budget = params.get("budget")
    if budget is None and core.budget_limit is not None:
        # Reference spawn.ex:152-155: children of budgeted parents MUST get
        # an explicit allocation or the escrow books don't balance.
        raise ActionError("budget is required when the parent has a budget")
    allocated: Optional[Decimal] = None
    if budget is not None:
        try:
            allocated = Decimal(str(budget))
            deps.escrow.lock_for_child(core.agent_id, child_id, allocated)
        except (BudgetError, KeyError) as e:
            raise ActionError(f"budget escrow failed: {e}")

    profile = params.get("profile")
    # Topology auto-injection (reference TopologyResolver
    # apply_spawn_contract, spawn.ex:117): the grove edge this spawn follows
    # assigns the child's node, skills, and any contract overrides.
    from quoracle_tpu.governance.fields import (
        accumulate_constraints, child_fields_from_spawn,
        compose_field_prompt,
    )
    resolved = None
    if core.grove is not None:
        from quoracle_tpu.governance.grove import GroveError
        try:
            resolved = core.grove.resolve_spawn(core.config.grove_node,
                                                params)
        except GroveError as e:
            if allocated is not None:
                try:
                    deps.escrow.release_child(child_id)
                except (BudgetError, KeyError):
                    pass
            raise ActionError(str(e))
    child_node = resolved.node if resolved else None
    child_skills = tuple(params.get("skills") or ())
    extra_constraints: list[str] = []
    forbidden = set(core.config.forbidden_actions)
    governance_docs = core.config.governance_docs
    if resolved is not None:
        child_skills += tuple(s for s in resolved.skills
                              if s not in child_skills)
        profile = resolved.profile or profile
        if resolved.constraints:
            extra_constraints.append(resolved.constraints)
    if core.grove is not None:
        forbidden |= core.grove.blocked_actions(child_node)
        governance_docs = core.grove.governance_docs_for(child_node)

    # Constraint accumulation down the tree (reference
    # ConstraintAccumulator): child inherits every ancestor constraint.
    inherited = accumulate_constraints(core.config.accumulated_constraints,
                                       core.config.own_constraints)
    inherited += tuple(extra_constraints)

    def build_cfg(p: dict) -> AgentConfig:
        # built from the (possibly summarized) params so an oversized
        # global_context doesn't reach the child's system prompt verbatim
        fields = child_fields_from_spawn(p)
        return AgentConfig(
            agent_id=child_id,
            task_id=core.config.task_id,
            parent_id=core.agent_id,
            model_pool=(resolved.model_pool if resolved else None)
                        or list(core.config.model_pool),
            profile=profile,
            capability_groups=(resolved.capability_groups
                               if resolved is not None
                               and resolved.capability_groups is not None
                               else core.config.capability_groups),
            forbidden_actions=tuple(sorted(forbidden)),
            max_refinement_rounds=core.config.max_refinement_rounds,
            field_system_prompt=compose_field_prompt(fields, inherited),
            own_constraints=p.get("constraints"),
            accumulated_constraints=inherited,
            profile_names=core.config.profile_names,
            grove_path=core.config.grove_path,
            grove_node=child_node,
            governance_docs=governance_docs,
            active_skills=child_skills,
            budget_mode="allocated" if allocated is not None else "na",
            budget_limit=allocated,
            working_dir=core.config.working_dir,
            # QoS: tenant attribution flows down the tree; the child's
            # CLASS is derived from its depth at build time, not copied
            tenant=core.config.tenant,
        )

    def _release_escrow() -> None:
        if allocated is not None:
            try:
                deps.escrow.release_child(child_id)
            except (BudgetError, KeyError):
                pass

    async def do_spawn() -> None:
        last_err: Optional[Exception] = None
        try:
            # dismissing check FIRST: no paid summarization call for a
            # child that will never spawn (the spawn/dismiss race,
            # reference core.ex:213-220)
            if registry.dismissing(core.agent_id) \
                    or registry.lookup(core.agent_id) is None:
                last_err = RuntimeError("parent dismissed during spawn")
            else:
                try:
                    # oversized fields summarize INSIDE the background
                    # task — an LLM call must not delay the spawn
                    # action's immediate return
                    sum_params = await _summarize_spawn_fields(core,
                                                               params)
                except Exception:     # noqa: BLE001 — degrade, never block
                    logger.warning("spawn field summarization failed",
                                   exc_info=True)
                    sum_params = params
                cfg = build_cfg(sum_params)
                initial_message = _compose_initial_message(sum_params)
                for attempt in range(SPAWN_MAX_RETRIES):
                    # Re-check right before registering: terminate_tree
                    # may have flagged the parent while this task ran.
                    if registry.dismissing(core.agent_id) \
                            or registry.lookup(core.agent_id) is None:
                        last_err = RuntimeError(
                            "parent dismissed during spawn")
                        break
                    try:
                        child = await deps.supervisor.start_agent(cfg)
                        if registry.dismissing(core.agent_id) \
                                or registry.lookup(core.agent_id) is None:
                            # Parent was torn down after tree collection:
                            # this child escaped the BFS, so reap it here
                            # — the subtree must not grow during
                            # dismissal.
                            await deps.supervisor.terminate_tree(
                                child_id, by=core.agent_id,
                                reason="parent dismissed")
                            last_err = RuntimeError(
                                "parent dismissed during spawn")
                            break
                        # UI learns about the child before any blocking
                        # waits (reference spawn.ex:264-272).
                        child.post({"type": "user_message",
                                    "content": initial_message,
                                    "from": core.agent_id})
                        core.post({"type": "child_spawned",
                                   "child_id": child_id,
                                   "profile": profile})
                        return
                    except Exception as e:            # noqa: BLE001
                        last_err = e
                        await asyncio.sleep(
                            SPAWN_RETRY_DELAY_S * (attempt + 1))
        except asyncio.CancelledError:
            # core teardown cancels background tasks — the escrow must
            # not stay committed to a child that never spawned (the
            # summarization call widened this window to seconds)
            _release_escrow()
            raise
        _release_escrow()
        core.post({"type": "spawn_failed", "child_id": child_id,
                   "reason": f"{type(last_err).__name__}: {last_err}"})

    core.track_background(asyncio.ensure_future(do_spawn()))
    return {"status": "ok", "agent_id": child_id,
            "budget_allocated": str(allocated) if allocated is not None else None}


@register("dismiss_child")
async def dismiss_child_action(core, router, params: dict) -> dict:
    """Recursive subtree dismissal + budget absorption (reference
    dismiss_child.ex + TreeTerminator, agent AGENTS.md:168-175)."""
    child_id = params["child_id"]
    reg = core.deps.registry.lookup(child_id)
    if reg is None or reg.parent_id != core.agent_id:
        raise ActionError(f"{child_id!r} is not a live child of this agent")
    terminated = await core.deps.supervisor.terminate_tree(
        child_id, by=core.agent_id, reason=params.get("reason", "dismissed"))
    core.children = [c for c in core.children if c["agent_id"] != child_id]
    core.ctx.children = list(core.children)
    return {"status": "ok", "dismissed": child_id,
            "agents_terminated": terminated}


# ---------------------------------------------------------------------------
# Budget / costs
# ---------------------------------------------------------------------------

@register("adjust_budget")
async def adjust_budget_action(core, router, params: dict) -> dict:
    child_id = params["child_id"]
    if not any(c["agent_id"] == child_id for c in core.children):
        raise ActionError(f"{child_id!r} is not a child of this agent")
    try:
        state = core.deps.escrow.adjust_child(
            core.agent_id, child_id, Decimal(str(params["amount"])))
    except (BudgetError, KeyError) as e:
        raise ActionError(f"adjust_budget failed: {e}")
    core.deps.events.budget_updated(child_id, state.snapshot())
    return {"status": "ok", "child_id": child_id,
            "new_allocation": str(params["amount"])}


@register("record_cost")
async def record_cost_action(core, router, params: dict) -> dict:
    from quoracle_tpu.infra.costs import CostEntry
    entry = core.deps.costs.record(CostEntry(
        agent_id=core.agent_id, task_id=core.config.task_id,
        amount=Decimal(str(params["amount"])), cost_type="manual",
        description=params["description"]))
    return {"status": "ok", "recorded": str(entry.amount)}


# ---------------------------------------------------------------------------
# Secrets
# ---------------------------------------------------------------------------

@register("generate_secret")
async def generate_secret_action(core, router, params: dict) -> dict:
    name = params["name"]
    store = core.deps.secrets
    if params.get("value"):
        store.put(name, params["value"], params.get("description", ""),
                  created_by=core.agent_id)
    else:
        store.generate(name, length=int(params.get("length") or 32),
                       charset=params.get("charset") or "alphanumeric",
                       description=params.get("description", ""),
                       created_by=core.agent_id)
    return {"status": "ok", "name": name,
            "usage": f"reference it as {{{{SECRET:{name}}}}} in action params"}


@register("search_secrets")
async def search_secrets_action(core, router, params: dict) -> dict:
    return {"status": "ok",
            "secrets": core.deps.secrets.search(params["query"])}


# ---------------------------------------------------------------------------
# Skills (reference actions/learn_skills.ex / create_skill.ex)
# ---------------------------------------------------------------------------

@register("learn_skills")
async def learn_skills_action(core, router, params: dict) -> dict:
    """Load skills into the active set; invalidates the cached system prompt
    so next cycle carries the skill content (reference core.ex:338-341)."""
    loader = core.skills_loader
    if loader is None:
        raise ActionError("no skills directory is configured")
    available = loader.all()
    missing = [s for s in params["skills"] if s not in available]
    if missing:
        raise ActionError(
            f"unknown skills: {', '.join(missing)}. Available: "
            f"{', '.join(sorted(available)) or '(none)'}")
    added = [s for s in params["skills"] if s not in core.active_skills]
    core.active_skills.extend(added)
    # Learned skills must survive pause/restore: mirror into the persisted
    # config (restore reads config.active_skills).
    core.config.active_skills = tuple(core.active_skills)
    if core.deps.persistence is not None:
        core.deps.persistence.persist_agent(core)
    core.invalidate_system_prompt()
    return {"status": "ok", "active_skills": list(core.active_skills),
            "added": added}


@register("create_skill")
async def create_skill_action(core, router, params: dict) -> dict:
    loader = core.skills_loader
    if loader is None:
        raise ActionError("no skills directory is configured")
    from quoracle_tpu.governance.skills import SkillError
    try:
        skill = loader.create(params["name"], params["description"],
                              params["content"])
    except SkillError as e:
        raise ActionError(str(e))
    return {"status": "ok", "name": skill.name, "path": skill.path}


# ---------------------------------------------------------------------------
# Batching
# ---------------------------------------------------------------------------

async def _run_sub_action(core, router, sub: dict) -> dict:
    name = sub.get("action")
    try:
        fn = get_executor(name)
        result = await fn(core, router, sub.get("params", {}))
        if "status" not in result:
            result["status"] = "ok"
    except ActionError as e:
        result = {"status": "error", "error": str(e)}
    except Exception as e:                                 # noqa: BLE001
        result = {"status": "error", "error": f"{type(e).__name__}: {e}"}
    return {"action": name, **result}


@register("batch_sync")
async def batch_sync_action(core, router, params: dict) -> dict:
    """Sequential sub-actions; an error stops the remainder (the agent sees
    partial results and can re-plan). Batchable set per reference
    action_list.ex:33-47."""
    allowed = batchable_sync_actions()
    results = []
    for sub in params["actions"]:
        if sub.get("action") not in allowed:
            results.append({"action": sub.get("action"), "status": "error",
                            "error": "not batchable in batch_sync"})
            break
        result = await _run_sub_action(core, router, sub)
        results.append(result)
        if result["status"] != "ok":
            break
    status = "ok" if all(r["status"] == "ok" for r in results) else "partial"
    return {"status": status, "results": results}


@register("batch_async")
async def batch_async_action(core, router, params: dict) -> dict:
    """Concurrent sub-actions (reference batch_async.ex — excludes only
    wait/batch_*, action_list.ex:79)."""
    allowed = batchable_async_actions()
    subs = list(params["actions"])
    for sub in subs:
        if sub.get("action") not in allowed:
            raise ActionError(
                f"{sub.get('action')!r} is not batchable in batch_async")
    results = await asyncio.gather(
        *(_run_sub_action(core, router, sub) for sub in subs))
    status = "ok" if all(r["status"] == "ok" for r in results) else "partial"
    return {"status": status, "results": list(results)}
