"""Parameter validation against action schemas.

Parity with the reference's Validator (reference
lib/quoracle/actions/validator.ex:14-50): required params, types, enums, XOR
constraints, recursive batch sub-action validation, and the wait parameter.
Invalid responses are FILTERED before clustering (reference
agent/consensus.ex:269-293) — validation errors also feed per-model
correction feedback on retry.
"""

from __future__ import annotations

from typing import Any, Optional

from quoracle_tpu.actions.schema import (
    ACTIONS, ActionSchema, batchable_async_actions, batchable_sync_actions,
    get_schema,
)


class ValidationError(Exception):
    def __init__(self, errors: list[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


_TYPE_CHECKS = {
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
    "map": lambda v: isinstance(v, dict),
    "list": lambda v: isinstance(v, list),
}


def validate_params(action: str, params: dict,
                    allowed_actions: Optional[set[str]] = None,
                    profile_optional: bool = False) -> list[str]:
    """Returns a list of error strings; empty = valid.

    ``profile_optional`` relaxes spawn_child's required profile under grove
    topology auto-injection (reference validator.ex:14-50).
    """
    errors: list[str] = []
    if action not in ACTIONS:
        return [f"unknown action {action!r}"]
    if allowed_actions is not None and action not in allowed_actions:
        return [f"action {action!r} not permitted for this agent"]
    schema = ACTIONS[action]
    if not isinstance(params, dict):
        return [f"params must be an object, got {type(params).__name__}"]

    required = set(schema.required)
    if profile_optional and action == "spawn_child":
        required.discard("profile")
    for p in sorted(required):
        if params.get(p) is None:
            errors.append(f"missing required param {p!r}")

    for group in schema.xor_groups:
        present = [p for p in group if params.get(p) is not None]
        if len(present) != 1:
            errors.append(
                f"exactly one of {group} required, got {present or 'none'}")

    known = set(schema.params)
    for key, value in params.items():
        if key not in known:
            errors.append(f"unknown param {key!r} for action {action!r}")
            continue
        if value is None:
            continue
        expected = schema.types.get(key)
        if expected and not _TYPE_CHECKS[expected](value):
            errors.append(
                f"param {key!r} must be {expected}, got {type(value).__name__}")
            continue
        enum = schema.enums.get(key)
        if enum is not None and value not in enum:
            errors.append(f"param {key!r} must be one of {enum}, got {value!r}")

    if action in ("batch_sync", "batch_async"):
        errors.extend(_validate_batch(action, params, allowed_actions,
                                      profile_optional))
    return errors


def _validate_batch(action: str, params: dict,
                    allowed_actions: Optional[set[str]],
                    profile_optional: bool = False) -> list[str]:
    errors: list[str] = []
    subs = params.get("actions")
    if not isinstance(subs, list) or not subs:
        return ["batch requires a non-empty 'actions' list"]
    allowed_set = (batchable_sync_actions() if action == "batch_sync"
                   else batchable_async_actions())
    for i, sub in enumerate(subs):
        if not isinstance(sub, dict) or "action" not in sub:
            errors.append(f"batch item {i} must be an object with 'action'")
            continue
        sub_action = sub["action"]
        if sub_action not in allowed_set:
            errors.append(f"batch item {i}: {sub_action!r} not batchable in {action}")
            continue
        # profile_optional flows into sub-actions: a grove agent batching
        # spawn_childs gets the same topology profile injection a bare
        # spawn_child gets
        sub_errors = validate_params(sub_action, sub.get("params", {}),
                                     allowed_actions=allowed_actions,
                                     profile_optional=profile_optional)
        errors.extend(f"batch item {i}: {e}" for e in sub_errors)
    return errors


def validate_wait_param(action: str, wait: Any) -> Optional[str]:
    """The wait parameter accompanies every action except `wait` itself
    (reference schema.ex:100-102). Legal: bool, or non-negative int."""
    schema = get_schema(action)
    if not schema.wait_required:
        return None
    if wait is None:
        return "missing wait parameter"
    if isinstance(wait, bool):
        return None
    if isinstance(wait, int) and wait >= 0:
        return None
    return f"wait must be true/false or a non-negative integer, got {wait!r}"
