"""Actions layer: the gated action vocabulary agents execute.

Re-design of the reference's lib/quoracle/actions/ (SURVEY.md §2.4): schemas
+ validation + consensus merge rules as pure data/logic here, execution via
per-action router tasks in router.py.
"""

from quoracle_tpu.actions.schema import (  # noqa: F401
    ACTIONS,
    ActionSchema,
    batchable_sync_actions,
    batchable_async_actions,
    get_schema,
)

# Executor registration side effects: importing these fills EXECUTORS.
from quoracle_tpu.actions import executors as _executors  # noqa: E402,F401
from quoracle_tpu.actions import world as _world  # noqa: E402,F401
