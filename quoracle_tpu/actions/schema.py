"""Action schemas: the single source of truth for the action vocabulary.

Parity with the reference's 22 actions
(reference lib/quoracle/actions/schema/action_list.ex:6-29) and their
per-param consensus rules / priorities
(reference lib/quoracle/actions/schema.ex:72-102, schema/agent_schemas.ex,
schema/api_schemas.ex). Expressed as one dataclass per action rather than
scattered function heads; everything downstream (validator, prompt builder,
aggregator fingerprints, result merging, capability gating) reads from here.

Consensus rules per param (reference actions/consensus_rules.ex:18-120):
  exact            — byte equality; differing values split clusters
  semantic(t)      — embedding cosine >= t treats values as equivalent
  mode             — most common value wins at merge
  union            — sorted union of list values
  structural       — deep-sorted structural merge for maps/lists
  percentile(p)    — numeric: p-th percentile of cluster values
  batch_sequence   — per-position merge of batch sub-actions
  wait             — wait-parameter voting (False/0 < int < True)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


# -- consensus rule descriptors ---------------------------------------------

def exact() -> tuple: return ("exact",)
def semantic(threshold: float = 0.85) -> tuple: return ("semantic", threshold)
def mode() -> tuple: return ("mode",)
def union() -> tuple: return ("union",)
def structural() -> tuple: return ("structural",)
def percentile(p: float = 50.0) -> tuple: return ("percentile", p)
def batch_sequence() -> tuple: return ("batch_sequence",)
def wait_rule() -> tuple: return ("wait",)


@dataclasses.dataclass(frozen=True)
class ActionSchema:
    name: str
    description: str
    required: tuple[str, ...] = ()
    optional: tuple[str, ...] = ()
    types: dict[str, str] = dataclasses.field(default_factory=dict)
    enums: dict[str, tuple] = dataclasses.field(default_factory=dict)
    descriptions: dict[str, str] = dataclasses.field(default_factory=dict)
    rules: dict[str, tuple] = dataclasses.field(default_factory=dict)
    # Exactly one of each group must be present (shell: command XOR check_id).
    xor_groups: tuple[tuple[str, ...], ...] = ()
    # Tiebreak priority: LOWER wins ties (reference schema.ex action priorities).
    priority: int = 50
    # All actions except `wait` itself require the model to supply a wait
    # parameter deciding whether to pause after execution
    # (reference schema.ex:100-102).
    wait_required: bool = True

    @property
    def params(self) -> tuple[str, ...]:
        return self.required + self.optional

    def rule_for(self, param: str) -> tuple:
        return self.rules.get(param, exact())


_A: dict[str, ActionSchema] = {}


def _register(schema: ActionSchema) -> ActionSchema:
    _A[schema.name] = schema
    return schema


# --- agent lifecycle --------------------------------------------------------

_register(ActionSchema(
    name="spawn_child",
    description="Spawn a child agent to work on a subtask.",
    required=("task_description", "success_criteria", "immediate_context",
              "approach_guidance", "profile"),
    optional=("budget", "skills", "cognitive_style", "constraints",
              "global_context", "role"),
    types={"task_description": "string", "success_criteria": "string",
           "immediate_context": "string", "approach_guidance": "string",
           "profile": "string", "budget": "number", "skills": "list",
           "cognitive_style": "string", "constraints": "string",
           "global_context": "string", "role": "string"},
    rules={"task_description": semantic(0.85), "success_criteria": semantic(0.85),
           "immediate_context": semantic(0.80), "approach_guidance": semantic(0.80),
           "profile": mode(), "budget": percentile(50), "skills": union(),
           "cognitive_style": mode(), "constraints": semantic(0.80),
           "global_context": semantic(0.80), "role": mode()},
    priority=20,
))

_register(ActionSchema(
    name="dismiss_child",
    description="Dismiss a child agent (recursively terminates its subtree).",
    required=("child_id",),
    optional=("reason",),
    types={"child_id": "string", "reason": "string"},
    rules={"child_id": exact(), "reason": semantic(0.7)},
    priority=25,
))

_register(ActionSchema(
    name="send_message",
    description="Send a message to parent, children, or a specific agent.",
    required=("target", "content"),
    optional=("message_type",),
    types={"target": "string", "content": "string", "message_type": "string"},
    enums={"message_type": ("info", "question", "result", "error", "announcement")},
    rules={"target": exact(), "content": semantic(0.80), "message_type": mode()},
    priority=10,
))

_register(ActionSchema(
    name="wait",
    description="Pause until new events arrive (or a timeout).",
    required=(),
    optional=("duration", "reason"),
    types={"duration": "integer", "reason": "string"},
    rules={"duration": percentile(50), "reason": semantic(0.7)},
    priority=90,
    wait_required=False,
))

_register(ActionSchema(
    name="orient",
    description="Structured self-reflection on progress and strategy.",
    required=("current_understanding", "progress_assessment"),
    optional=("obstacles", "next_steps", "confidence", "assumptions",
              "information_needed", "risks", "alternatives", "decision_rationale",
              "success_likelihood", "course_correction"),
    types={"current_understanding": "string", "progress_assessment": "string",
           "obstacles": "string", "next_steps": "string", "confidence": "number",
           "assumptions": "string", "information_needed": "string",
           "risks": "string", "alternatives": "string",
           "decision_rationale": "string", "success_likelihood": "number",
           "course_correction": "string"},
    rules={k: semantic(0.75) for k in
           ("current_understanding", "progress_assessment", "obstacles",
            "next_steps", "assumptions", "information_needed", "risks",
            "alternatives", "decision_rationale", "course_correction")}
          | {"confidence": percentile(50), "success_likelihood": percentile(50)},
    priority=80,
))

_register(ActionSchema(
    name="todo",
    description="Replace the agent's TODO list.",
    required=("items",),
    types={"items": "list"},
    rules={"items": structural()},
    priority=70,
))

# --- world-facing -----------------------------------------------------------

_register(ActionSchema(
    name="execute_shell",
    description="Run a shell command (sync if fast, async with command_id if slow); "
                "or poll/terminate a running command via check_id.",
    required=(),
    optional=("command", "working_dir", "timeout", "check_id", "terminate"),
    types={"command": "string", "working_dir": "string", "timeout": "integer",
           "check_id": "string", "terminate": "boolean"},
    rules={"command": exact(), "working_dir": exact(),
           "timeout": percentile(75), "check_id": exact(), "terminate": mode()},
    xor_groups=(("command", "check_id"),),
    priority=30,
))

_register(ActionSchema(
    name="fetch_web",
    description="Fetch a URL and convert to markdown.",
    required=("url",),
    optional=("timeout",),
    types={"url": "string", "timeout": "integer"},
    rules={"url": exact(), "timeout": percentile(75)},
    priority=35,
))

_register(ActionSchema(
    name="call_api",
    description="Call an external HTTP API (REST/JSON-RPC/GraphQL).",
    required=("url", "method"),
    optional=("headers", "body", "auth", "timeout", "protocol"),
    types={"url": "string", "method": "string", "headers": "map",
           "body": "map", "auth": "map", "timeout": "integer",
           "protocol": "string"},
    enums={"method": ("GET", "POST", "PUT", "PATCH", "DELETE"),
           "protocol": ("rest", "jsonrpc", "graphql")},
    rules={"url": exact(), "method": exact(), "headers": structural(),
           "body": structural(), "auth": structural(),
           "timeout": percentile(75), "protocol": mode()},
    priority=35,
))

_register(ActionSchema(
    name="call_mcp",
    description="Invoke a tool on a configured MCP server.",
    required=("server", "tool"),
    optional=("arguments", "timeout"),
    types={"server": "string", "tool": "string", "arguments": "map",
           "timeout": "integer"},
    rules={"server": exact(), "tool": exact(), "arguments": structural(),
           "timeout": percentile(75)},
    priority=35,
))

_register(ActionSchema(
    name="answer_engine",
    description="Ask a web-grounded answer engine.",
    required=("query",),
    optional=("focus",),
    types={"query": "string", "focus": "string"},
    rules={"query": semantic(0.85), "focus": mode()},
    priority=40,
))

_register(ActionSchema(
    name="file_read",
    description="Read a file from the workspace.",
    required=("path",),
    optional=("offset", "limit"),
    types={"path": "string", "offset": "integer", "limit": "integer"},
    rules={"path": exact(), "offset": percentile(50), "limit": percentile(50)},
    priority=30,
))

_register(ActionSchema(
    name="file_write",
    description="Write content to a file in the workspace.",
    required=("path", "content"),
    optional=("append",),
    types={"path": "string", "content": "string", "append": "boolean"},
    rules={"path": exact(), "content": semantic(0.90), "append": mode()},
    priority=30,
))

# --- knowledge / skills -----------------------------------------------------

_register(ActionSchema(
    name="learn_skills",
    description="Load skills into the agent's active skill set.",
    required=("skills",),
    types={"skills": "list"},
    rules={"skills": union()},
    priority=60,
))

_register(ActionSchema(
    name="create_skill",
    description="Author a new skill file.",
    required=("name", "description", "content"),
    types={"name": "string", "description": "string", "content": "string"},
    rules={"name": exact(), "description": semantic(0.8),
           "content": semantic(0.85)},
    priority=60,
))

# --- secrets / budget / costs ----------------------------------------------

_register(ActionSchema(
    name="generate_secret",
    description="Create and store an encrypted secret.",
    required=("name",),
    optional=("length", "charset", "value", "description"),
    types={"name": "string", "length": "integer", "charset": "string",
           "value": "string", "description": "string"},
    enums={"charset": ("alphanumeric", "hex", "base64", "ascii")},
    rules={"name": exact(), "length": percentile(50), "charset": mode(),
           "value": exact(), "description": semantic(0.7)},
    priority=55,
))

_register(ActionSchema(
    name="search_secrets",
    description="Search stored secrets by name/description.",
    required=("query",),
    types={"query": "string"},
    rules={"query": semantic(0.8)},
    priority=55,
))

_register(ActionSchema(
    name="record_cost",
    description="Record a manually-incurred cost against the budget.",
    required=("amount", "description"),
    types={"amount": "number", "description": "string"},
    rules={"amount": percentile(50), "description": semantic(0.7)},
    priority=65,
))

_register(ActionSchema(
    name="adjust_budget",
    description="Adjust a child agent's budget allocation.",
    required=("child_id", "amount"),
    types={"child_id": "string", "amount": "number"},
    rules={"child_id": exact(), "amount": percentile(50)},
    priority=45,
))

# --- media ------------------------------------------------------------------

_register(ActionSchema(
    name="generate_images",
    description="Generate images from a text prompt across configured image models.",
    required=("prompt",),
    optional=("count", "size"),
    types={"prompt": "string", "count": "integer", "size": "string"},
    rules={"prompt": semantic(0.85), "count": percentile(50), "size": mode()},
    priority=50,
))

# --- batching ---------------------------------------------------------------

_register(ActionSchema(
    name="batch_sync",
    description="Execute multiple actions sequentially in one consensus cycle.",
    required=("actions",),
    types={"actions": "list"},
    rules={"actions": batch_sequence()},
    priority=15,
))

_register(ActionSchema(
    name="batch_async",
    description="Execute multiple actions in parallel in one consensus cycle.",
    required=("actions",),
    types={"actions": "list"},
    rules={"actions": batch_sequence()},
    priority=15,
))


ACTIONS: dict[str, ActionSchema] = dict(_A)


def get_schema(name: str) -> ActionSchema:
    if name not in ACTIONS:
        raise KeyError(f"unknown action {name!r}")
    return ACTIONS[name]


def batchable_sync_actions() -> set[str]:
    """Actions allowed inside batch_sync (reference action_list.ex:33-47):
    no nested batches, no wait, no spawn/dismiss lifecycle races."""
    return set(ACTIONS) - {"batch_sync", "batch_async", "wait",
                           "spawn_child", "dismiss_child"}


def batchable_async_actions() -> set[str]:
    """batch_async excludes only wait and nested batches
    (reference action_list.ex:79)."""
    return set(ACTIONS) - {"batch_sync", "batch_async", "wait"}
