"""World-facing action executors: fetch_web, call_api, call_mcp,
answer_engine, generate_images.

Parity targets (reference files):
  fetch_web     — actions/web.ex:12-36 (fetch → HTML-to-Markdown, image
                  content-type handling, optional SSRF check, truncation)
  call_api      — actions/api.ex + api/ submodules (REST + JSON-RPC +
                  GraphQL adapters, auth handling, response parsing)
  call_mcp      — actions/mcp.ex:1-20 (tool invocation through the MCP
                  client, 120s default timeout)
  answer_engine — actions/answer_engine.ex:1-52 (web-grounded answers with
                  source extraction + cost recording; the reference grounds
                  through a hosted grounding model — here grounding is an
                  optional search fetch + the designated on-device answer
                  model)
  generate_images — actions/generate_images.ex + models/image_query.ex
                  (multi-image generation with cost recording)

Network I/O rides the injectable HTTP seam (infra/http.py); results are
NO_EXECUTE-fenced by the Core before entering model history.
"""

from __future__ import annotations

import asyncio
import base64
import json
from decimal import Decimal
from typing import Any, Optional

from quoracle_tpu.actions.executors import ActionError, register
from quoracle_tpu.actions.router import truncate_output
from quoracle_tpu.infra.http import SSRFError, check_ssrf
from quoracle_tpu.utils.html_md import html_to_markdown

FETCH_MAX_CHARS = 50_000
IMAGE_MAX_BYTES = 512_000


async def _http(core, url: str, method: str = "GET", headers=None,
                body: Optional[bytes] = None,
                timeout_s: float = 30.0):
    """Run the (blocking) HTTP transport off-loop. When the SSRF guard is
    on, it also re-checks every redirect hop (transports that don't accept
    verify_url — test fakes — don't follow redirects anyway)."""
    fn = core.deps.http
    if fn is None:
        raise ActionError("no HTTP transport configured (zero-egress mode)")
    loop = asyncio.get_running_loop()
    kwargs = {}
    if core.deps.ssrf_check and fn is _default_transport():
        kwargs["verify_url"] = check_ssrf
    return await loop.run_in_executor(
        None, lambda: fn(url, method, headers or {}, body, timeout_s,
                         **kwargs))


def _default_transport():
    from quoracle_tpu.infra.http import urllib_http
    return urllib_http


# ---------------------------------------------------------------------------
# fetch_web
# ---------------------------------------------------------------------------

@register("fetch_web")
async def fetch_web_action(core, router, params: dict) -> dict:
    url = params["url"]
    if core.deps.ssrf_check:
        # Off-loop: the guard resolves DNS, which must never block the
        # runtime loop. The default transport re-checks redirect hops.
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, check_ssrf, url)
        except SSRFError as e:
            raise ActionError(f"fetch_web blocked: {e}")
    resp = await _http(core, url,
                       timeout_s=float(params.get("timeout") or 30))
    ctype = resp.content_type
    if ctype.startswith("image/"):
        # Image responses return as base64 for multimodal use (reference
        # web.ex image content-type handling), capped so one large image
        # can't blow the context window (the reference compresses via
        # libvips; our resize path is the native image preprocessor).
        if len(resp.body) > IMAGE_MAX_BYTES:
            return {"status": "ok", "url": resp.url or url,
                    "content_type": ctype, "bytes": len(resp.body),
                    "note": (f"image is {len(resp.body)} bytes "
                             f"(> {IMAGE_MAX_BYTES} cap); not inlined")}
        return {"status": "ok", "url": resp.url or url, "content_type": ctype,
                "image_base64": base64.b64encode(resp.body).decode(),
                "bytes": len(resp.body)}
    text = resp.text()
    if "html" in ctype or text.lstrip()[:1] == "<":
        content = html_to_markdown(text)
    else:
        content = text
    return {"status": "ok", "url": resp.url or url,
            "http_status": resp.status, "content_type": ctype,
            "content": truncate_output(content, FETCH_MAX_CHARS)}


# ---------------------------------------------------------------------------
# call_api (REST / JSON-RPC / GraphQL)
# ---------------------------------------------------------------------------

def _auth_headers(auth: Optional[dict],
                  core: Optional[object] = None) -> dict[str, str]:
    if not auth:
        return {}
    kind = auth.get("type", "bearer")
    if kind == "credential":
        # stored-credential auth (reference CredentialManager: encrypted
        # at rest, decrypted on fetch, access audited): the action names a
        # credential id instead of carrying the secret inline — keys never
        # pass through the model's context
        store = getattr(getattr(core, "deps", None), "credentials", None)
        if store is None:
            raise ActionError("no credential store is wired")
        data = store.get(auth.get("id", ""),
                         agent_id=getattr(core, "agent_id", ""),
                         action="call_api")
        if data is None:
            raise ActionError(
                f"unknown credential {auth.get('id')!r}")
        auth = data                            # payload is an auth dict
    from quoracle_tpu.infra.http import build_auth_headers
    try:
        return build_auth_headers(auth)
    except ValueError as e:
        raise ActionError(str(e))


@register("call_api")
async def call_api_action(core, router, params: dict) -> dict:
    url = params["url"]
    method = params["method"].upper()
    protocol = params.get("protocol") or "rest"
    headers = {**(params.get("headers") or {}),
               **_auth_headers(params.get("auth"), core)}
    body_param = params.get("body")
    body: Optional[bytes] = None

    if protocol == "jsonrpc":
        method = "POST"
        payload = {"jsonrpc": "2.0", "id": 1,
                   "method": (body_param or {}).get("method"),
                   "params": (body_param or {}).get("params", {})}
        body = json.dumps(payload).encode()
        headers.setdefault("content-type", "application/json")
    elif protocol == "graphql":
        method = "POST"
        payload = {"query": (body_param or {}).get("query", ""),
                   "variables": (body_param or {}).get("variables", {})}
        body = json.dumps(payload).encode()
        headers.setdefault("content-type", "application/json")
    elif body_param is not None:
        body = json.dumps(body_param).encode()
        headers.setdefault("content-type", "application/json")

    resp = await _http(core, url, method, headers, body,
                       timeout_s=float(params.get("timeout") or 30))
    out: dict[str, Any] = {"status": "ok", "http_status": resp.status,
                           "url": url}
    text = resp.text()
    try:
        parsed = json.loads(text)
        if protocol == "jsonrpc" and isinstance(parsed, dict):
            if parsed.get("error"):
                out["error_detail"] = parsed["error"]
            parsed = parsed.get("result", parsed)
        if protocol == "graphql" and isinstance(parsed, dict):
            if parsed.get("errors"):
                out["error_detail"] = parsed["errors"]
            parsed = parsed.get("data", parsed)
        out["body"] = parsed
    except json.JSONDecodeError:
        out["body"] = truncate_output(text, FETCH_MAX_CHARS)
    if resp.status >= 400:
        out["status"] = "error"
        out["error"] = f"HTTP {resp.status}"
    return out


# ---------------------------------------------------------------------------
# call_mcp
# ---------------------------------------------------------------------------

@register("call_mcp")
async def call_mcp_action(core, router, params: dict) -> dict:
    from quoracle_tpu.infra.mcp import MCPError
    mcp = core.deps.mcp
    if mcp is None:
        raise ActionError("no MCP servers configured")
    try:
        result = await mcp.call_tool(
            params["server"], params["tool"], params.get("arguments") or {},
            timeout_s=float(params["timeout"]) if params.get("timeout")
            else None, agent_id=core.agent_id)
    except (MCPError, asyncio.TimeoutError) as e:
        # surface the server's captured stderr tail into the agent-visible
        # error (reference error_context.ex) — a dying stdio server's last
        # words are usually the whole diagnosis
        ctx = mcp.error_context(params["server"])
        extra = f"\nserver stderr tail:\n{ctx}" if (
            ctx and "stderr tail" not in str(e)) else ""
        raise ActionError(f"call_mcp failed: {e}{extra}")
    # MCP results carry a content list; flatten text parts for the history
    content = (result or {}).get("content", [])
    texts = [c.get("text", "") for c in content if c.get("type") == "text"]
    raw = None
    if not texts:
        # Non-text content (screenshots, resources) can be megabytes of
        # base64 — cap it like every other world-facing payload.
        from quoracle_tpu.utils.normalize import to_json
        raw = truncate_output(to_json(result), FETCH_MAX_CHARS)
    return {"status": "error" if (result or {}).get("isError") else "ok",
            "server": params["server"], "tool": params["tool"],
            "content": truncate_output("\n".join(texts), FETCH_MAX_CHARS),
            "raw": raw}


# ---------------------------------------------------------------------------
# answer_engine
# ---------------------------------------------------------------------------

ANSWER_SOURCE_CHARS = 8_000      # per-source extraction cap
ANSWER_CONTEXT_CHARS = 28_000    # whole grounding block cap
_HREF = None                     # compiled lazily (regex import cost)


def _extract_result_links(html: str, base_url: str,
                          max_links: int) -> list[dict]:
    """Top-k result links from a search page: absolute http(s) hrefs (plus
    relative ones joined against the search URL), same-host navigation
    links dropped, deduped in page order, anchor text kept as the source
    title. Regex extraction — the HTTP seam's test fakes and real search
    pages both serve plain anchors."""
    global _HREF
    import re
    import urllib.parse
    if _HREF is None:
        _HREF = re.compile(
            r'<a\s[^>]*href=["\']([^"\']+)["\'][^>]*>(.*?)</a>',
            re.IGNORECASE | re.DOTALL)
    search_host = urllib.parse.urlparse(base_url).netloc
    out, seen = [], set()
    for href, anchor in _HREF.findall(html):
        # keep fragment-bearing result links; the fragment itself is
        # stripped (same page) so #-variants dedupe together
        url, _ = urllib.parse.urldefrag(
            urllib.parse.urljoin(base_url, href.strip()))
        parsed = urllib.parse.urlparse(url)
        if parsed.scheme not in ("http", "https"):
            continue
        if parsed.netloc == search_host or not parsed.netloc:
            continue                      # search-engine nav/self links
        if url in seen:
            continue
        seen.add(url)
        title = re.sub(r"<[^>]+>", "", anchor).strip()[:200]
        out.append({"url": url, "title": title})
        if len(out) >= max_links:
            break
    return out


@register("answer_engine")
async def answer_engine_action(core, router, params: dict) -> dict:
    """Grounded Q&A with PER-SOURCE extraction and citations (reference
    answer_engine.ex:1-52 — provider-side search grounding with source
    metadata): the search template's result page yields top-k result
    URLs, each is fetched CONCURRENTLY and extracted to markdown, the
    numbered source sections ground the on-device answer model, and the
    result carries per-source citation metadata. A search page with no
    extractable result links degrades to the old single-context mode
    (the page itself as grounding)."""
    from quoracle_tpu.models.runtime import QueryRequest
    query = params["query"]
    deps = core.deps
    sources: list[dict] = []
    context = ""
    numbered_grounding = False      # context holds "[n] ..." sections
    search_url = None
    max_sources = 3
    if deps.persistence is not None:
        search_url = deps.persistence.get_setting("answer_engine_search_url")
        try:
            max_sources = int(deps.persistence.get_setting(
                "answer_engine_max_sources") or 3)
        except (TypeError, ValueError):
            max_sources = 3
    if search_url and deps.http is not None:
        import urllib.parse
        url = search_url.replace("{query}", urllib.parse.quote(query))
        try:
            resp = await _http(core, url, timeout_s=20)
            page = resp.text() if resp.status < 400 else ""
        except Exception:
            page = ""
        links = (_extract_result_links(page, url, max_sources)
                 if page else [])

        async def fetch_one(link: dict) -> Optional[str]:
            try:
                if core.deps.ssrf_check:
                    # result links are CONTENT-DERIVED (a hostile search
                    # page could point at link-local metadata endpoints) —
                    # explicit pre-flight like fetch_web's, off-loop (DNS);
                    # _http only re-checks redirects for the default
                    # transport, so this guard must not depend on it
                    await asyncio.get_running_loop().run_in_executor(
                        None, check_ssrf, link["url"])
                r = await _http(core, link["url"], timeout_s=15)
                if r.status >= 400:
                    return None
                body = r.text()
                if "html" in r.content_type or body.lstrip()[:1] == "<":
                    body = html_to_markdown(body)
                return truncate_output(body, ANSWER_SOURCE_CHARS)
            except Exception:
                return None

        if links:
            extracts = await asyncio.gather(*(fetch_one(l) for l in links))
            blocks = []
            for i, (link, text) in enumerate(zip(links, extracts), 1):
                fetched = text is not None
                sources.append({"index": i, "url": link["url"],
                                "title": link["title"], "fetched": fetched})
                if fetched:
                    head = f"[{i}] {link['title'] or link['url']} " \
                           f"({link['url']})"
                    blocks.append(f"{head}\n{text}")
            context = truncate_output("\n\n".join(blocks),
                                      ANSWER_CONTEXT_CHARS)
            numbered_grounding = bool(blocks)
        if not context and page:
            # no result links (or every fetch failed): the search page
            # itself is the grounding, as before
            context = truncate_output(html_to_markdown(page), 20_000)
            sources = [{"index": 1, "url": url, "title": "search results",
                        "fetched": True}]
    answer_model = None
    if deps.persistence is not None:
        answer_model = deps.persistence.get_setting("answer_engine_model")
    answer_model = answer_model or core.config.model_pool[0]

    prompt = "Answer the question concisely and factually."
    if params.get("focus"):
        prompt += f" Focus: {params['focus']}."
    if numbered_grounding:
        prompt += (" Ground the answer in the numbered sources and cite "
                   "them inline as [n].")
    user = (f"Sources:\n{context}\n\nQuestion: {query}" if context
            else f"Question: {query}")
    loop = asyncio.get_running_loop()
    results = await loop.run_in_executor(None, lambda: deps.backend.query([
        QueryRequest(model_spec=answer_model, messages=[
            {"role": "system", "content": prompt},
            {"role": "user", "content": user}], temperature=0.3)]))
    res = results[0]
    if not res.ok:
        raise ActionError(f"answer engine query failed: {res.error}")
    if res.usage.cost:
        from quoracle_tpu.infra.costs import CostEntry
        deps.costs.record(CostEntry(
            agent_id=core.agent_id, task_id=core.config.task_id,
            amount=Decimal(str(res.usage.cost)), cost_type="model",
            model_spec=answer_model, input_tokens=res.usage.prompt_tokens,
            output_tokens=res.usage.completion_tokens,
            description="answer_engine"))
    return {"status": "ok", "answer": res.text, "model": answer_model,
            "sources": sources}


# ---------------------------------------------------------------------------
# generate_images
# ---------------------------------------------------------------------------

@register("generate_images")
async def generate_images_action(core, router, params: dict) -> dict:
    backend = core.deps.images
    if backend is None:
        raise ActionError("no image backend configured")
    loop = asyncio.get_running_loop()
    try:
        images = await loop.run_in_executor(None, lambda: backend.generate(
            params["prompt"], count=int(params.get("count") or 1),
            size=params.get("size") or "256x256",
            out_dir=core.config.working_dir))
    except ValueError as e:
        raise ActionError(str(e))
    total_cost = sum(i.cost for i in images)
    if total_cost:
        from quoracle_tpu.infra.costs import CostEntry
        core.deps.costs.record(CostEntry(
            agent_id=core.agent_id, task_id=core.config.task_id,
            amount=Decimal(str(total_cost)), cost_type="image",
            description=f"generate_images x{len(images)}"))
    return {"status": "ok",
            "images": [{"path": i.path, "model": i.model,
                        "width": i.width, "height": i.height}
                       for i in images]}
