"""Persistence facade: the hooks the agent runtime calls + durable stores.

Implements the write-through discipline of the reference (SURVEY.md §5
checkpoint/resume): agent row on init (reference Core.Persistence,
core.ex:479-484), conversation after every decision (reference
action_executor.ex:102-105), ACE state on terminate (core.ex:464-467), rows
deleted on dismissal (reference TreeTerminator deletes agents/logs/messages/
costs). The bus writer makes logs/messages/actions durable the way the
reference's Ecto inserts do, without the agents knowing about the DB.
"""

from __future__ import annotations

import json
import logging
import time
import uuid
from decimal import Decimal
from typing import Any, Optional

from quoracle_tpu.context.history import AgentContext, HistoryEntry, Lesson
from quoracle_tpu.infra.bus import EventBus, Subscription
from quoracle_tpu.infra.security import SecretStore
from quoracle_tpu.persistence.db import Database

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Serialization (reference agents.conversation_history / ace_state JSONB)
# ---------------------------------------------------------------------------


def serialize_context(ctx: AgentContext, children: list[dict]) -> str:
    return json.dumps({
        "model_histories": {
            m: [{"kind": e.kind, "content": e.content, "ts": e.ts,
                 "action_type": e.action_type} for e in entries]
            for m, entries in ctx.model_histories.items()
        },
        "context_lessons": {
            # Embeddings are NOT persisted (like KV caches, SURVEY.md §5) —
            # they re-embed lazily on the next dedup pass after resume.
            m: [{"type": l.type, "content": l.content,
                 "confidence": l.confidence} for l in lessons]
            for m, lessons in ctx.context_lessons.items()
        },
        "model_states": ctx.model_states,
        "todos": ctx.todos,
        "children": children,
        "context_summary": ctx.context_summary,
    })


def deserialize_context(raw: str) -> AgentContext:
    d = json.loads(raw or "{}")
    ctx = AgentContext()
    ctx.model_histories = {
        m: [HistoryEntry(kind=e["kind"], content=e["content"],
                         ts=e.get("ts", 0.0),
                         action_type=e.get("action_type"))
            for e in entries]
        for m, entries in d.get("model_histories", {}).items()
    }
    ctx.context_lessons = {
        m: [Lesson(type=l["type"], content=l["content"],
                   confidence=l.get("confidence", 1)) for l in lessons]
        for m, lessons in d.get("context_lessons", {}).items()
    }
    ctx.model_states = d.get("model_states", {})
    ctx.todos = d.get("todos", [])
    ctx.children = d.get("children", [])
    ctx.context_summary = d.get("context_summary")
    return ctx


def serialize_config(config: Any) -> str:
    import dataclasses
    d = dataclasses.asdict(config)
    d.pop("restored_context", None)
    if d.get("budget_limit") is not None:
        d["budget_limit"] = str(d["budget_limit"])
    return json.dumps(d)


def deserialize_config(raw: str) -> Any:
    from quoracle_tpu.agent.state import AgentConfig
    d = json.loads(raw)
    if d.get("budget_limit") is not None:
        d["budget_limit"] = Decimal(d["budget_limit"])
    for k in ("forbidden_actions", "profile_names",
              "accumulated_constraints", "active_skills"):
        if d.get(k) is not None:
            d[k] = tuple(d[k])
    return AgentConfig(**d)


# ---------------------------------------------------------------------------
# Durable secret store
# ---------------------------------------------------------------------------


class PersistentSecretStore(SecretStore):
    """SecretStore backed by the secrets/secret_usage tables; values
    AES-encrypted at rest via the DB vault (reference TableCredentials +
    Cloak Encrypted.Binary; audit trail reference audit/secret_usage.ex)."""

    def __init__(self, db: Database):
        super().__init__()
        self.db = db
        for row in db.query("SELECT * FROM secrets"):
            if row["encrypted"] and not db.vault.active:
                # Degraded boot without the key (reference
                # application.ex:25-36): the rest of the system keeps
                # working; this secret is just unavailable.
                logger.warning("secret %r is encrypted but no encryption "
                               "key is loaded; skipping", row["name"])
                continue
            value = db.vault.decrypt(row["value"], bool(row["encrypted"]))
            super().put(row["name"], value, row["description"] or "",
                        row["created_by"])

    def put(self, name, value, description="", created_by=None):
        secret = super().put(name, value, description, created_by)
        blob, enc = self.db.vault.encrypt(value)
        self.db.execute(
            "INSERT OR REPLACE INTO secrets "
            "(name, value, encrypted, description, created_by, created_at) "
            "VALUES (?,?,?,?,?,?)",
            (name, blob, int(enc), description, created_by, secret.created_at))
        return secret

    def delete(self, name):
        # the DB row can exist without an in-memory entry (degraded boot
        # skipped encrypted secrets); report what was actually destroyed
        row = self.db.query_one("SELECT name FROM secrets WHERE name=?",
                                (name,))
        existed = super().delete(name) or row is not None
        self.db.execute("DELETE FROM secrets WHERE name=?", (name,))
        return existed

    def lookup(self, name, *, agent_id="", action=""):
        value = super().lookup(name, agent_id=agent_id, action=action)
        if value is not None and agent_id:
            self.db.execute(
                "INSERT INTO secret_usage (secret_name, agent_id, action, ts)"
                " VALUES (?,?,?,?)", (name, agent_id, action, time.time()))
        return value


# ---------------------------------------------------------------------------
# Credential store
# ---------------------------------------------------------------------------


class CredentialStore:
    """Encrypted provider-credential records over the ``credentials`` table
    (reference CredentialManager/TableCredentials: per-model encrypted
    api_key + endpoint metadata, Cloak-encrypted at rest,
    models/credential_manager.ex + table_credentials.ex). On-device
    serving needs no API keys, so here credentials gate the OUTBOUND
    integrations — ``call_api`` auth and MCP server headers — with the
    same at-rest encryption and usage-audit treatment secrets get.

    A record's ``data`` is the auth payload (e.g. ``{"type": "bearer",
    "token": ...}`` or ``{"type": "header", "name": ..., "value": ...}``
    plus optional endpoint metadata); ``model_spec`` keeps the reference's
    per-model association for provider-style records."""

    def __init__(self, db: Database):
        self.db = db

    def put(self, cred_id: str, data: dict,
            model_spec: Optional[str] = None) -> None:
        if not cred_id or not isinstance(cred_id, str):
            raise ValueError("credential id must be a non-empty string")
        blob, enc = self.db.vault.encrypt(json.dumps(data))
        self.db.execute(
            "INSERT OR REPLACE INTO credentials "
            "(id, model_spec, data, encrypted) VALUES (?,?,?,?)",
            (cred_id, model_spec, blob, int(enc)))

    def get(self, cred_id: str, *, agent_id: str = "",
            action: str = "") -> Optional[dict]:
        row = self.db.query_one("SELECT * FROM credentials WHERE id=?",
                                (cred_id,))
        if row is None:
            return None
        if row["encrypted"] and not self.db.vault.active:
            logger.warning("credential %r is encrypted but no encryption "
                           "key is loaded", cred_id)
            return None
        data = json.loads(
            self.db.vault.decrypt(row["data"], bool(row["encrypted"])))
        if agent_id:   # audit trail, same table/shape as secret access
            self.db.execute(
                "INSERT INTO secret_usage (secret_name, agent_id, action, "
                "ts) VALUES (?,?,?,?)",
                (f"credential:{cred_id}", agent_id, action, time.time()))
        return data

    def for_model(self, model_spec: str) -> Optional[dict]:
        rows = self.db.query(
            "SELECT id FROM credentials WHERE model_spec=? ORDER BY id",
            (model_spec,))
        if not rows:
            return None
        if len(rows) > 1:
            # no UNIQUE constraint on model_spec — deterministic pick
            # (lowest id) instead of whichever row the engine returns first
            logger.warning(
                "%d credentials registered for model_spec=%r; using %r",
                len(rows), model_spec, rows[0]["id"])
        return self.get(rows[0]["id"])

    def delete(self, cred_id: str) -> bool:
        row = self.db.query_one("SELECT id FROM credentials WHERE id=?",
                                (cred_id,))
        self.db.execute("DELETE FROM credentials WHERE id=?", (cred_id,))
        return row is not None

    def list(self) -> list[dict]:
        """Metadata only — never the decrypted payloads."""
        return [{"id": r["id"], "model_spec": r["model_spec"],
                 "encrypted": bool(r["encrypted"])}
                for r in self.db.query(
                    "SELECT id, model_spec, encrypted FROM credentials "
                    "ORDER BY id")]


# ---------------------------------------------------------------------------
# Persistence facade
# ---------------------------------------------------------------------------


class Persistence:
    def __init__(self, db: Database):
        self.db = db
        self._bus_sub: Optional[Subscription] = None

    # -- agent hooks (called by AgentCore / AgentSupervisor) ---------------

    def persist_agent(self, core: Any) -> None:
        now = time.time()
        self.db.execute(
            "INSERT OR REPLACE INTO agents "
            "(agent_id, task_id, parent_id, status, config, ace_state, "
            " created_at, updated_at) VALUES (?,?,?,?,?,?,"
            " COALESCE((SELECT created_at FROM agents WHERE agent_id=?),?),?)",
            (core.agent_id, core.config.task_id, core.config.parent_id,
             "running", serialize_config(core.config),
             serialize_context(core.ctx, core.children),
             core.agent_id, now, now))

    def persist_conversation(self, core: Any) -> None:
        """After every decision/result (reference action_executor.ex:102-105
        persists conversation continuously)."""
        self.db.execute(
            "UPDATE agents SET ace_state=?, updated_at=? WHERE agent_id=?",
            (serialize_context(core.ctx, core.children), time.time(),
             core.agent_id))

    def persist_ace_state(self, core: Any) -> None:
        self.db.execute(
            "UPDATE agents SET ace_state=?, status=?, updated_at=? "
            "WHERE agent_id=?",
            (serialize_context(core.ctx, core.children), "stopped",
             time.time(), core.agent_id))

    def delete_agent(self, agent_id: str) -> None:
        """Dismissal cleanup (reference TreeTerminator deletes the agent's
        rows across agents/logs/messages/costs)."""
        self.db.execute("DELETE FROM agents WHERE agent_id=?", (agent_id,))
        self.db.execute("DELETE FROM logs WHERE agent_id=?", (agent_id,))
        self.db.execute("DELETE FROM agent_costs WHERE agent_id=?",
                        (agent_id,))
        self.db.execute("DELETE FROM actions WHERE agent_id=?", (agent_id,))
        self.db.execute("DELETE FROM consensus_audit WHERE agent_id=?",
                        (agent_id,))

    # -- costs (CostRecorder persist_fn) -----------------------------------

    def persist_cost(self, entry: Any) -> None:
        self.db.execute(
            "INSERT OR REPLACE INTO agent_costs "
            "(id, agent_id, task_id, amount, cost_type, model_spec, "
            " input_tokens, output_tokens, description, ts) "
            "VALUES (?,?,?,?,?,?,?,?,?,?)",
            (entry.id, entry.agent_id, entry.task_id, str(entry.amount),
             entry.cost_type, entry.model_spec, entry.input_tokens,
             entry.output_tokens, entry.description, entry.ts))

    def costs_for_task(self, task_id: str) -> Decimal:
        # Sum in Decimal: amounts are stored as text precisely so money math
        # never passes through floats (reference uses decimal(12,10)).
        rows = self.db.query(
            "SELECT amount FROM agent_costs WHERE task_id=?", (task_id,))
        return sum((Decimal(r["amount"]) for r in rows), Decimal("0"))

    def total_costs(self) -> Decimal:
        """Every recorded cost across all tasks (telemetry roll-up)."""
        rows = self.db.query("SELECT amount FROM agent_costs")
        return sum((Decimal(r["amount"]) for r in rows), Decimal("0"))

    def agent_spent(self, agent_id: str) -> Decimal:
        rows = self.db.query(
            "SELECT amount FROM agent_costs WHERE agent_id=?", (agent_id,))
        return sum((Decimal(r["amount"]) for r in rows), Decimal("0"))

    # -- consensus audit (ISSUE 5) -----------------------------------------

    def audit_for_task(self, task_id: str, limit: int = 200) -> list[dict]:
        """Durable consensus-audit records for one task, oldest first
        (the /api/consensus read model beyond the in-memory ring)."""
        rows = self.db.query(
            "SELECT record FROM consensus_audit WHERE task_id=? "
            "ORDER BY id DESC LIMIT ?", (task_id, limit))
        out = []
        for r in reversed(rows):
            try:
                out.append(json.loads(r["record"]))
            except (TypeError, json.JSONDecodeError):
                continue
        return out

    # -- tasks -------------------------------------------------------------

    def create_task_row(self, task_id: str, task_fields: dict,
                        agent_fields: dict) -> None:
        now = time.time()
        self.db.execute(
            "INSERT INTO tasks (id, status, task_fields, agent_fields, "
            "created_at, updated_at) VALUES (?,?,?,?,?,?)",
            (task_id, "running", json.dumps(task_fields),
             json.dumps(agent_fields), now, now))

    def set_task_status(self, task_id: str, status: str) -> None:
        self.db.execute("UPDATE tasks SET status=?, updated_at=? WHERE id=?",
                        (status, time.time(), task_id))

    def get_task(self, task_id: str) -> Optional[dict]:
        row = self.db.query_one("SELECT * FROM tasks WHERE id=?", (task_id,))
        if row is None:
            return None
        return {"id": row["id"], "status": row["status"],
                "task_fields": json.loads(row["task_fields"]),
                "agent_fields": json.loads(row["agent_fields"]),
                "created_at": row["created_at"],
                "updated_at": row["updated_at"]}

    def list_tasks(self, status: Optional[str] = None) -> list[dict]:
        rows = (self.db.query("SELECT id FROM tasks WHERE status=?", (status,))
                if status else self.db.query("SELECT id FROM tasks"))
        return [t for t in (self.get_task(r["id"]) for r in rows) if t]

    def agents_for_task(self, task_id: str) -> list[dict]:
        rows = self.db.query(
            "SELECT * FROM agents WHERE task_id=? ORDER BY created_at",
            (task_id,))
        return [{"agent_id": r["agent_id"], "parent_id": r["parent_id"],
                 "status": r["status"],
                 "config": deserialize_config(r["config"]),
                 "context": deserialize_context(r["ace_state"])}
                for r in rows]

    # -- profiles / settings (reference TableProfiles, ConfigModelSettings) -

    def save_profile(self, name: str, data: dict) -> None:
        self.db.execute(
            "INSERT OR REPLACE INTO profiles (name, data) VALUES (?,?)",
            (name, json.dumps(data)))

    def get_profile(self, name: str) -> Optional[dict]:
        row = self.db.query_one("SELECT data FROM profiles WHERE name=?",
                                (name,))
        return json.loads(row["data"]) if row else None

    def list_profiles(self) -> list[str]:
        return [r["name"] for r in
                self.db.query("SELECT name FROM profiles ORDER BY name")]

    def delete_profile(self, name: str) -> bool:
        existed = self.get_profile(name) is not None
        self.db.execute("DELETE FROM profiles WHERE name=?", (name,))
        return existed

    def set_setting(self, key: str, value: Any) -> None:
        self.db.execute(
            "INSERT OR REPLACE INTO model_settings (key, value) VALUES (?,?)",
            (key, json.dumps(value)))

    def get_setting(self, key: str, default: Any = None) -> Any:
        row = self.db.query_one(
            "SELECT value FROM model_settings WHERE key=?", (key,))
        return json.loads(row["value"]) if row else default

    def all_settings(self) -> dict:
        return {r["key"]: json.loads(r["value"]) for r in
                self.db.query("SELECT key, value FROM model_settings "
                              "ORDER BY key")}

    # -- durable event log (bus → logs/messages/actions rows) --------------

    def attach_bus(self, bus: EventBus) -> Subscription:
        """Tail every broadcast into the durable tables — the reference's
        Ecto inserts for logs/messages/actions, decoupled from agents."""
        self._bus_sub = bus.subscribe("*", self._on_event)
        return self._bus_sub

    def _on_event(self, topic: str, event: dict) -> None:
        kind = event.get("event")
        ts = event.get("ts", time.time())
        if kind in ("log", "decision", "raw_response"):
            data = {k: v for k, v in event.items()
                    if k not in ("event", "ts", "agent_id", "message",
                                 "level")}
            self.db.execute(
                "INSERT INTO logs (agent_id, level, message, data, ts) "
                "VALUES (?,?,?,?,?)",
                (event.get("agent_id"), event.get("level", kind),
                 event.get("message", kind),
                 json.dumps(data, default=str), ts))
        elif kind == "task_message":
            m = event.get("message", {})
            self.db.execute(
                "INSERT INTO messages (task_id, sender, content, "
                "message_type, targets, ts) VALUES (?,?,?,?,?,?)",
                (event.get("task_id"), m.get("from"),
                 json.dumps(m.get("content"), default=str),
                 m.get("message_type"),
                 json.dumps(m.get("targets", []), default=str), ts))
        elif kind == "action_started":
            self.db.execute(
                "INSERT OR REPLACE INTO actions (action_id, agent_id, "
                "action, params, status, started_at) VALUES (?,?,?,?,?,?)",
                (event.get("action_id"), event.get("agent_id"),
                 event.get("action"),
                 json.dumps(event.get("params", {}), default=str),
                 "running", ts))
        elif kind == "action_completed":
            self.db.execute(
                "UPDATE actions SET status=?, completed_at=? "
                "WHERE action_id=?",
                (event.get("status", "ok"), ts, event.get("action_id")))
        elif kind == "consensus_audit":
            # Per-decide audit record (ISSUE 5, consensus/quality.py):
            # durable alongside the decision logs, keyed by task for
            # /api/consensus?task_id=… deep history (the EventHistory
            # ring covers the live tail).
            self.db.execute(
                "INSERT INTO consensus_audit "
                "(task_id, agent_id, decide_id, ts, record) "
                "VALUES (?,?,?,?,?)",
                (event.get("task_id"), event.get("agent_id"),
                 event.get("decide_id"), ts,
                 json.dumps(event, default=str)))

    def detach_bus(self) -> None:
        if self._bus_sub is not None:
            self._bus_sub.unsubscribe()
            self._bus_sub = None


def new_task_id() -> str:
    return f"task-{uuid.uuid4().hex[:12]}"
