"""Task lifecycle: create / pause / restore / boot revival.

Parity with the reference's Tasks.TaskManager (create → insert row + spawn
root agent, reference tasks/task_manager.ex:39-92), TaskRestorer (pause =
status "pausing" → leaves-first stop → "paused"; restore rebuilds the agent
tree from rows, reference tasks/task_restorer.ex:31-80) and
Boot.AgentRevival (restore running tasks at boot, finalize stale "pausing" →
"paused", reference boot/agent_revival.ex:27-84,124-141).
"""

from __future__ import annotations

import logging
from decimal import Decimal
from typing import Any, Optional

from quoracle_tpu.agent.registry import AlreadyRegisteredError
from quoracle_tpu.agent.state import AgentConfig, AgentDeps, new_agent_id
from quoracle_tpu.persistence.store import Persistence, new_task_id

logger = logging.getLogger(__name__)


class TaskManager:
    """Entry point for task-level operations. Holds the same deps object the
    agents run with; the supervisor inside deps owns the actual actors."""

    def __init__(self, deps: AgentDeps, persistence: Persistence):
        self.deps = deps
        self.store = persistence
        deps.persistence = persistence

    # ------------------------------------------------------------------

    def resolve_profile(self, profile: Optional[str]) -> dict:
        """Profile → model_pool / capability_groups / refinement config
        (reference profiles/resolver.ex — task creation REQUIRES a resolvable
        profile when one is named)."""
        if profile is None:
            return {}
        data = self.store.get_profile(profile)
        if data is None:
            raise ValueError(f"unknown profile {profile!r}")
        return data

    async def create_task(
        self, description: Optional[str] = None, *,
        model_pool: Optional[list[str]] = None,
        profile: Optional[str] = None,
        budget: Optional[str] = None,
        system_prompt: Optional[str] = None,
        working_dir: str = "/tmp",
        grove: Optional[str] = None,
        task_fields: Optional[dict] = None,
        tenant: str = "default",
    ) -> tuple[str, Any]:
        """Create the task row, spawn the root agent, deliver the initial
        message (reference task_manager.ex:39-92). With ``grove`` (a grove
        directory), the manifest's bootstrap pre-fills the missing fields
        and the root agent becomes the topology root node (reference
        BootstrapResolver + grove selector in the new-task modal). Returns
        (task_id, root core)."""
        prof = self.resolve_profile(profile)
        pool = model_pool or prof.get("model_pool")
        if not pool:
            raise ValueError("a model_pool is required (directly or via "
                             "profile)")

        enforcer = root_node = None
        governance_docs = None
        forbidden: tuple[str, ...] = ()
        active_skills: tuple[str, ...] = ()
        if grove is not None:
            from quoracle_tpu.governance.fields import (
                AgentFields, compose_field_prompt,
            )
            from quoracle_tpu.governance.grove import (
                GroveEnforcer, load_grove,
            )
            enforcer = GroveEnforcer(load_grove(grove))
            boot = enforcer.bootstrap_fields()
            root_node = enforcer.manifest.root_node
            description = description or boot.get("task_description")
            active_skills = tuple(boot.get("skills") or ())
            governance_docs = enforcer.governance_docs_for(root_node)
            forbidden = tuple(sorted(enforcer.blocked_actions(root_node)))
            ws = enforcer.workspace_dir()
            if ws:
                import os
                os.makedirs(ws, exist_ok=True)
                working_dir = ws
            if system_prompt is None:
                system_prompt = compose_field_prompt(AgentFields(
                    role=boot.get("role"),
                    cognitive_style=boot.get("cognitive_style"),
                    global_context=boot.get("global_context"),
                    delegation_strategy=boot.get("delegation_strategy"),
                ))
            if boot.get("success_criteria") and description:
                description = (f"{description}\n\n[SUCCESS CRITERIA]\n"
                               f"{boot['success_criteria']}")
        if not description:
            raise ValueError("a task description is required (directly or "
                             "via the grove bootstrap)")

        task_id = new_task_id()
        self.store.create_task_row(task_id, task_fields or
                                   {"description": description},
                                   {"profile": profile,
                                    "model_pool": pool,
                                    "budget": budget,
                                    "grove": grove})
        config = AgentConfig(
            agent_id=new_agent_id(),
            task_id=task_id,
            model_pool=list(pool),
            profile=profile,
            profile_description=prof.get("description"),
            capability_groups=prof.get("capability_groups"),
            forbidden_actions=forbidden,
            max_refinement_rounds=prof.get("max_refinement_rounds", 4),
            force_reflection=prof.get("force_reflection", False),
            field_system_prompt=system_prompt,
            profile_names=tuple(self.store.list_profiles()),
            grove_path=grove,
            grove_node=root_node,
            governance_docs=governance_docs,
            active_skills=active_skills,
            budget_mode="root" if budget is not None else "na",
            budget_limit=Decimal(budget) if budget is not None else None,
            working_dir=working_dir,
            # QoS (ISSUE 4): the whole agent tree bills its model rows to
            # the creating tenant (dashboard: bearer token → tenant)
            tenant=tenant,
        )
        root = await self.deps.supervisor.start_agent(config)
        root.post({"type": "user_message", "content": description,
                   "from": "user"})
        self.deps.events.task_status_changed(task_id, "running")
        return task_id, root

    # ------------------------------------------------------------------

    async def pause_task(self, task_id: str) -> int:
        """Graceful pause: leaves-first stop_requested; each agent persists
        its ACE state in terminate (reference task_restorer.ex:31-80)."""
        self.store.set_task_status(task_id, "pausing")
        self.deps.events.task_status_changed(task_id, "pausing")
        stopped = await self.deps.supervisor.stop_all(task_id, reason="pause")
        # Late-registration sweep: a spawn that raced the pause may have
        # registered after stop_all collected (reference task_restorer late
        # sweep); stop again until quiescent.
        while self.deps.registry.agents_for_task(task_id):
            stopped += await self.deps.supervisor.stop_all(task_id,
                                                           reason="pause")
        self.store.set_task_status(task_id, "paused")
        self.deps.events.task_status_changed(task_id, "paused")
        return stopped

    async def restore_task(self, task_id: str) -> int:
        """Rebuild the agent tree from persisted rows, parents before
        children; agents resume idle with their histories and wake on the
        next message (KV caches re-prefill from history — SURVEY.md §5)."""
        task = self.store.get_task(task_id)
        if task is None:
            raise ValueError(f"unknown task {task_id!r}")
        rows = self.store.agents_for_task(task_id)
        by_id = {r["agent_id"]: r for r in rows}

        def depth(row: dict) -> int:
            d, cur = 0, row
            while cur and cur["parent_id"]:
                cur = by_id.get(cur["parent_id"])
                d += 1
            return d

        restored = 0
        for row in sorted(rows, key=depth):
            config = row["config"]
            config.restored_context = row["context"]
            try:
                await self.deps.supervisor.start_agent(config)
            except AlreadyRegisteredError:
                # ConflictResolver parity: already live (double restore) —
                # leave the live one alone.
                continue
            # Escrow books rebuild parent-first: children re-lock against
            # their parent, roots re-register, and historical spend returns
            # from the agent_costs ledger. This runs before the agent's own
            # run-task gets a loop slot, so its lazy register never races.
            escrow = self.deps.escrow
            if config.budget_limit is not None and config.parent_id:
                try:
                    escrow.lock_for_child(config.parent_id, config.agent_id,
                                          config.budget_limit)
                except Exception:
                    logger.warning("escrow re-lock failed for %s",
                                   config.agent_id)
            else:
                try:
                    escrow.get(config.agent_id)
                except KeyError:
                    escrow.register(config.agent_id, config.budget_mode,
                                    config.budget_limit)
            spent = self.store.agent_spent(config.agent_id)
            if spent:
                try:
                    escrow.record_spend(config.agent_id, spent)
                except KeyError:
                    pass
            self.store.db.execute(
                "UPDATE agents SET status='running' WHERE agent_id=?",
                (config.agent_id,))
            restored += 1
        self.store.set_task_status(task_id, "running")
        self.deps.events.task_status_changed(task_id, "running")
        return restored

    # ------------------------------------------------------------------

    async def boot_revival(self) -> dict:
        """Boot-time revival (reference agent_revival.ex:27-84): finalize
        stale 'pausing' tasks to 'paused', then restore every 'running' task
        sequentially and failure-isolated."""
        for task in self.store.list_tasks("pausing"):
            self.store.set_task_status(task["id"], "paused")
        revived, failed = [], []
        for task in self.store.list_tasks("running"):
            try:
                await self.restore_task(task["id"])
                revived.append(task["id"])
            except Exception:
                logger.exception("revival of task %s failed", task["id"])
                failed.append(task["id"])
        return {"revived": revived, "failed": failed}
