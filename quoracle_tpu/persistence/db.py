"""SQLite database: schema + thread-safe access + at-rest encryption.

Schema mirrors the reference's migration set (reference
priv/repo/migrations/: agents 20251001000001, actions 20250122000002,
secret_usage 20251025014144, model_settings 20251205064131, profiles
20260105050308) with Postgres types mapped to SQLite: JSONB → JSON text,
decimal(12,10) → text (Decimal round-trips through str), binary_id → hex.

Encryption: secret/credential values encrypt with AES-256-GCM, key from
``QUORACLE_ENCRYPTION_KEY`` (the reference's Cloak vault +
CLOAK_ENCRYPTION_KEY, reference lib/quoracle/vault.ex, application.ex:25-36).
Without the env var the store runs degraded (plaintext + warning), exactly
like the reference boots without its key.
"""

from __future__ import annotations

import base64
import hashlib
import logging
import os
import sqlite3
import threading
from typing import Any, Iterable, Optional

logger = logging.getLogger(__name__)

SCHEMA = """
CREATE TABLE IF NOT EXISTS tasks (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL DEFAULT 'running',   -- running | pausing | paused | completed
    task_fields TEXT NOT NULL DEFAULT '{}',
    agent_fields TEXT NOT NULL DEFAULT '{}',
    created_at REAL, updated_at REAL
);
CREATE TABLE IF NOT EXISTS agents (
    agent_id TEXT PRIMARY KEY,
    task_id TEXT NOT NULL,
    parent_id TEXT,
    status TEXT NOT NULL DEFAULT 'running',
    config TEXT NOT NULL DEFAULT '{}',
    ace_state TEXT NOT NULL DEFAULT '{}',     -- model_histories + lessons + states
    created_at REAL, updated_at REAL
);
CREATE INDEX IF NOT EXISTS idx_agents_task ON agents(task_id);
CREATE TABLE IF NOT EXISTS logs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    agent_id TEXT, level TEXT, message TEXT, data TEXT, ts REAL
);
CREATE INDEX IF NOT EXISTS idx_logs_agent ON logs(agent_id);
CREATE TABLE IF NOT EXISTS messages (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    task_id TEXT, sender TEXT, content TEXT, message_type TEXT,
    targets TEXT, ts REAL
);
CREATE INDEX IF NOT EXISTS idx_messages_task ON messages(task_id);
CREATE TABLE IF NOT EXISTS actions (
    action_id TEXT PRIMARY KEY,
    agent_id TEXT, action TEXT, params TEXT,
    status TEXT, result TEXT,
    started_at REAL, completed_at REAL
);
CREATE INDEX IF NOT EXISTS idx_actions_agent ON actions(agent_id);
CREATE TABLE IF NOT EXISTS agent_costs (
    id TEXT PRIMARY KEY,
    agent_id TEXT, task_id TEXT,
    amount TEXT, cost_type TEXT, model_spec TEXT,
    input_tokens INTEGER, output_tokens INTEGER,
    description TEXT, ts REAL
);
CREATE INDEX IF NOT EXISTS idx_costs_agent ON agent_costs(agent_id);
CREATE TABLE IF NOT EXISTS secrets (
    name TEXT PRIMARY KEY,
    value BLOB NOT NULL,               -- AES-256-GCM (nonce || ciphertext)
    encrypted INTEGER NOT NULL DEFAULT 0,
    description TEXT, created_by TEXT, created_at REAL
);
CREATE TABLE IF NOT EXISTS secret_usage (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    secret_name TEXT, agent_id TEXT, action TEXT, ts REAL
);
CREATE TABLE IF NOT EXISTS credentials (
    id TEXT PRIMARY KEY,
    model_spec TEXT, data BLOB, encrypted INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS profiles (
    name TEXT PRIMARY KEY,
    data TEXT NOT NULL DEFAULT '{}'    -- model_pool, capability_groups,
                                       -- max_refinement_rounds, force_reflection
);
CREATE TABLE IF NOT EXISTS model_settings (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL DEFAULT 'null' -- JSON
);
CREATE TABLE IF NOT EXISTS consensus_audit (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    task_id TEXT, agent_id TEXT, decide_id TEXT,
    ts REAL,
    record TEXT NOT NULL DEFAULT '{}'  -- the full audit record (JSON):
                                       -- member->cluster map, winner,
                                       -- entropy, margin, failures by kind
);
CREATE INDEX IF NOT EXISTS idx_consensus_audit_task
    ON consensus_audit(task_id);
"""


class Vault:
    """AES-256-GCM envelope for at-rest values (reference Cloak vault)."""

    def __init__(self, key: Optional[str] = None):
        raw = key if key is not None else os.environ.get(
            "QUORACLE_ENCRYPTION_KEY")
        self._aes = None
        if raw:
            try:
                from cryptography.hazmat.primitives.ciphers.aead import AESGCM
                self._aes = AESGCM(self._derive(raw))
            except ImportError:
                logger.warning("cryptography unavailable; secrets stored "
                               "in plaintext (degraded mode)")
        else:
            logger.warning("QUORACLE_ENCRYPTION_KEY not set; secrets stored "
                           "in plaintext (degraded mode)")

    @staticmethod
    def _derive(raw: str) -> bytes:
        try:
            decoded = base64.b64decode(raw, validate=True)
            if len(decoded) == 32:
                return decoded
        except Exception:
            pass
        return hashlib.sha256(raw.encode()).digest()

    @property
    def active(self) -> bool:
        return self._aes is not None

    def encrypt(self, plaintext: str) -> tuple[bytes, bool]:
        """Returns (blob, encrypted?)."""
        if self._aes is None:
            return plaintext.encode(), False
        nonce = os.urandom(12)
        return nonce + self._aes.encrypt(nonce, plaintext.encode(), None), True

    def decrypt(self, blob: bytes, encrypted: bool) -> str:
        if not encrypted:
            return bytes(blob).decode()
        if self._aes is None:
            raise RuntimeError("encrypted value but no encryption key loaded")
        blob = bytes(blob)
        return self._aes.decrypt(blob[:12], blob[12:], None).decode()


class Database:
    """One SQLite connection, serialized by a lock. Writes come from the
    event loop and executor threads; SQLite itself is fast enough at this
    event rate that a single serialized connection beats connection-pool
    complexity. WAL mode keeps readers unblocked."""

    def __init__(self, path: str = ":memory:",
                 encryption_key: Optional[str] = None):
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = threading.Lock()
        self.vault = Vault(encryption_key)
        with self._lock:
            if path != ":memory:":
                self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA foreign_keys=ON")
            self._conn.executescript(SCHEMA)
            self._conn.commit()

    def execute(self, sql: str, params: Iterable[Any] = ()) -> None:
        with self._lock:
            self._conn.execute(sql, tuple(params))
            self._conn.commit()

    def executemany(self, sql: str, rows: list[tuple]) -> None:
        with self._lock:
            self._conn.executemany(sql, rows)
            self._conn.commit()

    def query(self, sql: str, params: Iterable[Any] = ()) -> list[sqlite3.Row]:
        with self._lock:
            return self._conn.execute(sql, tuple(params)).fetchall()

    def query_one(self, sql: str,
                  params: Iterable[Any] = ()) -> Optional[sqlite3.Row]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def close(self) -> None:
        with self._lock:
            self._conn.close()
