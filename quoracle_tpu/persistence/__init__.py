"""Persistence: SQLite-backed durable state + task lifecycle.

Re-design of the reference's Ecto/PostgreSQL layer (reference
lib/quoracle/repo.ex + priv/repo/migrations/ — tables tasks, agents, logs,
messages, actions, credentials, secrets, secret_usage, profiles,
model_settings, agent_costs; SURVEY.md §2.10/§5 checkpoint-resume) on
SQLite: same tables, JSONB columns become JSON text, AES-256-GCM at-rest
encryption for secret values (the reference's Cloak vault), and the same
continuous-persistence discipline — conversation after every decision, ACE
state on terminate, boot revival of running tasks.
"""

from quoracle_tpu.persistence.db import Database
from quoracle_tpu.persistence.store import Persistence
from quoracle_tpu.persistence.tasks import TaskManager

__all__ = ["Database", "Persistence", "TaskManager"]
