"""Compat shim (ISSUE 19): the draft trainer grew into the serving
flywheel's training plane and lives at
:mod:`quoracle_tpu.training.draft_check` — the pjit data-parallel step
itself is :mod:`quoracle_tpu.training.trainer`. This module keeps the
historical entry point stable:

    python -m quoracle_tpu.tools.train_draft --check

and ``run_check``/``main`` importable from here (the tier-1 contract in
tests/test_train_draft_check.py and run_live_bench.sh's bonus capture
both use this path).
"""

from __future__ import annotations

from quoracle_tpu.training.draft_check import main, run_check

__all__ = ["main", "run_check"]

if __name__ == "__main__":
    main()
