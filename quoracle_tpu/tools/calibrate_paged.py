"""Measure the gather/direct paged-path crossover ON THIS HOST and write
the engine's gate file (utils/calibration.py; VERDICT r3 weak #2 — the
gate must be a measurement, not a hardcoded constant).

For each resident size in the sweep, times resumed rounds under the
unified (ISSUE 8 ragged kernel), gather, direct_decode, and direct_full
paths (tools/bench_longctx.py harness). The smallest resident size where
a direct path's p50 beats gather becomes its ``*_min_resident`` gate; a
path that never wins stays null (off). The UNIFIED gate works the other
way around — the kernel is the TPU default without a file, so the sweep
records where gather is the better fallback: unified winning at the
smallest size writes 0 (explicit always-on), losing everywhere writes
null (gather is the measured default on this host). Writes the file the
engine loads at startup (~/.cache/quoracle_tpu/paged_gates.json, or
--out / QUORACLE_PAGED_CALIB).

Run on the serving host (ONE python process on TPU deployments):

    PYTHONPATH=/root/repo:/root/.axon_site python -m \
        quoracle_tpu.tools.calibrate_paged --sweep 1024 4096 16384

``--prefer-memory`` enables a direct path at its smallest MEASURED size
even when it loses on latency (within --latency-slack), for deployments
where peak HBM matters more than p50.
"""

from __future__ import annotations

import argparse
import gc
import json
import sys


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", type=int, nargs="+",
                    default=[1024, 4096, 16384])
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--scale", default="1b", choices=["1b", "tiny"])
    ap.add_argument("--out", default=None,
                    help="gate file path (default: the engine's load path)")
    ap.add_argument("--prefer-memory", action="store_true",
                    help="enable direct paths for peak-HBM reasons even "
                         "when they lose on latency within --latency-slack")
    ap.add_argument("--latency-slack", type=float, default=1.25,
                    help="with --prefer-memory: max direct/gather p50 "
                         "ratio still considered acceptable")
    args = ap.parse_args()

    import jax

    from quoracle_tpu.tools.bench_longctx import build_engine, measure_paths
    from quoracle_tpu.utils.calibration import save_paged_gates
    from quoracle_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    sweep = sorted(args.sweep)
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    log(f"calibrating on {device_kind}; sweep {sweep}")

    by_size = {}
    for resident in sweep:
        log(f"--- resident {resident} ---")
        # fresh engine PER size: one engine sized for sweep[-1] would
        # bucket-pad mid-sweep gather rounds to the largest size,
        # inflating gather ~sweep[-1]/resident× and writing gates that
        # enable the direct paths where properly-bucketed gather wins.
        # Pool budget 2 GiB (not the serving default 8 GiB): the session
        # only ever holds ~resident+rounds·new tokens, and two engines
        # briefly coexist between sweep sizes — 1b weights + a 32·max_seq
        # token pool each OOMed a 16 GB v5e at the 4096 step.
        eng, tok = build_engine(resident, args.rounds, args.new_tokens,
                                args.scale, session_max_bytes=2 << 30)
        by_size[resident] = measure_paths(
            eng, tok, resident, args.rounds, args.new_tokens)
        # Free this size's weights + pool BEFORE the next build: the jit
        # caches keep executables (and through them donated-buffer aliases)
        # alive past `del`, and GC alone is too lazy to beat the next
        # engine's allocation to the HBM.
        del eng, tok
        gc.collect()
        jax.clear_caches()
        gc.collect()

    def crossover(path: str):
        for resident in sweep:
            r = by_size[resident]
            ratio = (r[path]["p50_round_ms"]
                     / max(1e-9, r["gather"]["p50_round_ms"]))
            if ratio <= 1.0:
                return resident
            if args.prefer_memory and ratio <= args.latency_slack:
                return resident
        return None

    decode_gate = crossover("direct_decode")
    full_gate = crossover("direct_full")
    # UNIFIED ragged kernel (ISSUE 8): measured unified-vs-gather per
    # geometry. The engine's default is ON (threshold 0) on TPU without a
    # file, so the calibration's job here is the REVERSE of the direct
    # gates': record where gather is the better fallback. Unified winning
    # at the smallest sweep size → gate 0 (always on, making the measured
    # default explicit); winning only above some size → that size;
    # losing everywhere → explicit off (JSON null — gather is the
    # measured default on this host).
    unified_gate = crossover("unified")
    if unified_gate == sweep[0]:
        unified_gate = 0
    # The engine's use_direct_pre requires use_direct (the gather decode
    # cannot read what the direct prefill wrote without a working cache),
    # so a winning direct_full must PULL THE DECODE GATE DOWN to its own
    # crossover — otherwise the measured-as-winning path is unreachable.
    prefill_gate = full_gate
    if full_gate is not None and (decode_gate is None
                                  or decode_gate > full_gate):
        decode_gate = full_gate

    note = "; ".join(
        f"resident {r}: " + ", ".join(
            f"{p}={v['p50_round_ms']:.0f}ms" for p, v in res.items())
        for r, res in by_size.items())
    path = save_paged_gates(
        args.out, decode_min_resident=decode_gate,
        prefill_min_resident=prefill_gate,
        unified_min_resident=unified_gate, device_kind=device_kind,
        note=note)
    summary = {
        "metric": "paged_gate_calibration",
        "decode_min_resident": decode_gate,
        "prefill_min_resident": prefill_gate,
        "unified_min_resident": unified_gate,
        "gate_file": path,
        "device_kind": device_kind,
        "measurements": {str(k): {p: v["p50_round_ms"]
                                  for p, v in r.items()}
                         for k, r in by_size.items()},
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
