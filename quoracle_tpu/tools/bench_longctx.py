"""Long-context decode micro-benchmark: ragged paged kernel vs gather.

The main bench (bench.py) measures consensus rounds at ~1-2k resident
tokens, where the fused gather decode wins (the ragged kernel pays ~16
pallas launches per token — models/generate.py `direct_decode_min_tokens`
gate). This tool measures the regime the kernel exists for: a LONG
resident session resumed for short decodes, where the gather path
materializes a [B, maxp·page] working cache and attends over the padded
length every step while the kernel reads only the row's real pages.

Run on the TPU host (ONE python process; keeps /root/.axon_site on
PYTHONPATH):

    PYTHONPATH=/root/repo:/root/.axon_site python -m \
        quoracle_tpu.tools.bench_longctx --resident 16384 --rounds 4

Prints one JSON line: p50 resumed-round ms for each decode path at the
given resident size. Uses the bench llama-1b checkpoint with a widened
catalog window (perf measurement only — RoPE beyond the family's trained
window is numerically fine and irrelevant to timing).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--resident", type=int, default=16384,
                    help="target resident session size in tokens")
    ap.add_argument("--rounds", type=int, default=4,
                    help="timed resumed rounds per path")
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--scale", default="1b", choices=["1b", "tiny"])
    args = ap.parse_args()

    import jax

    from quoracle_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    from quoracle_tpu.models.config import register_model
    from quoracle_tpu.models.generate import GenerateEngine
    from quoracle_tpu.models.loader import (
        load_params, register_hf_checkpoint, to_device,
    )
    from quoracle_tpu.models.make_checkpoint import make_checkpoint
    from quoracle_tpu.models.tokenizer import get_tokenizer

    root = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "checkpoints")
    ckpt = make_checkpoint(os.path.join(root, f"llama-{args.scale}"),
                           family="llama", scale=args.scale)
    base = register_hf_checkpoint(ckpt, name="longctx-base")
    max_seq = args.resident + 4 * args.new_tokens * (args.rounds + 2) + 1024
    cfg = register_model(dataclasses.replace(
        base, name="longctx", context_window=max_seq))
    tok = get_tokenizer("xla:longctx")
    params = to_device(load_params(ckpt, cfg))
    eng = GenerateEngine(
        cfg, params, tok, max_seq=max_seq,
        prompt_buckets=(1024, args.resident, max_seq),
        session_max_bytes=8 << 30)
    log(f"engine ready; resident target {args.resident} tokens")

    # Build the resident session with one long prefill.
    filler = ("The quick brown fox jumps over the lazy dog. "
              "Numbers: 0123456789. ")
    ids = tok.encode(filler)
    prompt = (ids * (args.resident // len(ids) + 1))[:args.resident - 1]
    prompt = [tok.bos_id] + prompt
    t0 = time.monotonic()
    r = eng.generate([prompt], temperature=0.0,
                     max_new_tokens=args.new_tokens, session_ids=["s"])[0]
    log(f"prefill of {len(prompt)} tokens: {time.monotonic() - t0:.1f}s")

    results = {}
    conv = list(prompt) + r.token_ids
    for path, setup in (("gather", lambda: setattr(
            eng, "_force_gather_decode", True)),
            ("direct_kernel", lambda: (
                setattr(eng, "_force_gather_decode", False),
                setattr(eng, "direct_decode_min_tokens", 0)))):
        setup()
        lats = []
        for i in range(args.rounds + 1):       # first = warmup/compile
            nxt = conv + tok.encode(f" continue {path} {i}.")
            t0 = time.monotonic()
            rr = eng.generate([nxt], temperature=0.0,
                              max_new_tokens=args.new_tokens,
                              session_ids=["s"])[0]
            lats.append((time.monotonic() - t0) * 1000)
            conv = nxt + rr.token_ids
            log(f"{path} round {i}: {lats[-1]:.0f}ms "
                f"(reused {rr.n_cached_tokens} tokens)")
        results[path] = {
            "p50_round_ms": statistics.median(lats[1:]),
            "rounds": args.rounds,
        }

    print(json.dumps({
        "metric": "longctx_resumed_round_p50",
        "resident_tokens": args.resident,
        "new_tokens_per_round": args.new_tokens,
        **{f"{k}_p50_ms": round(v["p50_round_ms"], 1)
           for k, v in results.items()},
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
