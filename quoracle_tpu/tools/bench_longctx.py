"""Long-context resumed-round micro-benchmark: paged kernels vs gather.

The main bench (bench.py) measures consensus rounds at ~1-2k resident
tokens, where the fused gather decode wins on hosts with expensive kernel
launches (models/generate.py paged gates; utils/calibration.py). This
tool measures the regime the paged kernels exist for: a LONG resident
session resumed for short rounds, where the gather path materializes a
[B, maxp·page] working cache and attends over the padded length while the
kernels read only the row's real pages. Three paths:

  gather          — working-cache gather prefill + gather decode
  direct_decode   — gather prefill, ragged-kernel decode (r3 path)
  direct_full     — paged prefill (suffix chunk vs pages in place,
                    VERDICT r4 item 2) + ragged-kernel decode: no
                    [B, maxp·page] materialization anywhere in the call

Per path it reports p50 resumed-round latency and the allocator's peak
HBM. The peak counter is cumulative per process, so paths run in
ascending expected-footprint order (direct_full first) — each row's
reported peak is the high-water AFTER that path; a jump attributes to it.

Run on the TPU host (ONE python process; keep /root/.axon_site on
PYTHONPATH):

    PYTHONPATH=/root/repo:/root/.axon_site python -m \
        quoracle_tpu.tools.bench_longctx --resident 16384 --rounds 4

tools/calibrate_paged.py reuses measure_paths() to find each path's
crossover on the current host and writes the engine's gate file.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def peak_hbm_gb() -> float | None:
    import jax
    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return round(peak / 1e9, 3) if peak else None


def build_engine(resident: int, rounds: int, new_tokens: int, scale: str,
                 session_max_bytes: int = 8 << 30):
    from quoracle_tpu.models.config import register_model
    from quoracle_tpu.models.generate import GenerateEngine
    from quoracle_tpu.models.loader import (
        load_params, register_hf_checkpoint, to_device,
    )
    from quoracle_tpu.models.make_checkpoint import make_checkpoint
    from quoracle_tpu.models.tokenizer import get_tokenizer

    root = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "checkpoints")
    ckpt = make_checkpoint(os.path.join(root, f"llama-{scale}"),
                           family="llama", scale=scale)
    base = register_hf_checkpoint(ckpt, name="longctx-base")
    max_seq = resident + 4 * new_tokens * (rounds + 2) + 1024
    cfg = register_model(dataclasses.replace(
        base, name="longctx", context_window=max_seq))
    tok = get_tokenizer("xla:longctx")
    params = to_device(load_params(ckpt, cfg))
    eng = GenerateEngine(
        cfg, params, tok, max_seq=max_seq,
        prompt_buckets=(256, 1024, resident, max_seq),
        session_max_bytes=session_max_bytes)
    return eng, tok


# Ascending expected-footprint order (the peak-HBM counter is cumulative):
# unified holds only the pool (KV written straight to pages — no working
# cache, no tail buffer), direct_full adds the tail, direct_decode adds
# the working cache at prefill, gather keeps it through decode.
PATHS = ("unified", "direct_full", "direct_decode", "gather")


def _set_path(eng, path: str) -> None:
    eng._force_gather_decode = path == "gather"
    eng.unified_min_tokens = 0 if path == "unified" else 1 << 30
    eng.direct_decode_min_tokens = 0 if path.startswith("direct") else 1 << 30
    eng.direct_prefill_min_tokens = 0 if path == "direct_full" else 1 << 30


def measure_paths(eng, tok, resident: int, rounds: int, new_tokens: int,
                  paths=PATHS) -> dict:
    """Build one resident session, then time resumed refinement-shaped
    rounds under each path. Returns {path: {p50_round_ms, peak_hbm_gb}}.

    Comparability contracts (these feed calibrate_paged's gate decisions):
      * the session is built INCREMENTALLY in ≤1024-token suffix chunks
        under the FIRST path's gates — so when direct_full runs first, the
        cumulative peak-HBM counter never includes a full-resident gather
        working cache that isn't that path's own doing;
      * every path replays rounds from the SAME base conversation (conv
        resets per path) — each path is timed at the same resident size,
        not at whatever the previous path grew the session to.
    """
    filler = ("The quick brown fox jumps over the lazy dog. "
              "Numbers: 0123456789. ")
    ids = tok.encode(filler)
    prompt = [tok.bos_id] + (ids * (resident // len(ids) + 1))[:resident - 1]
    _set_path(eng, paths[0])
    eng.sessions.drop("s")
    t0 = time.monotonic()
    step = 1024
    for end in range(step, len(prompt), step):
        eng.generate([prompt[:end]], temperature=0.0, max_new_tokens=1,
                     session_ids=["s"])
    r = eng.generate([prompt], temperature=0.0,
                     max_new_tokens=new_tokens, session_ids=["s"])[0]
    log(f"incremental prefill of {len(prompt)} tokens: "
        f"{time.monotonic() - t0:.1f}s (path {paths[0]})")

    results = {}
    base_conv = list(prompt) + r.token_ids
    for path in paths:
        _set_path(eng, path)
        conv = list(base_conv)
        lats = []
        for i in range(rounds + 1):            # first = warmup/compile
            nxt = conv + tok.encode(f" continue {path} {i}.")
            t0 = time.monotonic()
            rr = eng.generate([nxt], temperature=0.0,
                              max_new_tokens=new_tokens,
                              session_ids=["s"])[0]
            lats.append((time.monotonic() - t0) * 1000)
            conv = nxt + rr.token_ids
            log(f"{path} round {i}: {lats[-1]:.0f}ms "
                f"(reused {rr.n_cached_tokens} tokens)")
        results[path] = {
            "p50_round_ms": statistics.median(lats[1:]),
            "peak_hbm_gb": peak_hbm_gb(),
            "rounds": rounds,
        }
    return results


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--resident", type=int, default=16384,
                    help="target resident session size in tokens")
    ap.add_argument("--rounds", type=int, default=4,
                    help="timed resumed rounds per path")
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--scale", default="1b", choices=["1b", "tiny"])
    args = ap.parse_args()

    import jax

    from quoracle_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    eng, tok = build_engine(args.resident, args.rounds, args.new_tokens,
                            args.scale)
    log(f"engine ready; resident target {args.resident} tokens")
    results = measure_paths(eng, tok, args.resident, args.rounds,
                            args.new_tokens)

    print(json.dumps({
        "metric": "longctx_resumed_round_p50",
        "resident_tokens": args.resident,
        "new_tokens_per_round": args.new_tokens,
        **{f"{k}_p50_ms": round(v["p50_round_ms"], 1)
           for k, v in results.items()},
        **{f"{k}_peak_hbm_gb": v["peak_hbm_gb"] for k, v in results.items()},
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
