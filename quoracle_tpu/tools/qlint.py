"""qlint CLI — run the repo-native analyzers against the baseline.

    python -m quoracle_tpu.tools.qlint [--format=text|json]
                                       [--rules lock-blocking,...]
                                       [--baseline PATH]
                                       [--update-baseline]
                                       [--root PATH]
                                       [--show-resolved]

Exit-code contract (the CI gate depends on it):

* ``0`` — clean: no findings outside the committed baseline (stale
  baseline entries are reported as warnings, not failures, unless
  ``--strict-baseline``).
* ``1`` — NEW findings (not in the baseline). Fix them or, for a
  deliberate exception, annotate the site with
  ``# qlint: allow[rule] reason``; ``--update-baseline`` is the last
  resort and the diff reviewer will ask why.
* ``2`` — internal error (analyzer crash, unparseable source).

Wall-time budget: the full repo must analyze in well under 30 s (it is
pure-AST, no jax import on the analysis path) so the CI gates stage
stays cheap; ``--timings`` prints per-pass wall time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="qlint",
        description="repo-native static analyzer (ISSUE 9)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", default="",
                   help="comma-separated rule filter (default: all)")
    p.add_argument("--baseline", default=None,
                   help="baseline path (default: <root>/qlint_baseline"
                        ".json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the current findings as the new baseline")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect)")
    p.add_argument("--show-resolved", action="store_true",
                   help="list baseline entries no longer reported")
    p.add_argument("--strict-baseline", action="store_true",
                   help="stale baseline entries fail the run too")
    p.add_argument("--timings", action="store_true")
    return p


def run_passes(root: str, rules: set | None = None,
               timings: dict | None = None) -> list:
    """All findings over the repo at ``root`` (sorted, rule-filtered).
    Imports stay inside so ``--help`` is instant."""
    from quoracle_tpu.analysis import common, compilekeys, locks
    from quoracle_tpu.analysis import registry as registry_pass
    from quoracle_tpu.analysis import skips

    t0 = time.monotonic()
    pkg_modules = common.load_modules(root, ["quoracle_tpu"])
    test_modules = common.load_modules(root, ["tests"])
    if timings is not None:
        timings["parse"] = time.monotonic() - t0

    findings: list = []
    for name, fn in (
            ("locks", lambda: locks.run(pkg_modules)),
            ("compilekeys", lambda: compilekeys.run(pkg_modules)),
            ("registry", lambda: registry_pass.run(pkg_modules, root)),
            ("skips", lambda: skips.run(test_modules))):
        t = time.monotonic()
        findings.extend(fn())
        if timings is not None:
            timings[name] = time.monotonic() - t
    if rules:
        findings = [f for f in findings if f.rule in rules]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return findings


def main(argv: list | None = None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        from quoracle_tpu.analysis import common

        root = args.root or common.repo_root(
            os.path.dirname(os.path.abspath(__file__)))
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        bad = rules - set(common.RULES)
        if bad:
            print(f"qlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 2
        timings: dict = {}
        t0 = time.monotonic()
        findings = run_passes(root, rules or None, timings)
        wall = time.monotonic() - t0

        baseline_path = args.baseline or os.path.join(
            root, common.BASELINE_NAME)
        if args.update_baseline:
            common.save_baseline(baseline_path, findings)
            print(f"qlint: baseline updated: {baseline_path} "
                  f"({len(findings)} findings)")
            return 0
        baseline = common.load_baseline(baseline_path)
        new, resolved = common.diff_baseline(findings, baseline)

        if args.format == "json":
            print(json.dumps({
                "findings": [f.as_dict() for f in findings],
                "new": [f.as_dict() for f in new],
                "resolved_baseline": resolved,
                "baseline_entries": len(baseline),
                "wall_s": round(wall, 3),
            }, indent=2))
        else:
            for f in new:
                print(f.render())
            n_known = len(findings) - len(new)
            print(f"qlint: {len(findings)} finding(s) "
                  f"({len(new)} new, {n_known} baselined), "
                  f"{len(resolved)} stale baseline entr"
                  f"{'y' if len(resolved) == 1 else 'ies'}, "
                  f"{wall:.1f}s")
            if args.timings:
                for k, v in timings.items():
                    print(f"  {k}: {v * 1000:.0f}ms")
            if resolved and (args.show_resolved or args.strict_baseline):
                for e in resolved:
                    print(f"  stale: [{e['rule']}] {e['path']} "
                          f"{e['symbol']}")
                print("qlint: prune with --update-baseline")
        if new:
            return 1
        if args.strict_baseline and resolved:
            return 1
        return 0
    except KeyboardInterrupt:
        raise
    except Exception as e:                  # noqa: BLE001 — exit contract
        import traceback
        traceback.print_exc()
        print(f"qlint: internal error: {e!r}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
