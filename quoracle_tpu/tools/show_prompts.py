"""Verbatim LLM prompt dumps for named scenarios — the prompt-debugging
tool + golden-prompt source of truth.

Parity with the reference's ``mix quoracle.show_llm_prompts``
(reference lib/mix/tasks/quoracle.show_llm_prompts.ex:10-25): every scenario
calls the REAL prompt-construction code (build_system_prompt,
build_messages_for_model, build_refinement_prompt, ConsensusEngine.decide),
never hand-written prompt text, so the dump shows exactly what a served
model would receive. The same 12 scenarios + ``all``.

Consensus scenarios run the full engine over a scripted MockBackend and dump
what each pool member saw in each round plus the outcome — the refinement
prompts in the dump are the engine's own.

Usage:
    python -m quoracle_tpu.tools.show_prompts <scenario>|all
    python -m quoracle_tpu.tools.show_prompts --write-golden tests/golden

tests/test_golden_prompts.py locks every scenario against checked-in golden
files; regenerate with --write-golden after INTENTIONAL prompt changes.
"""

from __future__ import annotations

import json
import sys
from typing import Callable

from quoracle_tpu.consensus.aggregator import build_refinement_prompt, Cluster
from quoracle_tpu.consensus.engine import ConsensusConfig, ConsensusEngine
from quoracle_tpu.consensus.parser import ActionProposal
from quoracle_tpu.consensus.prompt_builder import build_system_prompt
from quoracle_tpu.context.history import (
    DECISION, RESULT, USER, AgentContext, HistoryEntry, Lesson,
)
from quoracle_tpu.context.message_builder import build_messages_for_model
from quoracle_tpu.context.token_manager import TokenManager
from quoracle_tpu.governance.fields import AgentFields, compose_field_prompt
from quoracle_tpu.models.runtime import MockBackend

POOL = MockBackend.DEFAULT_POOL
MODEL = POOL[0]

SCENARIOS: dict[str, Callable[[], str]] = {}


def scenario(fn: Callable[[], str]) -> Callable[[], str]:
    SCENARIOS[fn.__name__] = fn
    return fn


def _tm() -> TokenManager:
    """Deterministic char-based counter (goldens must not depend on a model
    tokenizer being present)."""
    return TokenManager(lambda spec, text: max(1, len(text) // 4),
                        context_limit_fn=lambda spec: 128_000)


def render_messages(messages: list[dict]) -> str:
    parts = []
    for m in messages:
        parts.append(f"---------- {m['role']} ----------")
        parts.append(m["content"] if isinstance(m["content"], str)
                     else json.dumps(m["content"], indent=1))
    return "\n".join(parts) + "\n"


def _action_json(action: str, params: dict, reasoning: str,
                 wait=False) -> str:
    return json.dumps({"action": action, "params": params,
                       "reasoning": reasoning, "wait": wait})


# ---------------------------------------------------------------------------
# Prompt-construction scenarios
# ---------------------------------------------------------------------------

@scenario
def generalist_initial() -> str:
    """Generalist agent's first interaction: full ungoverned system prompt."""
    ctx = AgentContext()
    ctx.append(MODEL, HistoryEntry(
        kind=USER, content="$INITIAL_TASK_DESCRIPTION"))
    msgs = build_messages_for_model(
        ctx, MODEL, system_prompt=build_system_prompt(),
        token_manager=_tm())
    return render_messages(msgs)


@scenario
def generalist_with_history() -> str:
    """Generalist after an orient → shell sequence (decision + result
    entries rendered through the real history serializer)."""
    ctx = AgentContext()
    ctx.append(MODEL, HistoryEntry(kind=USER, content="$INITIAL_TASK"))
    ctx.append(MODEL, HistoryEntry(kind=DECISION, content={
        "action": "orient",
        "params": {
            "current_situation": "Starting data-analysis request",
            "goal_clarity": "Analyze /path/to/data.csv structure",
            "available_resources": "Shell, file read, web fetch",
            "key_challenges": "Unknown data format and size",
        },
        "reasoning": "Understand the task before acting", "wait": False,
        "confidence": 1.0, "kind": "consensus", "rounds": 1}))
    ctx.append(MODEL, HistoryEntry(kind=RESULT, action_type="orient", content={
        "action": "orient",
        "result": {"status": "ok", "recorded": True}}))
    ctx.append(MODEL, HistoryEntry(kind=DECISION, content={
        "action": "execute_shell",
        "params": {"command": "head -20 /path/to/data.csv"},
        "reasoning": "Inspect the file before parsing", "wait": False,
        "confidence": 1.0, "kind": "consensus", "rounds": 1}))
    ctx.append(MODEL, HistoryEntry(kind=RESULT, action_type="execute_shell",
                                   content={
        "action": "execute_shell",
        "result": {"status": "ok", "exit_code": 0,
                   "stdout": "id,name,value\n1,a,10\n2,b,20\n"}}))
    ctx.todos = [{"task": "inspect csv", "done": True},
                 {"task": "summarize columns", "done": False}]
    msgs = build_messages_for_model(
        ctx, MODEL, system_prompt=build_system_prompt(),
        token_manager=_tm())
    return render_messages(msgs)


@scenario
def with_fields_full() -> str:
    """All hierarchical identity fields + two ancestor constraints."""
    fields = AgentFields(
        role="Research coordinator for the data-pipeline workstream",
        cognitive_style="systematic",
        constraints="Never modify files outside /workspace",
        global_context="The org is migrating analytics to the new warehouse",
        delegation_strategy="Delegate independent subtasks; keep synthesis",
        communication_style="Terse status updates, full detail on request",
        risk_tolerance="Low: prefer reversible actions",
        planning_horizon="Multi-day",
        identity_notes="You were spawned to coordinate, not to implement",
    )
    field_prompt = compose_field_prompt(
        fields, accumulated_constraints=(
            "Stay under the task budget",
            "Do not contact external services without approval"))
    msgs = [{"role": "system", "content": build_system_prompt(
        field_system_prompt=field_prompt,
        capability_groups=["hierarchy", "file_read"],
        profile_name="coordinator",
        profile_description="Coordinates child agents",
        profile_names=("generalist", "coordinator", "implementer"))},
        {"role": "user", "content": "$INITIAL_TASK"}]
    return render_messages(msgs)


@scenario
def with_cognitive_style() -> str:
    """Cognitive-style directive rendered into the identity block."""
    out = []
    for style in ("systematic", "skeptical", "decisive"):
        prompt = compose_field_prompt(AgentFields(
            role="Analyst", cognitive_style=style))
        out.append(f"==== cognitive_style: {style} ====\n{prompt}\n")
    return "\n".join(out)


@scenario
def refinement_round() -> str:
    """The engine's own refinement prompt for a 2-1 split."""
    a = ActionProposal(model_spec=POOL[0], action="execute_shell",
                       params={"command": "ls /workspace"},
                       reasoning="List files first")
    b = ActionProposal(model_spec=POOL[1], action="execute_shell",
                       params={"command": "ls /workspace"},
                       reasoning="Same: inspect layout")
    c = ActionProposal(model_spec=POOL[2], action="spawn_child",
                       params={"task_description": "Survey the workspace",
                               "success_criteria": "A file inventory",
                               "immediate_context": "Fresh task",
                               "approach_guidance": "Use shell listings",
                               "profile": "generalist"},
                       reasoning="Delegate the survey")
    prompt = build_refinement_prompt(
        [Cluster(proposals=[a, b]), Cluster(proposals=[c])], own=c,
        round_num=2, max_rounds=4)
    return prompt + "\n"


@scenario
def with_secrets() -> str:
    """Secrets usage docs appear when the secret actions are allowed."""
    msgs = [{"role": "system", "content": build_system_prompt(
        capability_groups=["external_api", "local_execution"])},
        {"role": "user", "content": "Call the payments API with our key."}]
    return render_messages(msgs)


@scenario
def with_ace_context() -> str:
    """ACE lessons + state summary injected into the first user message
    (the 8-step injection order's step 2)."""
    ctx = AgentContext()
    ctx.append(MODEL, HistoryEntry(kind=USER, content="$CONTINUING_TASK"))
    ctx.context_lessons[MODEL] = [
        Lesson(type="factual", content="The data lives in /data/warehouse",
               confidence=3),
        Lesson(type="behavioral",
               content="Child agents need explicit success criteria",
               confidence=2),
    ]
    ctx.model_states[MODEL] = [
        "Phase 1 (inventory) complete; phase 2 (summaries) in progress"]
    ctx.budget_snapshot = {"mode": "allocated", "limit": "10.00",
                           "spent": "4.50", "committed": "2.00"}
    msgs = build_messages_for_model(ctx, MODEL, token_manager=_tm())
    return render_messages(msgs)


# ---------------------------------------------------------------------------
# Full-engine consensus scenarios (scripted pool, real engine)
# ---------------------------------------------------------------------------

def _run_consensus(scripts: dict[str, list[str]],
                   max_refinement_rounds: int = 4) -> str:
    backend = MockBackend(scripts={m: list(v) for m, v in scripts.items()})
    engine = ConsensusEngine(backend, ConsensusConfig(
        model_pool=list(POOL),
        max_refinement_rounds=max_refinement_rounds))
    messages = {m: [{"role": "system", "content": "$SYSTEM_PROMPT"},
                    {"role": "user", "content": "$TASK"}] for m in POOL}
    outcome = engine.decide(messages)

    # group the captured requests into rounds (one request per member per
    # round, in pool order)
    rounds: list[list] = []
    for i, req in enumerate(backend.calls):
        if i % len(POOL) == 0:
            rounds.append([])
        rounds[-1].append(req)
    parts = []
    for rnum, reqs in enumerate(rounds, 1):
        parts.append(f"======== ROUND {rnum} ========")
        for req in reqs:
            parts.append(f"\n#### what {req.model_spec} saw "
                         f"(temperature {req.temperature:.2f}) ####")
            parts.append(render_messages(req.messages))
    d = outcome.decision
    parts.append("======== OUTCOME ========")
    parts.append(json.dumps({
        "status": outcome.status,
        "kind": d.kind if d else None,
        "action": d.action if d else None,
        "params": d.params if d else None,
        "confidence": round(d.confidence, 3) if d else None,
        "rounds_used": outcome.rounds_used,
    }, indent=1, sort_keys=True))
    return "\n".join(parts) + "\n"


@scenario
def consensus_immediate() -> str:
    """3 models agree on round 1 (unanimity rule)."""
    shell = _action_json("execute_shell", {"command": "ls /workspace"},
                         "inspect")
    return _run_consensus({m: [shell] for m in POOL})


@scenario
def consensus_exact_match_params() -> str:
    """execute_shell commands must match exactly — differing commands split
    the clusters and refinement converges them."""
    ls_a = _action_json("execute_shell", {"command": "ls /workspace"},
                        "list files")
    ls_b = _action_json("execute_shell", {"command": "ls -la /workspace"},
                        "list with details")
    return _run_consensus({
        POOL[0]: [ls_a, ls_a],
        POOL[1]: [ls_a, ls_a],
        POOL[2]: [ls_b, ls_a],
    })


@scenario
def consensus_semantic_params() -> str:
    """spawn_child task descriptions merge by semantic similarity."""
    sa = _action_json("spawn_child", {
        "task_description": "Survey the repository files and sizes",
        "success_criteria": "Inventory produced",
        "immediate_context": "Fresh task", "approach_guidance": "Use shell",
        "profile": "generalist"}, "delegate")
    sb = _action_json("spawn_child", {
        "task_description": "Survey the repository files and their sizes",
        "success_criteria": "Inventory produced",
        "immediate_context": "Fresh task", "approach_guidance": "Use shell",
        "profile": "generalist"}, "delegate it")
    return _run_consensus({POOL[0]: [sa], POOL[1]: [sa], POOL[2]: [sb]})


@scenario
def consensus_different_actions() -> str:
    """Models disagree on the action type; refinement sways the minority."""
    shell = _action_json("execute_shell", {"command": "cat README.md"},
                         "read the readme")
    msg = _action_json("send_message", {"target": "parent",
                                        "content": "starting"},
                       "tell the parent")
    return _run_consensus({
        POOL[0]: [shell, shell],
        POOL[1]: [shell, shell],
        POOL[2]: [msg, shell],
    })


@scenario
def consensus_max_rounds() -> str:
    """No convergence: forced decision (plurality + tiebreak) after max
    refinement rounds."""
    shell = _action_json("execute_shell", {"command": "pwd"}, "locate")
    msg = _action_json("send_message", {"target": "parent",
                                        "content": "hello"}, "greet")
    wait = _action_json("wait", {}, "hold", wait=True)
    return _run_consensus({
        POOL[0]: [shell] * 3,
        POOL[1]: [msg] * 3,
        POOL[2]: [wait] * 3,
    }, max_refinement_rounds=2)


@scenario
def consensus_cluster_merge() -> str:
    """2-1 split where the minority joins the majority cluster in round 2;
    params merge within the winning cluster."""
    todo_a = _action_json("todo", {"items": [
        {"task": "read config", "done": False}]}, "plan")
    todo_b = _action_json("todo", {"items": [
        {"task": "scan sources", "done": False}]}, "plan differently")
    return _run_consensus({
        POOL[0]: [todo_a, todo_a],
        POOL[1]: [todo_a, todo_a],
        POOL[2]: [todo_b, todo_a],
    })


# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--write-golden":
        import os
        out_dir = argv[1]
        os.makedirs(out_dir, exist_ok=True)
        for name, fn in SCENARIOS.items():
            with open(os.path.join(out_dir, f"{name}.txt"), "w") as f:
                f.write(fn())
        print(f"wrote {len(SCENARIOS)} goldens to {out_dir}")
        return 0
    if not argv or argv[0] not in set(SCENARIOS) | {"all"}:
        names = "\n  ".join(sorted(SCENARIOS) + ["all"])
        print(f"usage: python -m quoracle_tpu.tools.show_prompts "
              f"<scenario>\n\nscenarios:\n  {names}")
        return 1 if not argv else 2
    targets = sorted(SCENARIOS) if argv[0] == "all" else [argv[0]]
    for name in targets:
        print("=" * 100)
        print(f"SCENARIO: {name}")
        print("=" * 100)
        print(SCENARIOS[name]())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
