"""Developer tooling (prompt debugging, golden generation)."""
