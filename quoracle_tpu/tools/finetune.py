"""Close the train → serve loop (VERDICT r4 item 5): fine-tune a bench
checkpoint on a locally-generated corpus, export it back to HF layout, and
measure the served result through the PRODUCTION stack.

Two runnable proofs, both impossible for the reference (its models are
hosted APIs, SURVEY §2.3):

  ``--target format`` (default) — instruction/format corpus teaching the
  agent-action JSON shape (actions/schema.py vocabulary, rendered through
  the checkpoint's own chat template). Served UNCONSTRAINED (grammar off),
  the fine-tuned model must emit parseable action JSON — the measured
  claim is ``json_compliance`` over held-out tasks, target ≥ 0.95.

  ``--target mmlu`` — the mmlu-pro grove subset in run_tpu_accuracy.py's
  exact prompt format. This TRAINS ON THE SUBSET ITSELF: the resulting
  number proves the train → checkpoint → serve → consensus → score
  lifecycle (the grove runner consumes the exported checkpoint), not any
  knowledge claim — the artifact says so explicitly.

Default scale is ``small`` (~7M params) so the loop runs in minutes on a
CPU-only host; pass --scale 1b on a live TPU for the real thing. Artifact:
one JSON line on stdout; ``--out-artifact`` also writes it to a file.

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python -m quoracle_tpu.tools.finetune --steps 600
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

NOUNS = ["test suite", "deployment", "budget report", "web crawler",
         "database migration", "log pipeline", "release notes",
         "staging cluster", "billing alert", "search index",
         "pull request", "config drift", "cache layer", "cron schedule"]
VERBS = ["Investigate", "Summarize", "Review", "Fix", "Plan", "Audit",
         "Document", "Prioritize", "Debug", "Coordinate"]
REASONS = ["the {n} needs attention first",
           "this unblocks the rest of the work on the {n}",
           "the parent asked for an update about the {n}",
           "splitting the {n} work keeps the tree responsive",
           "the {n} is the cheapest next step"]

SYSTEM = ('You are an autonomous agent. Respond ONLY with a JSON object '
          '{"action": ..., "params": {...}, "reasoning": ..., '
          '"wait": false}.')


def _format_sample(rng: random.Random) -> tuple[str, str]:
    """(user task, assistant JSON) — varied content, rigid shape."""
    n = rng.choice(NOUNS)
    task = f"{rng.choice(VERBS)} the {n} and report back."
    action = rng.choice([
        ("send_message", {"target": "parent",
                          "content": f"status update on the {n}"}),
        ("todo", {"items": [f"check the {n}", f"report on the {n}"]}),
        ("execute_shell", {"command": f"ls -la {n.split()[0]}"}),
        ("file_read", {"path": f"/tmp/{n.split()[0]}.txt"}),
        ("orient", {}),
        ("spawn_child", {"task": f"handle the {n}"}),
    ])
    obj = {"action": action[0], "params": action[1],
           "reasoning": rng.choice(REASONS).format(n=n), "wait": False}
    return task, json.dumps(obj, separators=(", ", ": "))


def build_format_corpus(tok, eos_id: int, n: int, seed: int,
                        max_len: int) -> list[tuple[list[int], int]]:
    """[(token ids, prompt_len)] — loss masked to the completion."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        task, answer = _format_sample(rng)
        prompt = tok.encode_chat([{"role": "system", "content": SYSTEM},
                                  {"role": "user", "content": task}])
        ids = prompt + tok.encode(answer) + [eos_id]
        if len(ids) <= max_len:
            out.append((ids, len(prompt)))
    return out


def build_mmlu_corpus(tok, eos_id: int, grove_dir: str, repeats: int,
                      max_len: int) -> list[tuple[list[int], int]]:
    """The grove subset in run_tpu_accuracy.py's EXACT prompt format →
    '{"action": "<key letter>"}' completions (lifecycle proof, see module
    docstring)."""
    from quoracle_tpu.governance.bench_scoring import load_questions
    qs = load_questions(grove_dir)
    out = []
    for _ in range(repeats):
        for q in qs:
            opts = "\n".join(f"{k}. {v}" for k, v in q["options"].items())
            prompt = tok.encode_chat([
                {"role": "system",
                 "content": "Answer the multiple-choice question. Respond "
                            'ONLY with JSON: {"action": "<LETTER A-J>"}.'},
                {"role": "user", "content": f"{q['question']}\n{opts}"},
            ])
            ids = prompt + tok.encode(
                json.dumps({"action": q["answer"]})) + [eos_id]
            if len(ids) <= max_len:
                out.append((ids, len(prompt)))
    return out


def train(ckpt_dir: str, rows, steps: int, batch: int, seq: int,
          lr: float, seed: int, log):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quoracle_tpu.models.loader import (
        load_params, register_hf_checkpoint, to_device,
    )
    from quoracle_tpu.models.train import (
        TrainState, make_optimizer, train_step,
    )
    cfg = register_hf_checkpoint(ckpt_dir, name="ft-base")
    params = to_device(load_params(ckpt_dir, cfg, dtype=np.float32))
    optimizer = make_optimizer(lr=lr)
    state = TrainState(params, optimizer.init(params),
                       jnp.asarray(0, jnp.int32))
    step_fn = jax.jit(lambda s, t, m: train_step(s, cfg, optimizer, t, m))

    rng = random.Random(seed)
    pad = cfg.eos_token_id
    t0 = time.monotonic()
    for i in range(steps):
        tok_b = np.full((batch, seq), pad, np.int32)
        mask_b = np.zeros((batch, seq), np.float32)
        for b in range(batch):
            ids, plen = rng.choice(rows)
            ids = ids[:seq]
            tok_b[b, :len(ids)] = ids
            mask_b[b, plen:len(ids)] = 1.0
        state, loss = step_fn(state, jnp.asarray(tok_b),
                              jnp.asarray(mask_b))
        if i % 50 == 0 or i == steps - 1:
            log(f"step {i}: loss {float(loss):.4f} "
                f"({time.monotonic() - t0:.0f}s)")
    return cfg, state


def eval_format(out_dir: str, n_eval: int, seed: int, log) -> dict:
    """Serve the exported checkpoint UNCONSTRAINED and measure how many
    held-out tasks yield parseable action JSON."""
    from quoracle_tpu.actions.schema import ACTIONS
    from quoracle_tpu.models.loader import register_hf_checkpoint
    from quoracle_tpu.models.runtime import QueryRequest, TPUBackend
    cfg = register_hf_checkpoint(out_dir, name="ft-tuned")
    backend = TPUBackend([f"xla:{cfg.name}"])
    rng = random.Random(seed + 1)             # disjoint from training seed
    ok = strict = 0
    n_greedy = n_eval // 2
    for i in range(n_eval):
        task, _ = _format_sample(rng)
        r = backend.query([QueryRequest(
            f"xla:{cfg.name}",
            [{"role": "system", "content": SYSTEM},
             {"role": "user", "content": task}],
            temperature=0.0 if i < n_greedy else 0.7,
            max_tokens=128, constrain_json=False)])[0]
        if not r.ok:
            continue
        try:
            obj = json.loads(r.text.strip())
            parsed = isinstance(obj, dict) and "action" in obj
        except json.JSONDecodeError:
            parsed = False
        ok += int(parsed)
        strict += int(parsed and obj.get("action") in ACTIONS
                      and isinstance(obj.get("params"), dict))
        if i < 3:
            log(f"sample {i}: {r.text[:100]!r}")
    return {"json_compliance": round(ok / max(1, n_eval), 4),
            "strict_action_compliance": round(strict / max(1, n_eval), 4),
            "n_eval": n_eval, "greedy": n_greedy,
            "sampled_t07": n_eval - n_greedy}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", choices=["format", "mmlu"],
                    default="format")
    ap.add_argument("--scale", default="small")
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--corpus-size", type=int, default=2000)
    ap.add_argument("--n-eval", type=int, default=60)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--out-artifact", default=None)
    args = ap.parse_args()

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    from quoracle_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache()

    from quoracle_tpu.models.loader import export_hf_checkpoint
    from quoracle_tpu.models.make_checkpoint import make_checkpoint
    from quoracle_tpu.models.tokenizer import HFAutoTokenizer

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    work = args.workdir or os.path.join(repo, "checkpoints",
                                        f"finetune-{args.target}")
    base = make_checkpoint(os.path.join(work, "base"), family="llama",
                           scale=args.scale, seed=args.seed)
    tok = HFAutoTokenizer(base)
    grove = os.path.join(repo, "groves", "mmlu-pro")

    if args.target == "format":
        rows = build_format_corpus(tok, tok.eos_id, args.corpus_size,
                                   args.seed, args.seq)
    else:
        rows = build_mmlu_corpus(tok, tok.eos_id, grove,
                                 repeats=max(1, args.corpus_size // 24),
                                 max_len=args.seq)
    log(f"corpus: {len(rows)} rows (target {args.target})")

    cfg, state = train(base, rows, args.steps, args.batch, args.seq,
                       args.lr, args.seed, log)
    out_dir = export_hf_checkpoint(state.params, cfg,
                                   os.path.join(work, "tuned"), base)
    log(f"exported fine-tuned checkpoint to {out_dir}")

    artifact = {
        "metric": f"train_serve_loop_{args.target}",
        "scale": args.scale, "steps": args.steps,
        "corpus_rows": len(rows), "checkpoint": out_dir,
        "trained_on_eval_set": args.target == "mmlu",
        "note": ("mmlu target trains ON the grove subset: the number "
                 "proves the train->checkpoint->serve->consensus->score "
                 "lifecycle, NOT model knowledge"
                 if args.target == "mmlu" else
                 "eval tasks drawn from a disjoint seed; grammar "
                 "constraint OFF during eval"),
    }
    if args.target == "format":
        artifact.update(eval_format(out_dir, args.n_eval, args.seed, log))
        artifact["value"] = artifact["json_compliance"]
        artifact["unit"] = "fraction"
    else:
        # the grove's own runner consumes the exported checkpoint; run it
        # in-process for one artifact
        sys.argv = ["run_tpu_accuracy", "--checkpoint", out_dir]
        sys.path.insert(0, os.path.join(grove, "scripts"))
        import io
        from contextlib import redirect_stdout
        import run_tpu_accuracy
        buf = io.StringIO()
        with redirect_stdout(buf):
            run_tpu_accuracy.main()
        grove_result = json.loads(buf.getvalue().strip().splitlines()[-1])
        artifact.update({"value": grove_result["value"],
                         "unit": "fraction",
                         "grove_result": grove_result})
    print(json.dumps(artifact))
    if args.out_artifact:
        with open(args.out_artifact, "w") as f:
            json.dump(artifact, f, indent=1)


if __name__ == "__main__":
    main()
