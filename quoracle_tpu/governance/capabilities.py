"""Capability groups: the single source of truth for action gating.

Parity with the reference's 5 selectable capability groups + 11 always-allowed
actions (reference lib/quoracle/profiles/capability_groups.ex:8-47) and
ActionGate filtering (reference lib/quoracle/profiles/action_gate.ex).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

ALWAYS_ALLOWED: frozenset[str] = frozenset({
    "wait", "orient", "todo", "send_message", "fetch_web", "answer_engine",
    "generate_images", "learn_skills", "create_skill", "batch_sync",
    "batch_async",
})

GROUP_ACTIONS: dict[str, frozenset[str]] = {
    "hierarchy": frozenset({"spawn_child", "dismiss_child", "adjust_budget"}),
    "local_execution": frozenset({"execute_shell", "call_mcp", "record_cost",
                                  "search_secrets", "generate_secret"}),
    "file_read": frozenset({"file_read"}),
    "file_write": frozenset({"file_write", "search_secrets",
                             "generate_secret"}),
    "external_api": frozenset({"call_api", "record_cost", "search_secrets",
                               "generate_secret"}),
}

# Display order (reference capability_groups.ex:38).
VALID_GROUPS: tuple[str, ...] = ("file_read", "file_write", "external_api",
                                 "hierarchy", "local_execution")

GROUP_DESCRIPTIONS: dict[str, str] = {
    "file_read": "Read files from the filesystem",
    "file_write": "Write and edit files on the filesystem",
    "external_api": "Make HTTP requests to external APIs",
    "hierarchy": "Spawn and manage child agents",
    "local_execution": "Execute shell commands and MCP calls",
}


class InvalidGroupError(ValueError):
    pass


def validate_groups(groups: Iterable[str]) -> None:
    bad = [g for g in groups if g not in GROUP_ACTIONS]
    if bad:
        raise InvalidGroupError(f"invalid capability groups: {bad}")


def allowed_actions_for_groups(groups: Sequence[str]) -> set[str]:
    """Base (always-allowed) actions plus everything the groups enable."""
    validate_groups(groups)
    allowed = set(ALWAYS_ALLOWED)
    for g in groups:
        allowed |= GROUP_ACTIONS[g]
    return allowed


def blocked_actions_for_groups(groups: Sequence[str],
                               all_actions: Iterable[str]) -> list[str]:
    allowed = allowed_actions_for_groups(groups)
    return sorted(a for a in all_actions if a not in allowed)


def filter_actions(actions: Iterable[str], groups: Optional[Sequence[str]],
                   forbidden: Iterable[str] = ()) -> list[str]:
    """Gate an action list by capability groups, then drop forbidden actions
    (grove hard rules, reference consensus_handler.ex:294-313). ``groups`` of
    None means ungoverned (all actions); an empty list means base actions
    only — the reference makes the same distinction."""
    forbidden_set = set(forbidden)
    if groups is None:
        allowed = None
    else:
        allowed = allowed_actions_for_groups(groups)
    out = []
    for a in actions:
        if a in forbidden_set:
            continue
        if allowed is not None and a not in allowed:
            continue
        out.append(a)
    return out
