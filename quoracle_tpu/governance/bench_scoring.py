"""Shared machinery for benchmark-grove scoring scripts.

Both shipped groves (groves/mmlu-pro, groves/livebench — reference
priv/groves/*) score the same run layout: a workspace with
``runs/<id>/answers/<qid>.json`` files graded against the grove's own
``data/questions.jsonl`` key (which never enters the agent workspace —
``prepare`` strips the secret fields from the copy the agents read). Only
the grading function, the grouping field, and the secret-field list differ
per grove, so each grove's ``scripts/score_run.py`` supplies those and
delegates the prepare/score/CLI skeleton here — one implementation of the
answered-counting and aggregation rules instead of a drifting copy per
grove.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
from typing import Callable, Sequence


def load_questions(grove_dir: str) -> list[dict]:
    with open(os.path.join(grove_dir, "data", "questions.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def prepare(workspace: str, grove_dir: str,
            secret_fields: Sequence[str]) -> None:
    """Copy the dataset into the workspace with the grading key stripped,
    and create runs/."""
    os.makedirs(os.path.join(workspace, "runs"), exist_ok=True)
    dst = os.path.join(workspace, "data")
    if os.path.isdir(dst):
        shutil.rmtree(dst)
    os.makedirs(dst)
    qs = load_questions(grove_dir)
    with open(os.path.join(dst, "questions.jsonl"), "w") as f:
        for q in qs:
            f.write(json.dumps({k: v for k, v in q.items()
                                if k not in secret_fields}) + "\n")
    print(f"workspace prepared at {workspace} ({len(qs)} questions)")


def score(workspace: str, run_id: str, grove_dir: str,
          grade_fn: Callable[[dict, object], bool],
          group_key: str, group_field: str) -> dict:
    """Grade runs/<run_id>/answers/*.json against the grove key; write and
    return runs/<run_id>/score.json with overall + per-group accuracy.
    ``group_key`` names the question field to group by (e.g. "subject");
    ``group_field`` names the result key (e.g. "per_subject")."""
    key = {q["id"]: q for q in load_questions(grove_dir)}
    answers_dir = os.path.join(workspace, "runs", run_id, "answers")
    groups: dict[str, list[int]] = {}
    answered = correct = 0
    for qid, q in key.items():
        path = os.path.join(answers_dir, f"{qid}.json")
        got = None
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    got = json.load(f).get("answer")
            except (json.JSONDecodeError, OSError):
                got = None
        # Normalize non-string answers (e.g. {"answer": 408}) so "answered"
        # never exceeds what graders can actually credit — write-time schema
        # validation can be bypassed by manual runs / external answer dirs.
        if got is not None and not isinstance(got, str):
            got = str(got)
        hit = int(grade_fn(q, got))
        answered += int(got is not None)
        correct += hit
        groups.setdefault(q[group_key], []).append(hit)
    result = {
        "run_id": run_id,
        "total": len(key),
        "answered": answered,
        "correct": correct,
        "accuracy": correct / max(1, len(key)),
        group_field: {g: sum(v) / len(v) for g, v in sorted(groups.items())},
    }
    out = os.path.join(workspace, "runs", run_id, "score.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    return result


def run_cli(grove_dir: str, default_workspace: str,
            grade_fn: Callable[[dict, object], bool], group_key: str,
            group_field: str, secret_fields: Sequence[str],
            doc: str) -> int:
    ap = argparse.ArgumentParser(description=doc)
    ap.add_argument("--prepare", action="store_true")
    ap.add_argument("--run", metavar="RUN_ID")
    ap.add_argument("--workspace", default=default_workspace)
    args = ap.parse_args()
    if args.prepare:
        prepare(args.workspace, grove_dir, secret_fields)
        return 0
    if args.run:
        print(json.dumps(score(args.workspace, args.run, grove_dir,
                               grade_fn, group_key, group_field), indent=1))
        return 0
    ap.print_help()
    return 2
