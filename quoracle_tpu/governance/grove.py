"""Groves: declarative multi-agent environments (GROVE.md manifests).

Parity with the reference's Groves subsystem (reference
lib/quoracle/groves/): Loader parses GROVE.md YAML frontmatter — bootstrap,
topology, governance, confinement, schemas, workspace (loader.ex:12-47);
HardRuleEnforcer applies shell_pattern_block / action_block rules and
path confinement with * and ** globs in strict-vs-warn mode
(hard_rule_enforcer.ex:41-60, README.md:450-486); PathSecurity rejects
traversal and symlink escapes (path_security.ex:14-50); SchemaValidator
runs JSON-Schema validation on file_write payloads matched by path_pattern
(schema_validator.ex, README.md:504-518); TopologyResolver auto-injects
skills/profile/constraints on spawn along declared edges
(README.md:520-545); GovernanceResolver injects governance docs into scoped
agents' prompts; BootstrapResolver pre-fills task creation.

An agent's place in a grove is its *node* (the reference scopes rules by
skill-role names, e.g. ``mmlu-answerer``); the node travels in AgentConfig
and every check takes it explicitly.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import Any, Optional

import yaml

from quoracle_tpu.governance.skills import _FRONTMATTER_RE, SkillsLoader

logger = logging.getLogger(__name__)


class GroveError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HardRule:
    type: str                         # shell_pattern_block | action_block
    message: str = ""
    pattern: Optional[str] = None     # shell_pattern_block
    actions: tuple[str, ...] = ()     # action_block
    scope: tuple[str, ...] = ()       # node names; empty = every node


@dataclasses.dataclass
class TopologyEdge:
    parent: str
    child: str
    auto_inject: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchemaRule:
    name: str
    definition: str                   # path to JSON schema, grove-relative
    path_pattern: str
    validate_on: str = "file_write"


@dataclasses.dataclass
class GroveManifest:
    name: str
    path: str                         # grove directory
    description: str = ""
    version: str = ""
    root_node: Optional[str] = None
    edges: tuple[TopologyEdge, ...] = ()
    hard_rules: tuple[HardRule, ...] = ()
    injections: tuple[dict, ...] = ()
    schemas: tuple[SchemaRule, ...] = ()
    workspace: Optional[str] = None
    confinement: dict = dataclasses.field(default_factory=dict)
    confinement_mode: str = "warn"    # "warn" | "strict"
    bootstrap: dict = dataclasses.field(default_factory=dict)

    @property
    def skills_dir(self) -> str:
        return os.path.join(self.path, "skills")


def load_grove(grove_dir: str) -> GroveManifest:
    """Parse <grove_dir>/GROVE.md (reference loader.ex:12-47)."""
    manifest_path = os.path.join(grove_dir, "GROVE.md")
    try:
        with open(manifest_path) as f:
            text = f.read()
    except OSError as e:
        raise GroveError(f"cannot read {manifest_path}: {e}")
    m = _FRONTMATTER_RE.match(text)
    if not m:
        raise GroveError(f"{manifest_path} has no YAML frontmatter")
    try:
        data = yaml.safe_load(m.group(1)) or {}
    except yaml.YAMLError as e:
        raise GroveError(f"bad YAML in {manifest_path}: {e}")
    if not data.get("name"):
        raise GroveError(f"{manifest_path}: grove needs a name")

    topology = data.get("topology") or {}
    edges = tuple(
        TopologyEdge(parent=e["parent"], child=e["child"],
                     auto_inject=e.get("auto_inject") or {})
        for e in topology.get("edges") or ())
    governance = data.get("governance") or {}
    hard_rules = tuple(
        HardRule(type=r.get("type", ""), message=r.get("message", ""),
                 pattern=r.get("pattern"),
                 actions=tuple(r.get("actions") or ()),
                 scope=tuple(r.get("scope") or ()))
        for r in governance.get("hard_rules") or ())
    schemas = tuple(
        SchemaRule(name=s.get("name", ""), definition=s["definition"],
                   path_pattern=s["path_pattern"],
                   validate_on=s.get("validate_on", "file_write"))
        for s in data.get("schemas") or ())
    return GroveManifest(
        name=str(data["name"]), path=os.path.abspath(grove_dir),
        description=str(data.get("description", "")).strip(),
        version=str(data.get("version", "")),
        root_node=topology.get("root"),
        edges=edges, hard_rules=hard_rules,
        injections=tuple(governance.get("injections") or ()),
        schemas=schemas,
        workspace=data.get("workspace"),
        confinement=data.get("confinement") or {},
        confinement_mode=str(data.get("confinement_mode", "warn")),
        bootstrap=data.get("bootstrap") or {},
    )


def list_groves(groves_dir: str) -> list[GroveManifest]:
    """Scan a directory of groves (reference loader.ex:57 list_groves)."""
    out = []
    if not os.path.isdir(groves_dir):
        return out
    for entry in sorted(os.listdir(groves_dir)):
        full = os.path.join(groves_dir, entry)
        if os.path.isfile(os.path.join(full, "GROVE.md")):
            try:
                out.append(load_grove(full))
            except GroveError:
                logger.warning("skipping malformed grove at %s", full)
    return out


# ---------------------------------------------------------------------------
# Path security (reference path_security.ex:14-50)
# ---------------------------------------------------------------------------

def _expand(p: str) -> str:
    return os.path.abspath(os.path.expanduser(p))


def _resolve_real(path: str) -> str:
    """Resolve symlinks on the deepest existing ancestor so a symlink inside
    an allowed directory cannot smuggle writes outside it."""
    path = _expand(path)
    probe = path
    while not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    real_probe = os.path.realpath(probe)
    return os.path.join(real_probe, os.path.relpath(path, probe)) \
        if probe != path else real_probe


def _glob_match(path: str, pattern: str,
                base: Optional[str] = None) -> bool:
    """Glob with ** (any depth) and * (single segment) semantics. A pattern
    ending in ``/**`` also matches the directory itself (a confined node
    must be able to use the root of its allowed tree as a working dir).
    Relative patterns resolve against ``base`` (the grove workspace), never
    the server process CWD."""
    if base and not pattern.startswith(("/", "~")):
        pattern = os.path.join(base, pattern)
    pattern = _expand(pattern)
    regex = ""
    i = 0
    while i < len(pattern):
        if pattern.startswith("/**", i) and i + 3 == len(pattern):
            regex += "(/.*)?"
            i += 3
        elif pattern.startswith("/**/", i):
            # Interior /**/ matches zero or more intermediate directories:
            # a/**/b matches both a/b and a/x/y/b (standard glob semantics).
            regex += "/(.*/)?"
            i += 4
        elif pattern.startswith("**", i):
            regex += ".*"
            i += 2
        elif pattern[i] == "*":
            regex += "[^/]*"
            i += 1
        else:
            regex += re.escape(pattern[i])
            i += 1
    return re.fullmatch(regex, path) is not None


# ---------------------------------------------------------------------------
# Enforcer (reference hard_rule_enforcer.ex + schema_validator.ex)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SpawnResolution:
    """What topology auto-injection adds to a spawn (reference
    TopologyResolver.apply_spawn_contract)."""
    node: Optional[str] = None
    skills: tuple[str, ...] = ()
    profile: Optional[str] = None
    constraints: Optional[str] = None
    model_pool: Optional[list[str]] = None
    capability_groups: Optional[list[str]] = None


class GroveEnforcer:
    """Runtime enforcement bound to one manifest. Every check takes the
    agent's node explicitly (no per-agent enforcer objects to keep in sync).
    Returns an error string to block, None to allow. In warn mode
    confinement violations log and pass; hard rules ALWAYS block
    (reference README.md:450-486 — hard rules are absolute, confinement has
    strict/warn)."""

    def __init__(self, manifest: GroveManifest):
        self.manifest = manifest
        self._schema_cache: dict[str, Any] = {}
        # base for relative confinement/schema patterns: the workspace,
        # falling back to the grove directory
        self._pattern_base = (_expand(manifest.workspace)
                              if manifest.workspace else manifest.path)

    # -- hard rules ----------------------------------------------------

    def _rules_for(self, node: Optional[str], rule_type: str):
        for rule in self.manifest.hard_rules:
            if rule.type != rule_type:
                continue
            if rule.scope and (node is None or node not in rule.scope):
                continue
            yield rule

    def check_shell_command(self, command: str,
                            node: Optional[str]) -> Optional[str]:
        for rule in self._rules_for(node, "shell_pattern_block"):
            if rule.pattern and re.search(rule.pattern, command):
                return (f"blocked by grove hard rule: "
                        f"{rule.message or rule.pattern}")
        return None

    def blocked_actions(self, node: Optional[str]) -> set[str]:
        """Feeds AgentConfig.forbidden_actions → capability filtering
        (reference consensus_handler.ex:294-313)."""
        out: set[str] = set()
        for rule in self._rules_for(node, "action_block"):
            out.update(rule.actions)
        return out

    # -- confinement ---------------------------------------------------

    def _confinement_for(self, node: Optional[str]) -> Optional[dict]:
        if node is None:
            return None
        return self.manifest.confinement.get(node)

    def check_file_path(self, path: str, *, write: bool,
                        node: Optional[str]) -> Optional[str]:
        conf = self._confinement_for(node)
        if conf is None:
            return None
        real = _resolve_real(path)
        writable = [p for p in conf.get("paths") or ()]
        readable = writable + [p for p in conf.get("read_only_paths") or ()]
        allowed = writable if write else readable
        if any(_glob_match(real, pat, self._pattern_base)
               for pat in allowed):
            return None
        verb = "write" if write else "read"
        msg = (f"confinement: {verb} of {path!r} is outside the allowed "
               f"paths for node {node!r}")
        if self.manifest.confinement_mode == "strict":
            return msg
        logger.warning("%s (warn mode: allowing)", msg)
        return None

    def check_working_dir(self, path: str,
                          node: Optional[str]) -> Optional[str]:
        conf = self._confinement_for(node)
        if conf is None:
            return None
        return self.check_file_path(path, write=True, node=node)

    # -- schema validation (reference schema_validator.ex) -------------

    def validate_file_schema(self, path: str, content: str) -> Optional[str]:
        real = _resolve_real(path)
        for rule in self.manifest.schemas:
            if rule.validate_on != "file_write":
                continue
            # relative path_patterns resolve against the workspace — never
            # as a floating suffix match anywhere on the filesystem
            if not _glob_match(real, rule.path_pattern, self._pattern_base):
                continue
            import json
            try:
                payload = json.loads(content)
            except json.JSONDecodeError as e:
                return f"schema {rule.name}: payload is not JSON ({e})"
            schema = self._schema_cache.get(rule.definition)
            if schema is None:
                try:
                    with open(os.path.join(self.manifest.path,
                                           rule.definition)) as f:
                        schema = json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    return f"schema {rule.name}: cannot load definition ({e})"
                self._schema_cache[rule.definition] = schema
            try:
                import jsonschema
                jsonschema.validate(payload, schema)
            except jsonschema.ValidationError as e:
                return f"schema {rule.name}: {e.message}"
        return None

    # -- topology (reference TopologyResolver / SpawnContractResolver) --

    def resolve_spawn(self, parent_node: Optional[str],
                      params: dict) -> SpawnResolution:
        """Find the edge this spawn follows and apply its contract. With one
        outgoing edge the child node is implied; with several, the spawn's
        requested profile/skills pick the edge."""
        if parent_node is None:
            # An agent outside the topology isn't constrained by it.
            return SpawnResolution(node=None)
        edges = [e for e in self.manifest.edges if e.parent == parent_node]
        if not edges:
            # Fail closed: a node with no outgoing edges may not spawn —
            # otherwise its children would escape every node-scoped rule.
            raise GroveError(
                f"grove topology: node {parent_node!r} has no outgoing "
                f"edges; it may not spawn children")
        edge: Optional[TopologyEdge] = None
        if len(edges) == 1:
            edge = edges[0]
        else:
            wanted = set(params.get("skills") or ())
            wanted.add(params.get("profile"))
            for e in edges:
                if e.child in wanted:
                    edge = e
                    break
        if edge is None:
            raise GroveError(
                f"grove topology: node {parent_node!r} has multiple child "
                f"node types ({', '.join(e.child for e in edges)}); name "
                f"one via the spawn profile or skills params")
        inject = edge.auto_inject
        return SpawnResolution(
            node=edge.child,
            skills=tuple(inject.get("skills") or ()),
            profile=inject.get("profile"),
            constraints=inject.get("constraints"),
            model_pool=inject.get("model_pool"),
            capability_groups=inject.get("capability_groups"),
        )

    # -- governance docs (reference GovernanceResolver) -----------------

    def governance_docs_for(self, node: Optional[str]) -> Optional[str]:
        chunks: list[tuple[int, str]] = []
        for inj in self.manifest.injections:
            targets = inj.get("inject_into") or ()
            if targets and (node is None or node not in targets):
                continue
            source = os.path.join(self.manifest.path, inj.get("source", ""))
            try:
                with open(source) as f:
                    text = f.read().strip()
            except OSError:
                logger.warning("governance injection source missing: %s",
                               source)
                continue
            prio = 0 if inj.get("priority") == "high" else 1
            chunks.append((prio, text))
        if not chunks:
            return None
        return "\n\n".join(text for _, text in sorted(chunks,
                                                      key=lambda c: c[0]))

    # -- bootstrap (reference BootstrapResolver) ------------------------

    def bootstrap_fields(self) -> dict:
        """Pre-fill for task creation: file-backed fields are read from the
        grove directory."""
        b = dict(self.manifest.bootstrap)
        for key, target in (("global_context_file", "global_context"),
                            ("task_description_file", "task_description"),
                            ("success_criteria_file", "success_criteria")):
            rel = b.pop(key, None)
            if rel:
                try:
                    with open(os.path.join(self.manifest.path, rel)) as f:
                        b[target] = f.read().strip()
                except OSError:
                    logger.warning("bootstrap file missing: %s", rel)
        return b

    def skills_loader(self, global_dir: Optional[str] = None) -> SkillsLoader:
        return SkillsLoader(global_dir=global_dir,
                            grove_dir=self.manifest.skills_dir)

    def workspace_dir(self) -> Optional[str]:
        if not self.manifest.workspace:
            return None
        return _expand(self.manifest.workspace)
