"""Governance layer: profiles, capability gating, groves, skills, prompt fields.

Reference: lib/quoracle/{profiles,groves,skills,fields}/ — cross-cutting rules
that gate actions, shape prompts, and constrain spawn (SURVEY.md §1 layer 8).
"""

from quoracle_tpu.governance.capabilities import (  # noqa: F401
    allowed_actions_for_groups, filter_actions, validate_groups,
)
from quoracle_tpu.governance.fields import (  # noqa: F401
    AgentFields, accumulate_constraints, compose_field_prompt,
)
from quoracle_tpu.governance.grove import (  # noqa: F401
    GroveEnforcer, GroveManifest, list_groves, load_grove,
)
from quoracle_tpu.governance.skills import Skill, SkillsLoader  # noqa: F401
