"""Governance layer: profiles, capability gating, groves, skills, prompt fields.

Reference: lib/quoracle/{profiles,groves,skills,fields}/ — cross-cutting rules
that gate actions, shape prompts, and constrain spawn (SURVEY.md §1 layer 8).
"""
