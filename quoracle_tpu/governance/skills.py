"""Skills: loadable instruction packages (SKILL.md files).

Parity with the reference's Skills.Loader / Creator (reference
lib/quoracle/skills/loader.ex:22-41,63-70 — SKILL.md = YAML frontmatter +
markdown body; a grove-local skills/ directory shadows the global one;
skills are listed in the system prompt and loaded at runtime via the
learn_skills action, which invalidates the cached system prompt,
reference core.ex:338-341).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

import yaml

_FRONTMATTER_RE = re.compile(r"\A---\s*\n(.*?)\n---\s*\n?(.*)\Z", re.DOTALL)


class SkillError(ValueError):
    pass


@dataclasses.dataclass
class Skill:
    name: str
    description: str
    content: str
    path: Optional[str] = None
    source: str = "global"          # "global" | "grove"

    def as_dict(self) -> dict:
        return {"name": self.name, "description": self.description,
                "content": self.content}


def parse_skill_md(text: str, path: Optional[str] = None) -> Skill:
    m = _FRONTMATTER_RE.match(text)
    if not m:
        raise SkillError(f"not a SKILL.md (missing frontmatter): {path}")
    try:
        meta = yaml.safe_load(m.group(1)) or {}
    except yaml.YAMLError as e:
        raise SkillError(f"bad frontmatter in {path}: {e}")
    if not isinstance(meta, dict) or not meta.get("name"):
        raise SkillError(f"frontmatter needs a name: {path}")
    return Skill(name=str(meta["name"]),
                 description=str(meta.get("description", "")).strip(),
                 content=m.group(2).strip(), path=path)


def render_skill_md(name: str, description: str, content: str) -> str:
    fm = yaml.safe_dump({"name": name, "description": description},
                        sort_keys=False).strip()
    return f"---\n{fm}\n---\n\n{content.strip()}\n"


class SkillsLoader:
    """Loads skills from a global directory, optionally shadowed by a
    grove-local one (reference loader.ex:63-70: grove skills win on name
    collision). Layout: <dir>/<skill-name>/SKILL.md or <dir>/<name>.md."""

    def __init__(self, global_dir: Optional[str] = None,
                 grove_dir: Optional[str] = None):
        self.global_dir = global_dir
        self.grove_dir = grove_dir

    # ------------------------------------------------------------------

    def _scan_dir(self, directory: Optional[str], source: str) -> dict[str, Skill]:
        found: dict[str, Skill] = {}
        if not directory or not os.path.isdir(directory):
            return found
        for entry in sorted(os.listdir(directory)):
            full = os.path.join(directory, entry)
            candidates = []
            if os.path.isdir(full):
                candidates.append(os.path.join(full, "SKILL.md"))
            elif entry.endswith(".md") and entry != "README.md":
                candidates.append(full)
            for c in candidates:
                if not os.path.isfile(c):
                    continue
                try:
                    with open(c) as f:
                        skill = parse_skill_md(f.read(), path=c)
                    skill.source = source
                    found[skill.name] = skill
                except (SkillError, OSError):
                    continue  # malformed skill files never break listing
        return found

    def all(self) -> dict[str, Skill]:
        skills = self._scan_dir(self.global_dir, "global")
        skills.update(self._scan_dir(self.grove_dir, "grove"))  # shadows
        return skills

    def load(self, name: str) -> Optional[Skill]:
        return self.all().get(name)

    def listing(self) -> list[dict]:
        """name+description dicts for the system prompt's Available Skills
        section."""
        return [{"name": s.name, "description": s.description}
                for s in self.all().values()]

    def search(self, query: str) -> list[Skill]:
        q = query.lower()
        return [s for s in self.all().values()
                if q in s.name.lower() or q in s.description.lower()]

    # ------------------------------------------------------------------

    def create(self, name: str, description: str, content: str) -> Skill:
        """Author a new skill into the global directory (reference
        skills/creator.ex)."""
        if not self.global_dir:
            raise SkillError("no global skills directory configured")
        if not re.fullmatch(r"[A-Za-z0-9_\-]+", name):
            raise SkillError(f"invalid skill name {name!r}")
        skill_dir = os.path.join(self.global_dir, name)
        os.makedirs(skill_dir, exist_ok=True)
        path = os.path.join(skill_dir, "SKILL.md")
        with open(path, "w") as f:
            f.write(render_skill_md(name, description, content))
        return Skill(name=name, description=description,
                     content=content.strip(), path=path)
