"""Prompt fields: agent identity composition + constraint accumulation.

Parity with the reference's Fields subsystem (reference
lib/quoracle/fields/ — PromptFieldManager: *injected* task-level fields
(global context, constraints) vs *provided* per-agent fields (role,
cognitive style, …); parent→child transformation; ConstraintAccumulator
carries constraints down the spawn tree so a child can never escape an
ancestor's constraint; CognitiveStyles maps style atoms to reasoning
directives, reference fields/cognitive_styles.ex:6-40).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

# Style atom → reasoning directive. Same vocabulary as the reference's
# style set; directive text is our own.
COGNITIVE_STYLES: dict[str, str] = {
    "systematic": (
        "Work systematically: decompose the task into explicit steps, "
        "execute them in order, and verify each step's outcome before "
        "moving on."),
    "creative": (
        "Favor novel approaches: generate multiple distinct options before "
        "committing, and prefer an unconventional path when the obvious one "
        "is weak."),
    "skeptical": (
        "Challenge assumptions: actively look for reasons the current plan "
        "or claim is wrong, and demand evidence before accepting results."),
    "collaborative": (
        "Coordinate actively: keep your parent and children informed of "
        "progress, surface blockers early, and prefer delegating to "
        "duplicating work."),
    "decisive": (
        "Bias to action: pick the best available option quickly, commit, "
        "and course-correct later rather than over-deliberating."),
    "analytical": (
        "Reason quantitatively: prefer measurements, counts, and concrete "
        "comparisons over qualitative impressions; show your working."),
}


def style_directive(style: Optional[str]) -> Optional[str]:
    if not style:
        return None
    return COGNITIVE_STYLES.get(style,
                                f"Adopt this cognitive style: {style}")


@dataclasses.dataclass(frozen=True)
class AgentFields:
    """The provided per-agent identity fields (reference's 9 agent fields,
    fields/schemas.ex). All optional; the composer skips empty ones."""
    role: Optional[str] = None
    cognitive_style: Optional[str] = None
    constraints: Optional[str] = None
    global_context: Optional[str] = None
    delegation_strategy: Optional[str] = None
    communication_style: Optional[str] = None
    risk_tolerance: Optional[str] = None
    planning_horizon: Optional[str] = None
    identity_notes: Optional[str] = None


def compose_field_prompt(fields: AgentFields,
                         accumulated_constraints: Sequence[str] = ()) -> Optional[str]:
    """Render the identity block of the system prompt (replaces the interim
    composer that lived in actions/executors.py). Accumulated ancestor
    constraints always render — a child cannot drop them."""
    parts: list[str] = []
    if fields.role:
        parts.append(f"Your role: {fields.role}")
    directive = style_directive(fields.cognitive_style)
    if directive:
        parts.append(directive)
    for label, value in (
        ("Delegation strategy", fields.delegation_strategy),
        ("Communication style", fields.communication_style),
        ("Risk tolerance", fields.risk_tolerance),
        ("Planning horizon", fields.planning_horizon),
    ):
        if value:
            parts.append(f"{label}: {value}")
    if fields.identity_notes:
        parts.append(fields.identity_notes)
    if fields.global_context:
        parts.append(f"Global context:\n{fields.global_context}")
    constraints = [c for c in accumulated_constraints if c]
    if fields.constraints:
        constraints.append(fields.constraints)
    if constraints:
        parts.append("Constraints you must respect (yours and every "
                     "ancestor's):\n"
                     + "\n".join(f"- {c}" for c in constraints))
    return "\n\n".join(parts) or None


def accumulate_constraints(parent_accumulated: Sequence[str],
                           parent_own: Optional[str]) -> tuple[str, ...]:
    """Constraints flow down the tree (reference ConstraintAccumulator):
    the child's accumulated set = parent's accumulated + parent's own."""
    out = list(parent_accumulated)
    if parent_own:
        out.append(parent_own)
    return tuple(out)


def child_fields_from_spawn(params: dict) -> AgentFields:
    """Spawn params → the child's provided fields (reference
    FieldTransformer: the spawn action's field params become the child's
    provided fields verbatim; transformation hooks apply on top)."""
    return AgentFields(
        role=params.get("role"),
        cognitive_style=params.get("cognitive_style"),
        constraints=params.get("constraints"),
        global_context=params.get("global_context"),
    )
