"""Composition root: builds and owns the whole service graph.

The explicit-factory equivalent of the reference's supervision tree
(reference lib/quoracle/application.ex:38-61: Vault → Repo → PubSub →
Registry → EmbeddingCache → Task.Supervisor → Agent.DynSup → EventHistory →
Endpoint, then boot revival at :74). There are no singletons: a Runtime owns
one instance of each service and hands them to agents via AgentDeps — build
two Runtimes and they share nothing (the reference's cardinal DI rule, root
AGENTS.md:5-33).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Optional

from quoracle_tpu.agent.registry import AgentRegistry
from quoracle_tpu.agent.state import AgentDeps
from quoracle_tpu.agent.supervisor import AgentSupervisor
from quoracle_tpu.context.token_manager import TokenManager
from quoracle_tpu.infra.budget import Escrow
from quoracle_tpu.consensus.quality import QUALITY
from quoracle_tpu.infra.bus import (
    TOPIC_CONSENSUS, TOPIC_RESOURCES, TOPIC_TRACE, AgentEvents, EventBus,
)
from quoracle_tpu.infra.costs import CostRecorder
from quoracle_tpu.infra.event_history import EventHistory
from quoracle_tpu.infra.flightrec import FLIGHT
from quoracle_tpu.infra.telemetry import METRICS, TRACER
from quoracle_tpu.models.runtime import MockBackend, ModelBackend, TPUBackend
from quoracle_tpu.persistence import Database, Persistence, TaskManager
from quoracle_tpu.persistence.store import PersistentSecretStore


logger = logging.getLogger(__name__)


class StallWatchdog:
    """Detects wedged decode loops (ISSUE 3): each SOURCE is a
    ``(name, fn)`` pair where ``fn() -> (active, progress)`` — ``active``
    says the source has work in flight, ``progress`` is a monotonic
    counter that advances whenever real work completes (the continuous
    batcher's chunk-step count, models/scheduler.py). A source that stays
    active with a frozen counter past ``deadline_s`` trips the watchdog:
    the stall counter/gauge record it, a ``watchdog_stall`` event rides
    ``TOPIC_RESOURCES`` onto the bus (dashboard SSE + /api/history), and
    the flight recorder dumps the last spans/resource samples/scheduler
    transitions to disk — the incident is attributable after the fact
    even if the process is killed moments later.

    A tripped source un-trips itself when progress resumes or the work
    drains (gauge back to 0); each distinct wedge trips once per
    ``rearm_cooldown_s``, not once per poll — and not once per PROCESS:
    after the cooldown a still-frozen (or newly re-frozen) source
    re-trips and re-dumps (ISSUE 11 satellite; the old one-shot
    behavior meant a second stall after the first was silently
    undetected and a day-long wedge produced exactly one artifact)."""

    def __init__(self, bus: Optional[EventBus] = None,
                 deadline_s: float = 30.0,
                 poll_s: Optional[float] = None,
                 rearm_cooldown_s: Optional[float] = None):
        self.bus = bus
        self.deadline_s = deadline_s
        self.poll_s = poll_s if poll_s is not None \
            else max(0.5, deadline_s / 4)
        # default: re-arm after 4 deadlines — long enough that one wedge
        # doesn't dump-storm, short enough that an operator watching a
        # multi-hour incident gets fresh evidence
        self.rearm_cooldown_s = (rearm_cooldown_s
                                 if rearm_cooldown_s is not None
                                 else 4 * deadline_s)
        self._sources: dict[str, Callable[[], tuple]] = {}
        self._last: dict[str, tuple] = {}     # name -> (progress, since)
        self._tripped: dict[str, float] = {}  # name -> last trip time
        self.trips = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_source(self, name: str, fn: Callable[[], tuple]) -> None:
        with self._lock:
            self._sources[name] = fn

    def start(self) -> None:
        """Start the poll thread — only once there is something to watch
        (a Runtime over a MockBackend registers no sources and spends no
        thread)."""
        if self._thread is not None or not self._sources:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="stall-watchdog", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check_now()

    def check_now(self) -> list[str]:
        """One scan over every source; returns the names that tripped in
        THIS scan (tests drive this directly instead of sleeping)."""
        now = time.monotonic()
        with self._lock:
            sources = dict(self._sources)
        tripped = []
        for name, fn in sources.items():
            try:
                active, progress = fn()
            except Exception:             # noqa: BLE001 — telemetry only
                continue
            last = self._last.get(name)
            if not active:
                self._last.pop(name, None)
                self._untrip(name)
                continue
            if last is None or last[0] != progress:
                self._last[name] = (progress, now)
                self._untrip(name)
                continue
            if now - last[1] < self.deadline_s:
                continue
            last_trip = self._tripped.get(name)
            # first trip fires immediately; a source STILL frozen past
            # the cooldown re-trips (fresh dump — the wedge is ongoing
            # and the first artifact may be long pruned)
            if last_trip is None \
                    or now - last_trip >= self.rearm_cooldown_s:
                self._tripped[name] = now
                self.trips += 1
                tripped.append(name)
                self._trip(name, now - last[1])
        return tripped

    def _untrip(self, name: str) -> None:
        if name in self._tripped:
            self._tripped.pop(name, None)
            from quoracle_tpu.infra.telemetry import WATCHDOG_STALLED
            WATCHDOG_STALLED.set(0.0, source=name)

    def _trip(self, name: str, stalled_s: float) -> None:
        from quoracle_tpu.infra.telemetry import (
            WATCHDOG_STALLED, WATCHDOG_STALLS,
        )
        WATCHDOG_STALLS.inc(source=name)
        WATCHDOG_STALLED.set(1.0, source=name)
        FLIGHT.record("watchdog_stall", source=name,
                      stalled_s=round(stalled_s, 1),
                      deadline_s=self.deadline_s)
        dump_path = None
        try:
            dump_path = FLIGHT.dump(reason=f"watchdog-{name}")
        except Exception:                 # noqa: BLE001 — keep serving
            logger.exception("flight-recorder dump failed on stall")
        # correlated incident capture (ISSUE 15): the trip also opens a
        # deterministic incident — on a fabric front door the id fans
        # out so every peer's flight ring joins the bundle
        from quoracle_tpu.infra.fleetobs import INCIDENTS
        INCIDENTS.capture("watchdog", name,
                          reason=f"no progress for {stalled_s:.1f}s")
        logger.error("stall watchdog tripped: %s made no progress for "
                     "%.1fs (flight recorder: %s)", name, stalled_s,
                     dump_path)
        if self.bus is not None:
            try:
                self.bus.broadcast(TOPIC_RESOURCES, {
                    "event": "watchdog_stall", "ts": time.time(),
                    "source": name, "stalled_s": round(stalled_s, 1),
                    "deadline_s": self.deadline_s,
                    "dump_path": dump_path,
                })
            except Exception:             # noqa: BLE001 — telemetry only
                pass

    def status(self) -> dict:
        with self._lock:
            return {
                "deadline_s": self.deadline_s,
                "rearm_cooldown_s": self.rearm_cooldown_s,
                "sources": sorted(self._sources),
                "tripped": sorted(self._tripped),
                "trips": self.trips,
                "running": self._thread is not None,
            }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


@dataclasses.dataclass
class RuntimeConfig:
    db_path: str = ":memory:"
    encryption_key: Optional[str] = None      # default: env QUORACLE_ENCRYPTION_KEY
    backend: str = "mock"                     # "mock" | "tpu"
    model_pool: Optional[list[str]] = None    # default pool for tpu backend
    embed_model: Optional[str] = None
    seed: int = 0
    skills_dir: Optional[str] = None          # global skills directory
    groves_dir: Optional[str] = None          # directory of grove dirs
    # HF checkpoint directories (real weights + the checkpoint's own
    # tokenizer). Each registers into the catalog as xla:<dirname> and — when
    # model_pool is unset — the registered names BECOME the pool, so
    # `--backend tpu --checkpoint dir1 --checkpoint dir2` serves real
    # checkpoints with zero extra wiring (reference model_query.ex:222-259
    # serves whatever models credentials point at).
    checkpoints: Optional[list[str]] = None
    # Multi-chip serving: tensor-parallel size per pool member. With more
    # than one visible device the pool is partitioned into per-member
    # sub-meshes (parallel.mesh.pool_submeshes) and members overlap from
    # host threads; on one chip this is ignored.
    tp: Optional[int] = None
    # generate_images backend: "procedural" (deterministic placeholder
    # PNGs, zero compute) or "diffusion" (on-device UNet + DDIM sampler,
    # models/diffusion.py — the TPU-native analog of the reference's hosted
    # image models, image_query.ex:1-12).
    image_backend: str = "procedural"
    # Speculative serving (models/speculative.py): {target_spec:
    # draft_spec} — eligible member queries draft-K/verify-one-chunk;
    # drafts load like members but never serve directly. Also settable
    # via the DB setting "draft_map" (dashboard /api/settings). Under
    # ``continuous`` the drafted members speculate INSIDE the shared
    # decode loop (BatchedSpeculator, ISSUE 6) with ``draft_k`` as the
    # initial adaptive draft length.
    draft_map: Optional[dict] = None
    draft_k: int = 6
    # Multi-host: join the JAX distributed system before building the
    # backend (parallel/distributed.init_process). On TPU pods the three
    # values are usually auto-detected — set coordinator_address (and
    # num_processes/process_id on CPU/GPU clusters) to join explicitly.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # Decode-level continuous batching (models/scheduler.py) for the TPU
    # backend's pool members (round-granularity baton batching otherwise).
    continuous: bool = False
    # Serving QoS (ISSUE 4): True for defaults, or a serving/qos.QoSConfig
    # (a dict of its fields also works — handy from CLI/JSON config).
    # Turns on weighted-fair admission + overload shedding; implies
    # nothing unless the backend is "tpu".
    qos: Any = None
    # Tiered KV (ISSUE 7, serving/kvtier.py): host-RAM budget per pool
    # member for hibernated sessions/prefix blocks (0 = tiering off
    # unless disk_kv_dir is set), and the directory of the checksummed
    # disk prefix store that warm-starts the next process. Resident
    # session capacity stops being bounded by resident_kv_tokens and
    # becomes bounded by host RAM.
    host_kv_mb: int = 0
    disk_kv_dir: Optional[str] = None
    # Byte budget of the disk prefix store (per member): oldest-LRU
    # entries prune when a write overflows it, so a long-running fleet
    # cannot fill the disk. Matches pool_sizing's disk_kv_gb knob.
    disk_kv_gb: float = 8.0
    # Disaggregated serving plane (ISSUE 10, serving/cluster.py):
    # ``replicas`` > 1 builds a ClusterPlane of N full per-member engine
    # sets, each on its own contiguous slice of the local devices
    # (parallel/mesh.replica_device_groups → pool_submeshes per
    # replica). ``disaggregate`` role-tags them into prefill/decode
    # tiers with KV handoff between them; off, replicas are uniform
    # data-parallel copies routed by session affinity + load. Scale
    # from here on means raising --replicas, not re-architecting.
    replicas: int = 1
    disaggregate: bool = False
    # Elastic fleet controller (ISSUE 14, serving/fleet.py):
    # ``fleet_max`` > 0 arms a FleetController over the ClusterPlane —
    # a ticker thread evaluates the policy every ``fleet_tick_s``,
    # scaling the serving tier within [fleet_min, fleet_max], re-tiering
    # roles when the traffic mix shifts, and draining replicas by live
    # session migration. Requires --replicas/--disaggregate (there is
    # no fleet without a cluster). 0 (the default) keeps the static
    # boot topology.
    fleet_min: int = 1
    fleet_max: int = 0
    fleet_tick_s: float = 5.0
    # Chaos plane (ISSUE 11, quoracle_tpu/chaos/): path to a JSON fault
    # plan ({"seed": N, "faults": [{"point", "kind", ...}]}) armed on
    # the process-wide CHAOS plane at boot — game-day runs against a
    # canary. None (the default) injects nothing and costs one
    # attribute read per seam hit.
    chaos_plan: Optional[str] = None
    # Cross-host cluster fabric (ISSUE 12, serving/fabric/). Three
    # process roles, mutually composable:
    #   fabric_peers  — this node is the standalone ROUTER FRONT DOOR:
    #                   no local engines; serve through a FabricPlane
    #                   over these "[role@]host:port" peers (the
    #                   SignalSnapshot poll protocol drives placement
    #                   and aggregate admission).
    #   fabric_listen — this node is a REPLICA PEER: serve the local
    #                   backend over the wire at "[role@]host:port"
    #                   (role prefill|decode|unified; default unified)
    #                   beside its normal local serving.
    #   prefixd       — "host:port" of the fleet prefix service: every
    #                   engine tier gets a read-through client, so this
    #                   replica warm-starts from the fleet's prefixes,
    #                   not only its own disk.
    fabric_peers: Optional[list[str]] = None
    fabric_listen: Optional[str] = None
    prefixd: Optional[str] = None
    # Quantized serving (ISSUE 13, models/quant.py): per-member opt-in
    # int8. ``quantize_weights`` quantizes every engine's projection
    # matrices per-channel at load (~2x more members fit at fixed HBM);
    # ``quantize_kv`` stores int8 KV pages with per-(token, kv-head)
    # scales beside them (resident_kv_tokens ~doubles; every demote,
    # spill, prefix write-through and handoff envelope ships ~half the
    # bytes). The KV quant format is part of kv_signature, so a
    # quantized↔unquantized peer pair rejects handoff before bytes move
    # and degrades to a cold re-prefill. Off by default: the
    # unquantized path keeps its temp-0 bit-equality gates untouched.
    quantize_weights: bool = False
    quantize_kv: bool = False
    # Fleet simulator (ISSUE 16, quoracle_tpu/sim/): ``sim_trace`` is a
    # path to a serialized workload trace replayed at boot on a daemon
    # thread — compressed virtual time, capacity model sized from the
    # live router's capacity_hint(), forecast priors offered to the
    # fleet controller's shadow seam, results on GET /api/sim and
    # TOPIC_SIM. ``sim_seed`` (with no trace path) regenerates the
    # canonical diurnal-mix trace from that seed instead. Both None
    # (the default) = no simulator thread at all.
    sim_trace: Optional[str] = None
    sim_seed: Optional[int] = None
    # Serving flywheel (ISSUE 19, quoracle_tpu/training/):
    # ``capture_dir`` installs the replay capture store at boot — the
    # BatchedSpeculator and consensus-quality taps start feeding it
    # crc-framed training examples, size-bounded to ``capture_mb``
    # (oldest-first segment eviction). Serving only ever APPENDS here;
    # the trainer/evaluator read it offline. None (the default) = no
    # store, and the taps cost one attribute read per round. The whole
    # plane is env-killable via QUORACLE_TRAIN_CAPTURE=0.
    capture_dir: Optional[str] = None
    capture_mb: float = 256.0


class Runtime:
    """One running quoracle_tpu node. Construct → (await) boot() → use
    .tasks / .deps; close() tears everything down."""

    def __init__(self, config: Optional[RuntimeConfig] = None,
                 backend: Optional[ModelBackend] = None):
        config = config if config is not None else RuntimeConfig()
        self.config = config
        self.db = Database(config.db_path,
                           encryption_key=config.encryption_key)
        self.store = Persistence(self.db)
        self.bus = EventBus()
        self.events = AgentEvents(self.bus)
        self.history = EventHistory(self.bus)
        self.escrow = Escrow()
        self.costs = CostRecorder(escrow=self.escrow, events=self.events,
                                  persist_fn=self.store.persist_cost)
        # Fabric peer server (ISSUE 12, --fabric-listen): set by
        # _build_backend when this node serves its backend over the wire
        self._fabric_peer = None
        # Elastic fleet controller (ISSUE 14, --fleet-max): set by
        # _build_backend over the ClusterPlane; ticked below
        self._fleet = None
        self._fleet_stop = threading.Event()
        self._fleet_thread: Optional[threading.Thread] = None
        self.backend = backend or self._build_backend(config)
        # serving telemetry (prefix-cache counters, phase timings) rides
        # the bus into EventHistory's ring + the dashboard SSE tail
        self.backend.attach_bus(self.bus)
        # finished trace spans (infra/telemetry.py — the process-wide
        # tracer) re-broadcast on THIS runtime's bus: EventHistory rings
        # them for /api/trace mount replay, SSE tails them live. The sink
        # detaches in close(); spans carry trace_id, so a second Runtime's
        # ring filters per task regardless.
        self._trace_sink = (
            lambda event: self.bus.broadcast(TOPIC_TRACE, event))
        TRACER.add_sink(self._trace_sink)
        # fleet observability (ISSUE 15): the pull-able span ring — any
        # runtime (front door, peer host, monolith) can answer
        # /api/timeline and the MSG_OBS spans op from it
        from quoracle_tpu.infra import fleetobs
        fleetobs.ensure_ring()
        # Consensus quality (ISSUE 5): audit records + model-health drift
        # alerts (consensus/quality.py QUALITY, process-wide like TRACER)
        # re-broadcast on THIS runtime's bus — EventHistory rings them for
        # /api/consensus + /api/history "consensus", the durable writer
        # persists audit records alongside the task's decisions, and the
        # SSE stream tails drift alerts live. Detached in close().
        self._quality_sink = (
            lambda event: self.bus.broadcast(TOPIC_CONSENSUS, event))
        QUALITY.add_sink(self._quality_sink)
        # Resource observability (ISSUE 3): crash hooks + span sink into
        # the process-wide flight recorder, a scrape-time collector that
        # refreshes the HBM/prefix-cache/compile-storm gauges from THIS
        # runtime's live state, and the stall watchdog over the backend's
        # decode loops. The collector detaches in close() (the recorder's
        # hooks are process-scoped by design and stay).
        FLIGHT.install()
        # Chaos plane (ISSUE 11): arm the configured fault plan before
        # any traffic — a game-day canary injects from its first row.
        if config.chaos_plan:
            from quoracle_tpu.chaos.faults import CHAOS, FaultPlan
            CHAOS.arm(FaultPlan.from_json(config.chaos_plan))
        # Serving flywheel (ISSUE 19): install the replay capture store
        # before traffic so the first speculative round is captured.
        if config.capture_dir:
            from quoracle_tpu.training.capture import CAPTURE
            CAPTURE.install(config.capture_dir,
                            budget_mb=config.capture_mb)
        from quoracle_tpu.infra.resources import ResourceCollector
        self._resource_collector = ResourceCollector(self)
        METRICS.register_collector(self._resource_collector)
        self.watchdog = StallWatchdog(self.bus)
        for name, fn in self.backend.watchdog_sources():
            self.watchdog.add_source(name, fn)
        self.watchdog.start()
        # Liveness & hotspot plane (ISSUE 18): heartbeat stall detector
        # + sampled wall-clock profiler over the same backend sources.
        from quoracle_tpu.infra import introspect
        introspect.start(self.backend.watchdog_sources())
        if self._fleet is not None:
            self._fleet_thread = threading.Thread(
                target=self._fleet_loop, name="fleet-ticker",
                daemon=True)
            self._fleet_thread.start()
        # Fleet simulator (ISSUE 16): boot-armed shadow replay — a
        # daemon thread replays the configured (or seeded canonical)
        # trace at compressed time beside live traffic; model-only, so
        # it never contends for device work.
        self._sim_driver = None
        self._sim_thread: Optional[threading.Thread] = None
        if config.sim_trace or config.sim_seed is not None:
            self._sim_thread = threading.Thread(
                target=self._sim_loop, name="sim-replay", daemon=True)
            self._sim_thread.start()
        self.token_manager = TokenManager(
            self.backend.count_tokens,
            context_limit_fn=self.backend.context_window)
        self.secrets = PersistentSecretStore(self.db)
        self.registry = AgentRegistry()
        from quoracle_tpu.governance.skills import SkillsLoader
        skills_dir = (config.skills_dir
                      or self.store.get_setting("skills_dir"))
        self.skills = SkillsLoader(global_dir=skills_dir)
        from quoracle_tpu.infra.http import urllib_http
        from quoracle_tpu.infra.mcp import MCPManager
        if config.image_backend == "diffusion":
            from quoracle_tpu.models.diffusion import DiffusionImageBackend
            images = DiffusionImageBackend(seed=config.seed)
        else:
            from quoracle_tpu.models.images import ProceduralImageBackend
            images = ProceduralImageBackend()
        from quoracle_tpu.persistence.store import CredentialStore
        self.credentials = CredentialStore(self.db)
        self.mcp = MCPManager(
            self.store.get_setting("mcp_servers") or {},
            credential_resolver=lambda cid: self.credentials.get(
                cid, agent_id="mcp", action="mcp_connect"))
        self.deps = AgentDeps(
            backend=self.backend, registry=self.registry, supervisor=None,
            events=self.events, escrow=self.escrow, costs=self.costs,
            token_manager=self.token_manager, secrets=self.secrets,
            persistence=self.store, skills=self.skills,
            http=urllib_http,
            ssrf_check=bool(self.store.get_setting("ssrf_check", True)),
            mcp=self.mcp, images=images, credentials=self.credentials)
        self.supervisor = AgentSupervisor(self.deps)
        self.tasks = TaskManager(self.deps, self.store)
        self.store.attach_bus(self.bus)

    def _build_backend(self, config: RuntimeConfig) -> ModelBackend:
        # instance method: the draft_map fallback reads the DB settings
        # (self.store is constructed before the backend)
        if config.backend != "tpu":
            if (config.checkpoints or config.tp or config.draft_map
                    or config.coordinator_address or config.num_processes
                    or config.process_id is not None
                    or config.replicas > 1 or config.disaggregate
                    or config.fabric_peers or config.fabric_listen
                    or config.prefixd or config.quantize_weights
                    or config.quantize_kv or config.fleet_max):
                # Silent fallback to mock would make the user believe their
                # checkpoint (or cluster, or fabric peer, or quantized
                # member) is serving while scripted responses come back.
                raise ValueError(
                    "--checkpoint/--tp/--draft/--coordinator/"
                    "--num-processes/--process-id/--replicas/"
                    "--disaggregate/--fabric-listen/--fabric-peers/"
                    "--prefixd/--quantize-weights/--quantize-kv/"
                    "--fleet-max require --backend tpu "
                    f"(backend is {config.backend!r})")
            return MockBackend()
        if config.fabric_peers:
            # The standalone router front door (ISSUE 12): no local
            # engines, no device runtime — placement, aggregate
            # admission, and the wire handoff flow over remote peers.
            if (config.replicas > 1 or config.disaggregate
                    or config.fabric_listen or config.fleet_max):
                raise ValueError(
                    "--fabric-peers is the front-door role: it excludes "
                    "--replicas/--disaggregate/--fabric-listen/"
                    "--fleet-max (peers carry the engines; the door "
                    "grows/shrinks its peer set via add_peer/"
                    "remove_peer + the re-join sweep)")
            from quoracle_tpu.serving.fabric.frontdoor import FabricPlane
            return FabricPlane.connect(list(config.fabric_peers))
        from quoracle_tpu.utils.compile_cache import (
            enable_compilation_cache,
        )
        enable_compilation_cache()
        # Join the JAX distributed system BEFORE any jax.devices() call:
        # explicit args when given, pod auto-detection otherwise (the
        # no-arg form degrades cleanly off-cluster but re-raises when the
        # environment says a cluster exists — parallel/distributed.py).
        from quoracle_tpu.parallel.distributed import init_process
        if (config.coordinator_address or config.num_processes
                or config.process_id is not None):
            info = init_process(config.coordinator_address,
                                config.num_processes, config.process_id)
        else:
            info = init_process()
        if info.num_processes > 1:
            logger.info("joined distributed system: process %d/%d, "
                        "%d global devices", info.process_id,
                        info.num_processes, info.global_devices)
        pool = list(config.model_pool or ())
        if config.checkpoints:
            from quoracle_tpu.models.loader import register_hf_checkpoint
            registered = [register_hf_checkpoint(path).name
                          for path in config.checkpoints]
            if not pool:
                pool = [f"xla:{name}" for name in registered]
        if not pool:
            from quoracle_tpu.models.config import BENCH_POOL
            pool = list(BENCH_POOL)
        import jax
        # Serving is HOST-LOCAL by design: the agent runtime on each host
        # drives its own engines over its own chips (the analog of the
        # reference's one-node BEAM; scale out = one Runtime per host).
        # Cross-host meshes would require every process to issue identical
        # collectives in lockstep, which independent agent loops cannot
        # guarantee — a cross-host psum would simply hang. The multihost
        # mesh layer (parallel/distributed.multihost_mesh) serves SPMD
        # jobs (training, dryruns) where one program drives all hosts.
        submeshes = None
        if len(jax.local_devices()) > 1:
            from quoracle_tpu.parallel.mesh import pool_submeshes
            submeshes = pool_submeshes(len(pool), tp=config.tp,
                                       devices=jax.local_devices())
        draft_map = (config.draft_map
                     or self.store.get_setting("draft_map"))
        if draft_map and not isinstance(draft_map, dict):
            logger.warning("ignoring non-dict draft_map setting %r",
                           draft_map)
            draft_map = None
        qos = config.qos
        if isinstance(qos, dict):
            from quoracle_tpu.serving.qos import QoSConfig
            qos = QoSConfig(**qos)
        if config.replicas > 1 or config.disaggregate:
            # Disaggregated / multi-replica plane (ISSUE 10): partition
            # the local devices per replica, then per pool member inside
            # each replica — replicas never share a collective, so the
            # host-local serving rule above holds per replica unchanged.
            from quoracle_tpu.parallel.mesh import (
                pool_submeshes, replica_device_groups,
            )
            from quoracle_tpu.serving.cluster import ClusterPlane
            if config.fabric_listen:
                raise ValueError(
                    "--fabric-listen serves ONE replica backend over "
                    "the wire; run one peer process per replica "
                    "instead of combining it with --replicas/"
                    "--disaggregate")
            n_rep = max(config.replicas,
                        2 if config.disaggregate else 1)
            submeshes_by_replica = None
            if len(jax.local_devices()) > 1:
                submeshes_by_replica = [
                    pool_submeshes(len(pool), tp=config.tp, devices=grp)
                    for grp in replica_device_groups(
                        n_rep, jax.local_devices())]
            built = ClusterPlane.build(
                pool, replicas=n_rep,
                disaggregate=config.disaggregate, seed=config.seed,
                submeshes_by_replica=submeshes_by_replica,
                qos=qos, draft_map=draft_map or None,
                draft_k=config.draft_k,
                continuous=config.continuous or config.disaggregate,
                host_kv_mb=config.host_kv_mb,
                disk_kv_dir=config.disk_kv_dir,
                disk_kv_gb=config.disk_kv_gb,
                embed_model=config.embed_model,
                quantize_weights=config.quantize_weights,
                quantize_kv=config.quantize_kv)
            if config.fleet_max:
                # Elastic fleet (ISSUE 14): the controller scales the
                # serving tier within [fleet_min, fleet_max] on a
                # deterministic policy tick, re-tiers roles, and drains
                # by live session migration; this thread is the only
                # production ticker.
                from quoracle_tpu.serving.fleet import (
                    FleetConfig, FleetController,
                )
                self._fleet = FleetController(
                    built, FleetConfig(
                        min_replicas=config.fleet_min,
                        max_replicas=config.fleet_max,
                        seed=config.seed))
        else:
            if config.fleet_max:
                raise ValueError(
                    "--fleet-max elasticizes a CLUSTER: it requires "
                    "--replicas > 1 or --disaggregate")
            built = TPUBackend(
                pool, seed=config.seed, draft_k=config.draft_k,
                embed_model=config.embed_model,
                submeshes=submeshes,
                draft_map=draft_map or None,
                continuous=config.continuous,
                qos=qos, host_kv_mb=config.host_kv_mb,
                disk_kv_dir=config.disk_kv_dir,
                disk_kv_gb=config.disk_kv_gb,
                quantize_weights=config.quantize_weights,
                quantize_kv=config.quantize_kv)
        if config.prefixd:
            self._attach_prefixd(built, config.prefixd)
        if config.fabric_listen:
            self._fabric_peer = self._listen_fabric(built, config)
        return built

    @staticmethod
    def _attach_prefixd(backend, addr: str) -> None:
        """Wire the fleet prefix service (ISSUE 12) into every pool
        engine's tier — one shared TCP transport, one read-through
        client per engine signature."""
        from quoracle_tpu.serving.fabric.prefixd import PrefixdClient
        from quoracle_tpu.serving.fabric.transport import (
            TcpTransport, parse_addr,
        )
        _, host, port = parse_addr(addr)
        transport = TcpTransport(host, port, peer_name="prefixd",
                                 lock_name="fabric.prefixd")
        reps = getattr(backend, "replicas", None)
        backends = ([rep.backend for rep in reps]
                    if reps is not None else [backend])
        for b in backends:
            for spec in b.pool:
                eng = b.engines[spec]
                tier = getattr(eng.sessions, "tier", None)
                if tier is None:
                    tier = eng.attach_tier(host_mb=256)
                tier.attach_prefixd(
                    PrefixdClient(transport, eng.kv_signature()))

    @staticmethod
    def _listen_fabric(backend, config: RuntimeConfig):
        """Serve this node's backend as a fabric peer (ISSUE 12,
        --fabric-listen "[role@]host:port"): the front door process
        places prefill/decode/whole-request work here over the wire."""
        from quoracle_tpu.serving.fabric.peer import FabricPeer
        from quoracle_tpu.serving.fabric.transport import parse_addr
        role, host, port = parse_addr(config.fabric_listen)
        role = role or "unified"
        if role == "prefill":
            for spec in backend.pool:
                backend.engines[spec].role = "prefill"
        elif role == "decode":
            for spec in backend.pool:
                backend.engines[spec].role = "decode"
        # handoff needs a KV tier on every pool engine (the transport
        # medium); a bare backend gets the default host tier
        for spec in backend.pool:
            eng = backend.engines[spec]
            if getattr(eng.sessions, "tier", None) is None:
                eng.attach_tier(host_mb=256)
        peer = FabricPeer(backend, replica_id=f"{role}@{host}:{port}",
                          role=role)
        peer.listen(host, port)
        logger.info("fabric peer %s serving at %s", peer.replica_id,
                    peer._server.addr)
        return peer

    def _fleet_loop(self) -> None:
        """The fleet ticker: wall-clock paces the ticks, never the
        decisions (the policy consumes only the gathered signals — the
        determinism contract lives in serving/fleet.py)."""
        while not self._fleet_stop.wait(self.config.fleet_tick_s):
            try:
                self._fleet.tick()
            except Exception:             # noqa: BLE001 — keep ticking
                logger.exception("fleet tick failed")

    def _sim_loop(self) -> None:
        """Boot-armed trace replay (ISSUE 16): loads --sim-trace (or
        generates the canonical diurnal-mix trace from --sim-seed),
        sizes the capacity model from the live router when the backend
        is a cluster, and replays at compressed time with forecast
        priors offered to the fleet controller's shadow seam."""
        try:
            from quoracle_tpu.sim.replay import (
                SIM, CapacityModel, ReplayDriver,
            )
            from quoracle_tpu.sim.workload import (
                Trace, canonical_spec, generate,
            )
            if self.config.sim_trace:
                trace = Trace.from_file(self.config.sim_trace)
            else:
                trace = generate(canonical_spec(
                    "diurnal_mix", seed=self.config.sim_seed or 0))
            SIM.note_trace(trace.stats())
            capacity = None
            router = getattr(self.backend, "router", None)
            if router is not None:
                hint = router.capacity_hint()
                slots = max(2, hint["decode_slots"])
                capacity = CapacityModel(
                    decode_slots=slots,
                    reserved_interactive=max(1, slots // 4))
            self._sim_driver = ReplayDriver(
                trace, capacity=capacity, fleet=self._fleet,
                bus=self.bus)
            self._sim_driver.run()
        except Exception:                 # noqa: BLE001 — shadow only
            logger.exception("sim replay failed")

    async def boot(self) -> dict:
        """Boot-time revival of persisted running tasks (reference
        application.ex:71-74 → AgentRevival)."""
        return await self.tasks.boot_revival()

    async def shutdown(self) -> None:
        """Graceful stop of every live agent, then release resources."""
        await self.supervisor.stop_all()
        await self.mcp.close()
        self.close()

    def close(self) -> None:
        if self._sim_driver is not None:
            self._sim_driver.stop()
        if self._sim_thread is not None:
            self._sim_thread.join(timeout=5)
            self._sim_thread = None
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=5)
            self._fleet_thread = None
        if self._fabric_peer is not None and \
                self._fabric_peer._server is not None:
            self._fabric_peer._server.close()
        self.watchdog.close()
        if self.config.capture_dir:
            from quoracle_tpu.training.capture import CAPTURE
            CAPTURE.uninstall()
        from quoracle_tpu.infra import introspect
        introspect.shutdown()
        METRICS.remove_collector(self._resource_collector)
        TRACER.remove_sink(self._trace_sink)
        QUALITY.remove_sink(self._quality_sink)
        self.store.detach_bus()
        self.history.close()
        self.db.close()

    # convenience passthroughs -------------------------------------------

    def live_agents(self) -> list[str]:
        return self.supervisor.live_agents()

    def default_pool(self) -> list[str]:
        """The pool used when a task names neither pool nor profile: the
        backend's POOL members — engines can also hold speculative draft
        models, which never serve directly. ClusterPlane (ISSUE 10)
        exposes the same ``pool`` surface, so a disaggregated runtime
        needs no special case."""
        pool = getattr(self.backend, "pool", None)
        if pool:
            return list(pool)
        return list(MockBackend.DEFAULT_POOL)

    def list_groves(self) -> list:
        from quoracle_tpu.governance.grove import list_groves
        groves_dir = (self.config.groves_dir
                      or self.store.get_setting("groves_dir"))
        return list_groves(groves_dir) if groves_dir else []

    def status(self) -> dict[str, Any]:
        return {
            "backend": type(self.backend).__name__,
            "live_agents": len(self.registry),
            "tasks": {t["id"]: t["status"]
                      for t in self.store.list_tasks()},
        }
