"""Composition root: builds and owns the whole service graph.

The explicit-factory equivalent of the reference's supervision tree
(reference lib/quoracle/application.ex:38-61: Vault → Repo → PubSub →
Registry → EmbeddingCache → Task.Supervisor → Agent.DynSup → EventHistory →
Endpoint, then boot revival at :74). There are no singletons: a Runtime owns
one instance of each service and hands them to agents via AgentDeps — build
two Runtimes and they share nothing (the reference's cardinal DI rule, root
AGENTS.md:5-33).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional

from quoracle_tpu.agent.registry import AgentRegistry
from quoracle_tpu.agent.state import AgentDeps
from quoracle_tpu.agent.supervisor import AgentSupervisor
from quoracle_tpu.context.token_manager import TokenManager
from quoracle_tpu.infra.budget import Escrow
from quoracle_tpu.infra.bus import TOPIC_TRACE, AgentEvents, EventBus
from quoracle_tpu.infra.costs import CostRecorder
from quoracle_tpu.infra.event_history import EventHistory
from quoracle_tpu.infra.telemetry import TRACER
from quoracle_tpu.models.runtime import MockBackend, ModelBackend, TPUBackend
from quoracle_tpu.persistence import Database, Persistence, TaskManager
from quoracle_tpu.persistence.store import PersistentSecretStore


logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RuntimeConfig:
    db_path: str = ":memory:"
    encryption_key: Optional[str] = None      # default: env QUORACLE_ENCRYPTION_KEY
    backend: str = "mock"                     # "mock" | "tpu"
    model_pool: Optional[list[str]] = None    # default pool for tpu backend
    embed_model: Optional[str] = None
    seed: int = 0
    skills_dir: Optional[str] = None          # global skills directory
    groves_dir: Optional[str] = None          # directory of grove dirs
    # HF checkpoint directories (real weights + the checkpoint's own
    # tokenizer). Each registers into the catalog as xla:<dirname> and — when
    # model_pool is unset — the registered names BECOME the pool, so
    # `--backend tpu --checkpoint dir1 --checkpoint dir2` serves real
    # checkpoints with zero extra wiring (reference model_query.ex:222-259
    # serves whatever models credentials point at).
    checkpoints: Optional[list[str]] = None
    # Multi-chip serving: tensor-parallel size per pool member. With more
    # than one visible device the pool is partitioned into per-member
    # sub-meshes (parallel.mesh.pool_submeshes) and members overlap from
    # host threads; on one chip this is ignored.
    tp: Optional[int] = None
    # generate_images backend: "procedural" (deterministic placeholder
    # PNGs, zero compute) or "diffusion" (on-device UNet + DDIM sampler,
    # models/diffusion.py — the TPU-native analog of the reference's hosted
    # image models, image_query.ex:1-12).
    image_backend: str = "procedural"
    # Speculative serving (models/speculative.py): {target_spec:
    # draft_spec} — eligible member queries draft-K/verify-one-chunk;
    # drafts load like members but never serve directly. Also settable
    # via the DB setting "draft_map" (dashboard /api/settings).
    draft_map: Optional[dict] = None
    # Multi-host: join the JAX distributed system before building the
    # backend (parallel/distributed.init_process). On TPU pods the three
    # values are usually auto-detected — set coordinator_address (and
    # num_processes/process_id on CPU/GPU clusters) to join explicitly.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


class Runtime:
    """One running quoracle_tpu node. Construct → (await) boot() → use
    .tasks / .deps; close() tears everything down."""

    def __init__(self, config: Optional[RuntimeConfig] = None,
                 backend: Optional[ModelBackend] = None):
        config = config if config is not None else RuntimeConfig()
        self.config = config
        self.db = Database(config.db_path,
                           encryption_key=config.encryption_key)
        self.store = Persistence(self.db)
        self.bus = EventBus()
        self.events = AgentEvents(self.bus)
        self.history = EventHistory(self.bus)
        self.escrow = Escrow()
        self.costs = CostRecorder(escrow=self.escrow, events=self.events,
                                  persist_fn=self.store.persist_cost)
        self.backend = backend or self._build_backend(config)
        # serving telemetry (prefix-cache counters, phase timings) rides
        # the bus into EventHistory's ring + the dashboard SSE tail
        self.backend.attach_bus(self.bus)
        # finished trace spans (infra/telemetry.py — the process-wide
        # tracer) re-broadcast on THIS runtime's bus: EventHistory rings
        # them for /api/trace mount replay, SSE tails them live. The sink
        # detaches in close(); spans carry trace_id, so a second Runtime's
        # ring filters per task regardless.
        self._trace_sink = (
            lambda event: self.bus.broadcast(TOPIC_TRACE, event))
        TRACER.add_sink(self._trace_sink)
        self.token_manager = TokenManager(
            self.backend.count_tokens,
            context_limit_fn=self.backend.context_window)
        self.secrets = PersistentSecretStore(self.db)
        self.registry = AgentRegistry()
        from quoracle_tpu.governance.skills import SkillsLoader
        skills_dir = (config.skills_dir
                      or self.store.get_setting("skills_dir"))
        self.skills = SkillsLoader(global_dir=skills_dir)
        from quoracle_tpu.infra.http import urllib_http
        from quoracle_tpu.infra.mcp import MCPManager
        if config.image_backend == "diffusion":
            from quoracle_tpu.models.diffusion import DiffusionImageBackend
            images = DiffusionImageBackend(seed=config.seed)
        else:
            from quoracle_tpu.models.images import ProceduralImageBackend
            images = ProceduralImageBackend()
        from quoracle_tpu.persistence.store import CredentialStore
        self.credentials = CredentialStore(self.db)
        self.mcp = MCPManager(
            self.store.get_setting("mcp_servers") or {},
            credential_resolver=lambda cid: self.credentials.get(
                cid, agent_id="mcp", action="mcp_connect"))
        self.deps = AgentDeps(
            backend=self.backend, registry=self.registry, supervisor=None,
            events=self.events, escrow=self.escrow, costs=self.costs,
            token_manager=self.token_manager, secrets=self.secrets,
            persistence=self.store, skills=self.skills,
            http=urllib_http,
            ssrf_check=bool(self.store.get_setting("ssrf_check", True)),
            mcp=self.mcp, images=images, credentials=self.credentials)
        self.supervisor = AgentSupervisor(self.deps)
        self.tasks = TaskManager(self.deps, self.store)
        self.store.attach_bus(self.bus)

    def _build_backend(self, config: RuntimeConfig) -> ModelBackend:
        # instance method: the draft_map fallback reads the DB settings
        # (self.store is constructed before the backend)
        if config.backend != "tpu":
            if (config.checkpoints or config.tp or config.draft_map
                    or config.coordinator_address or config.num_processes
                    or config.process_id is not None):
                # Silent fallback to mock would make the user believe their
                # checkpoint (or cluster, or speculative draft) is serving
                # while scripted responses come back.
                raise ValueError(
                    "--checkpoint/--tp/--draft/--coordinator/"
                    "--num-processes/--process-id require --backend tpu "
                    f"(backend is {config.backend!r})")
            return MockBackend()
        from quoracle_tpu.utils.compile_cache import (
            enable_compilation_cache,
        )
        enable_compilation_cache()
        # Join the JAX distributed system BEFORE any jax.devices() call:
        # explicit args when given, pod auto-detection otherwise (the
        # no-arg form degrades cleanly off-cluster but re-raises when the
        # environment says a cluster exists — parallel/distributed.py).
        from quoracle_tpu.parallel.distributed import init_process
        if (config.coordinator_address or config.num_processes
                or config.process_id is not None):
            info = init_process(config.coordinator_address,
                                config.num_processes, config.process_id)
        else:
            info = init_process()
        if info.num_processes > 1:
            logger.info("joined distributed system: process %d/%d, "
                        "%d global devices", info.process_id,
                        info.num_processes, info.global_devices)
        pool = list(config.model_pool or ())
        if config.checkpoints:
            from quoracle_tpu.models.loader import register_hf_checkpoint
            registered = [register_hf_checkpoint(path).name
                          for path in config.checkpoints]
            if not pool:
                pool = [f"xla:{name}" for name in registered]
        if not pool:
            from quoracle_tpu.models.config import BENCH_POOL
            pool = list(BENCH_POOL)
        import jax
        # Serving is HOST-LOCAL by design: the agent runtime on each host
        # drives its own engines over its own chips (the analog of the
        # reference's one-node BEAM; scale out = one Runtime per host).
        # Cross-host meshes would require every process to issue identical
        # collectives in lockstep, which independent agent loops cannot
        # guarantee — a cross-host psum would simply hang. The multihost
        # mesh layer (parallel/distributed.multihost_mesh) serves SPMD
        # jobs (training, dryruns) where one program drives all hosts.
        submeshes = None
        if len(jax.local_devices()) > 1:
            from quoracle_tpu.parallel.mesh import pool_submeshes
            submeshes = pool_submeshes(len(pool), tp=config.tp,
                                       devices=jax.local_devices())
        draft_map = (config.draft_map
                     or self.store.get_setting("draft_map"))
        if draft_map and not isinstance(draft_map, dict):
            logger.warning("ignoring non-dict draft_map setting %r",
                           draft_map)
            draft_map = None
        return TPUBackend(pool, seed=config.seed,
                          embed_model=config.embed_model,
                          submeshes=submeshes,
                          draft_map=draft_map or None)

    async def boot(self) -> dict:
        """Boot-time revival of persisted running tasks (reference
        application.ex:71-74 → AgentRevival)."""
        return await self.tasks.boot_revival()

    async def shutdown(self) -> None:
        """Graceful stop of every live agent, then release resources."""
        await self.supervisor.stop_all()
        await self.mcp.close()
        self.close()

    def close(self) -> None:
        TRACER.remove_sink(self._trace_sink)
        self.store.detach_bus()
        self.history.close()
        self.db.close()

    # convenience passthroughs -------------------------------------------

    def live_agents(self) -> list[str]:
        return self.supervisor.live_agents()

    def default_pool(self) -> list[str]:
        """The pool used when a task names neither pool nor profile: the
        backend's POOL members — engines can also hold speculative draft
        models, which never serve directly."""
        if isinstance(self.backend, TPUBackend):
            return list(self.backend.pool)
        return list(MockBackend.DEFAULT_POOL)

    def list_groves(self) -> list:
        from quoracle_tpu.governance.grove import list_groves
        groves_dir = (self.config.groves_dir
                      or self.store.get_setting("groves_dir"))
        return list_groves(groves_dir) if groves_dir else []

    def status(self) -> dict[str, Any]:
        return {
            "backend": type(self.backend).__name__,
            "live_agents": len(self.registry),
            "tasks": {t["id"]: t["status"]
                      for t in self.store.list_tasks()},
        }
