"""Pallas flash attention (blockwise online-softmax) for TPU prefill.

The XLA `attend` path materializes [B, H, T, S] scores in HBM; this kernel
streams KV blocks through VMEM with running (max, denom, acc) statistics so
the memory high-water is O(TQ x TK) per core — the standard flash recipe
mapped to the TPU constraints of /opt/skills/guides/pallas_guide.md (grid
over (batch, head, q-block), MXU contractions with
preferred_element_type=f32, VPU mask/softmax chain, lane dim 128).

Semantics match ops/attention.attend exactly (same masking: validity by
kv_len, causality by absolute position, optional sliding window) and the
tests assert numerical agreement. Off-TPU the kernel runs in interpreter
mode — correct but slow — so production callers gate on platform
(attend_auto below).

No reference counterpart: the reference never executes attention
(SURVEY.md §2.8 — all inference was remote HTTPS).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from quoracle_tpu.ops.attention import attend

DEFAULT_TQ = 128
DEFAULT_TK = 128
NEG_INF = -1e30


def _flash_kernel(kv_meta_ref, qpos_ref, q_ref, k_ref, v_ref, o_ref, *,
                  tk: int, scale: float, sliding_window: Optional[int]):
    """One (batch, head, q-block) program: stream KV in tk-sized blocks.

    Block shapes (leading singleton dims dropped by indexing):
      q_ref [1, 1, TQ, hd]   k_ref/v_ref [1, 1, S, hd]
      qpos_ref [1, TQ] (VMEM) kv_meta_ref [B, 2] (SMEM: kv_len, pos offset)
      o_ref [1, 1, TQ, hd]
    """
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [TQ, hd]
    tq, hd = q.shape
    s = k_ref.shape[2]
    kv_len = kv_meta_ref[pl.program_id(0), 0]             # this batch row
    kv_off = kv_meta_ref[pl.program_id(0), 1]             # abs pos of idx 0
    q_pos = qpos_ref[0].astype(jnp.int32)                 # [TQ]

    def body(i, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(i * tk, tk), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(i * tk, tk), :].astype(jnp.float32)
        scores = jax.lax.dot_general(                     # [TQ, tk] on MXU
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kv_idx = i * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        kv_pos = kv_idx + kv_off
        qp = q_pos[:, None]
        mask = (kv_idx < kv_len) & (kv_pos <= qp)
        if sliding_window is not None:
            mask &= qp - kv_pos < sliding_window
        scores = jnp.where(mask, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))
        # NEG_INF is finite, so a fully-masked block would give
        # exp(NEG_INF - NEG_INF) = 1 per position; re-mask p so masked
        # positions contribute 0 and fully-masked rows keep l == 0.
        p = jnp.where(mask, jnp.exp(scores - m_new), 0.0)  # [TQ, tk]
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((tq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((tq, 1), jnp.float32)
    acc0 = jnp.zeros((tq, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, s // tk, body, (m0, l0, acc0))
    # fully-masked rows (query padding) produce l == 0 → emit zeros
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int,
            value: float = 0.0) -> jax.Array:
    size = x.shape[axis]
    target = ((size + multiple - 1) // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad, constant_values=value)


@functools.partial(jax.jit, static_argnames=("sliding_window", "tq", "tk",
                                             "interpret"))
def flash_attend(
    q: jax.Array,            # [B, T, n_heads, hd]
    k: jax.Array,            # [B, S, n_kv, hd]
    v: jax.Array,            # [B, S, n_kv, hd]
    q_positions: jax.Array,  # [B, T] int32
    kv_len: jax.Array,       # [B] int32
    sliding_window: Optional[int] = None,
    kv_pos_offset: Optional[jax.Array] = None,   # [B] int32
    tq: int = DEFAULT_TQ,
    tk: int = DEFAULT_TK,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for attend() with flash memory behavior. GQA is handled by
    head-index mapping (kv never materializes repeated)."""
    b, t, n_heads, hd = q.shape
    s, n_kv = k.shape[1], k.shape[2]
    q_per_kv = n_heads // n_kv
    scale = hd ** -0.5

    # Lane/tile alignment: hd → 128-multiple, T → tq-multiple, S → tk-mult.
    hd_p = max(128, ((hd + 127) // 128) * 128)
    q2 = _pad_to(_pad_to(q, 3, hd_p), 1, tq)
    k2 = _pad_to(_pad_to(k, 3, hd_p), 1, tk)
    v2 = _pad_to(_pad_to(v, 3, hd_p), 1, tk)
    # padded queries get position -1: masked against every kv index
    qpos2 = _pad_to(q_positions.astype(jnp.int32), 1, tq, value=-1)
    t_p, s_p = q2.shape[1], k2.shape[1]

    q2 = q2.transpose(0, 2, 1, 3)        # [B, H, T, hd]
    k2 = k2.transpose(0, 2, 1, 3)        # [B, KVH, S, hd]
    v2 = v2.transpose(0, 2, 1, 3)

    grid = (b, n_heads, t_p // tq)
    kernel = functools.partial(_flash_kernel, tk=tk, scale=scale,
                               sliding_window=sliding_window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,            # kv_len rides SMEM
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq), lambda bb, h, qi, kvl: (bb, qi)),
                pl.BlockSpec((1, 1, tq, hd_p),
                             lambda bb, h, qi, kvl: (bb, h, qi, 0)),
                pl.BlockSpec((1, 1, s_p, hd_p),
                             lambda bb, h, qi, kvl, _q=q_per_kv:
                             (bb, h // _q, 0, 0)),
                pl.BlockSpec((1, 1, s_p, hd_p),
                             lambda bb, h, qi, kvl, _q=q_per_kv:
                             (bb, h // _q, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, tq, hd_p),
                                   lambda bb, h, qi, kvl: (bb, h, qi, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_heads, t_p, hd_p), q.dtype),
        interpret=interpret,
    )(jnp.stack([kv_len.astype(jnp.int32),
                 (jnp.zeros_like(kv_len, jnp.int32)
                  if kv_pos_offset is None
                  else kv_pos_offset.astype(jnp.int32))], axis=1),
      qpos2, q2, k2, v2)

    return out.transpose(0, 2, 1, 3)[:, :t, :, :hd]


def attend_auto(q, k, v, q_positions, kv_len,
                sliding_window: Optional[int] = None,
                kv_pos_offset: Optional[jax.Array] = None,
                min_flash_len: int = 256) -> jax.Array:
    """Pick the attention path: flash on TPU for long prefill chunks, dense
    XLA otherwise (decode steps and CPU tests). Same signature/semantics as
    attend()."""
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu and q.shape[1] >= min_flash_len:
        return flash_attend(q, k, v, q_positions, kv_len,
                            sliding_window=sliding_window,
                            kv_pos_offset=kv_pos_offset)
    return attend(q, k, v, q_positions, kv_len,
                  sliding_window=sliding_window,
                  kv_pos_offset=kv_pos_offset)
