"""TPU compute ops: attention implementations and (later) pallas kernels.

No reference counterpart — the reference (shelvick/quoracle) executes no model
math locally (SURVEY.md §2.8); this package exists because the model pool is
in-tree here.
"""
