"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context capability with no reference counterpart (the reference never
executes attention; SURVEY.md §5 calls this out as a NEW capability): when a
sequence outgrows one chip's HBM, shard it over the ``sp`` mesh axis. Each
device keeps its Q shard resident and the K/V shards rotate around the ring
with ``lax.ppermute`` (ICI neighbor exchange), one hop per step; partial
attention accumulates with online-softmax statistics so the result is
bit-comparable to single-device attention. This is the blockwise/ring
formulation (PAPERS.md: Ring Attention, blockwise transformers) expressed
at the XLA collective level per the scaling-book recipe — shard_map +
ppermute, letting XLA schedule compute/communication overlap.

The per-step local block math reuses the same masking semantics as
ops/attention.attend; tests assert exact agreement with the dense path on a
virtual 8-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from quoracle_tpu.ops.attention import repeat_kv

NEG_INF = -1e30


def _block_scores(q, k, scale):
    # [B, Sq, H, hd] x [B, Sk, H, hd] -> [B, H, Sq, Sk] (MXU contraction)
    return jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32) * scale,
                      k.astype(jnp.float32))


def _ring_shard(q, k, v, kv_len, *, axis_name: str, n_shards: int,
                sliding_window: Optional[int]):
    """Runs inside shard_map. q/k/v: [B, S_loc, H|KVH, hd] local shards;
    kv_len [B] replicated. Returns the local output shard."""
    b, s_loc, n_heads, hd = q.shape
    q_per_kv = n_heads // k.shape[2]
    scale = hd ** -0.5
    my = jax.lax.axis_index(axis_name)
    q_pos = (my * s_loc
             + jnp.arange(s_loc, dtype=jnp.int32))[None, :, None]  # [1,Sq,1]

    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # the shard currently held arrived from (my - i) around the ring
        owner = (my - i) % n_shards
        kv_pos = (owner * s_loc
                  + jnp.arange(s_loc, dtype=jnp.int32))[None, None, :]
        scores = _block_scores(q, repeat_kv(k_cur, q_per_kv), scale)
        mask = (kv_pos < kv_len[:, None, None]) & (kv_pos <= q_pos)
        if sliding_window is not None:
            mask &= q_pos - kv_pos < sliding_window
        scores = jnp.where(mask[:, None, :, :], scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        # NEG_INF is finite: re-mask p so steps whose block is fully masked
        # contribute 0 (not a uniform 1) and kv_len==0 rows keep l == 0.
        p = jnp.where(mask[:, None, :, :], jnp.exp(scores - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhts,bshd->bthd", p,
            repeat_kv(v_cur, q_per_kv).astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        # rotate K/V to the next neighbor (one ICI hop per step); the last
        # iteration's permute returns the shards home, keeping the loop
        # carry shape-uniform — XLA overlaps it with the block math above.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m_new, l_new, acc_new

    m0 = jnp.full((b, n_heads, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_heads, s_loc, 1), jnp.float32)
    acc0 = jnp.zeros((b, n_heads, s_loc, hd), jnp.float32)
    *_kv, m, l, acc = jax.lax.fori_loop(
        0, n_shards, step, (k, v, m0, l0, acc0))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)   # [B, S_loc, H, hd]


def ring_attend(
    mesh: Mesh,
    q: jax.Array,            # [B, S, n_heads, hd], S sharded on axis_name
    k: jax.Array,            # [B, S, n_kv, hd]
    v: jax.Array,
    kv_len: jax.Array,       # [B] int32 (valid prefix of the GLOBAL seq)
    axis_name: str = "sp",
    sliding_window: Optional[int] = None,
    batch_axis: Optional[str] = None,   # mesh axis carrying B (serving: dp)
    head_axis: Optional[str] = None,    # mesh axis carrying heads (tp)
) -> jax.Array:
    """Causal attention over a sequence sharded on ``axis_name``. The
    global sequence length must divide the axis size. ``batch_axis`` /
    ``head_axis`` let the serving path keep its dp/tp layout inside the
    shard_map (heads only shard when both q and kv head counts divide)."""
    n_shards = int(mesh.shape[axis_name])
    if q.shape[1] % n_shards:
        raise ValueError(f"sequence {q.shape[1]} not divisible by "
                         f"{axis_name}={n_shards}")
    if head_axis is not None:
        hs = int(mesh.shape[head_axis])
        if q.shape[2] % hs or k.shape[2] % hs:
            head_axis = None            # MQA/GQA mismatch: replicate heads
    q_spec = P(batch_axis, axis_name, head_axis, None)
    kv_spec = P(batch_axis, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_shard, axis_name=axis_name,
                          n_shards=n_shards, sliding_window=sliding_window),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P(batch_axis)),
        out_specs=q_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_len.astype(jnp.int32))
